"""Multi-slice training: (dcn x dp) mesh with DGC-compressed gradients.

The TPU-era successor to the reference's hierarchical allreduce + deep
gradient compression (nccl_helper.h:185, details/sparse_all_reduce_op_handle.cc):
`strategy.hybrid_dcn = N` builds an (N dcn x rest dp) mesh, the step runs
manually sharded over both axes, and each parameter gradient syncs
densely over the fast inner (ICI) axis and top-k + error-feedback
compressed across the slow outer (DCN) axis.

Runs on 8 virtual CPU devices:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/dcn_dgc.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu.fleet as fleet
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def main():
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_p, startup):
        x = fluid.data("x", [64, 32], "float32")
        y = fluid.data("y", [64, 1], "float32")
        h = layers.fc(x, 128, act="relu")
        h = layers.fc(h, 128, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_dcn = 2              # 2 slices on the DCN axis
        strategy.dgc = True                  # compress across slices only
        strategy.dgc_configs = {"sparsity": 0.9, "rampup_begin_step": 5}
        fleet.init()
        opt = fleet.distributed_optimizer(
            fluid.optimizer.SGDOptimizer(0.05), strategy
        )
        opt.minimize(loss)

    print("mesh:", dict(main_p._mesh.shape), "manual axes:", main_p._manual_axes)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    w = rng.randn(32, 1).astype("f4")
    for step in range(30):
        xv = rng.randn(64, 32).astype("f4")
        yv = xv @ w + 0.01 * rng.randn(64, 1).astype("f4")
        (lv,) = exe.run(main_p, feed={"x": xv, "y": yv}, fetch_list=[loss])
        if step % 5 == 0:
            phase = "dense warm-up" if step < 5 else "DGC top-10%"
            print(f"step {step:2d} [{phase}]: loss {float(np.asarray(lv).reshape(())):.4f}")


if __name__ == "__main__":
    main()
