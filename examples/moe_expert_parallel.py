"""BERT-MoE with expert parallelism over an "ep" mesh axis. On a single
chip the experts run locally; on a pod slice, XLA shards the expert dim
and inserts the dispatch all-to-alls (run with more devices or
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).

    python examples/moe_expert_parallel.py
"""
import dataclasses

import numpy as np

import paddle_tpu.fluid as fluid
import paddle_tpu.fleet as fleet
from paddle_tpu.models.bert import (
    BertConfig, build_bert_pretrain_program, random_pretrain_batch,
)


def main():
    import jax

    cfg = dataclasses.replace(BertConfig.tiny(), moe_num_experts=8)
    n = jax.device_count()
    ep = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
    dp = n // ep
    # batch must divide evenly over the dp axis (works for ANY device count)
    batch, seq, mp = 4 * dp, 64, 8
    m, st, _, loss = build_bert_pretrain_program(cfg, batch, seq, mp)
    with fluid.program_guard(m, st):
        strategy = fleet.DistributedStrategy()
        strategy.mesh_axes = {"dp": dp, "ep": ep}
        strategy.expert_parallel = ep > 1
        fleet.init()
        opt = fleet.distributed_optimizer(
            fluid.optimizer.AdamOptimizer(1e-3), strategy)
        opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(st)
    print(f"devices={n} mesh=dp{dp}xep{ep} experts={cfg.moe_num_experts}")
    for step in range(5):
        feed = random_pretrain_batch(cfg, batch, seq, mp, seed=step)
        (lv,) = exe.run(m, feed=feed, fetch_list=[loss])
        print(f"step {step}: loss {float(np.asarray(lv).reshape(())):.4f}")


if __name__ == "__main__":
    main()
