"""Sequence model on the wmt16 synthetic translation task: GRU encoder
(layers.rnn) + per-position projection. For the decoder-side API
(BasicDecoder / GreedyEmbeddingHelper / dynamic_decode) see
tests/test_rnn_api.py.

    python examples/seq2seq_nmt.py
"""
import itertools

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import dataset
from paddle_tpu.fluid import layers

VOCAB, MAXLEN, BATCH, HID = 40, 12, 32, 64


def pack(pairs):
    src = np.full((len(pairs), MAXLEN), 2, "int64")
    trg = np.full((len(pairs), MAXLEN), 2, "int64")
    for i, (s, t_in, t_next) in enumerate(pairs):
        src[i, : len(s)] = s[:MAXLEN]
        trg[i, : len(t_next)] = t_next[:MAXLEN]
    return src, trg


def main():
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        s_v = fluid.data("src", [BATCH, MAXLEN], "int64")
        t_v = fluid.data("trg", [BATCH, MAXLEN, 1], "int64")
        emb = layers.embedding(s_v, size=[VOCAB, HID])
        enc, final = layers.rnn(layers.GRUCell(HID, name="enc"), emb)
        logits = layers.fc(enc, VOCAB, num_flatten_dims=2)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, t_v))
        fluid.optimizer.AdamOptimizer(5e-3).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    reader = dataset.wmt16.train(VOCAB, VOCAB)
    data = list(itertools.islice(reader(), 512))
    for epoch in range(3):
        np.random.RandomState(epoch).shuffle(data)
        losses = []
        for i in range(0, len(data) - BATCH, BATCH):
            src, trg = pack(data[i : i + BATCH])
            (lv,) = exe.run(main_p, feed={"src": src, "trg": trg[..., None]},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f}")


if __name__ == "__main__":
    main()
