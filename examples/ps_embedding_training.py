"""Parameter-server embedding training over the networked data plane.

Single process (table in-process):

    python examples/ps_embedding_training.py

Multi-process with a real pserver (the reference's transpiler +
listen_and_serv deployment, launch_ps.py):

    python -m paddle_tpu.distributed.launch \
        --nproc_per_node 2 --server_num 1 \
        examples/ps_embedding_training.py

The launcher spawns the pserver process (distributed/ps_server.py),
exports PADDLE_PSERVERS_IP_PORT_LIST, and every trainer's
DistributeTranspiler-rewritten lookup rides a RemoteTable over TCP.
Sync mode barriers the per-step pushes server-side, so the 2-trainer
loss trace matches single-process exactly (tests/test_ps_dist.py).
"""
import os

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.distributed import ps
from paddle_tpu.fluid import layers

ROWS, DIM, NCLS, B, STEPS = 1_000_000, 64, 20, 64, 30


def main():
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        ids = layers.data("ids", [B], dtype="int64",
                          append_batch_size=False)
        y = layers.data("y", [B, 1], dtype="int64",
                        append_batch_size=False)
        # written like any single-chip model: a plain embedding ...
        emb = layers.embedding(
            ids, size=[ROWS, DIM],
            param_attr=fluid.ParamAttr(name="giant_table"))
        logits = layers.fc(emb, NCLS)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))

        # ... then transpiled onto the PS: the 1M x 64 table leaves the
        # device program; gradients push to the (possibly remote)
        # server, which applies its own optimizer per touched row
        t = fluid.DistributeTranspiler()
        tables = t.transpile(trainer_id=rank, program=main_prog,
                             startup_program=startup)
        print(f"[rank {rank}] tables on PS: {tables}")

        fluid.optimizer.AdamOptimizer(learning_rate=1e-3).minimize(loss)

    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(rank)
    for step in range(STEPS):
        ids_np = rng.randint(0, ROWS, (B,)).astype(np.int64)
        feed = {"ids": ids_np, "y": (ids_np % NCLS)[:, None]}
        (lv,) = exe.run(main_prog, feed=feed, fetch_list=[loss])
        if step % 10 == 0 or step == STEPS - 1:
            print(f"[rank {rank}] step {step} "
                  f"loss {float(np.asarray(lv).reshape(())):.4f}")

    table = ps.get_table("giant_table")
    stats = (table.stats() if hasattr(table, "stats")
             else {"push_calls": table.push_calls})
    print(f"[rank {rank}] server traffic: {stats}")


if __name__ == "__main__":
    main()
