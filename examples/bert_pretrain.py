"""BERT pretraining with the full TPU stack: bf16 AMP, flash attention,
scan-fused encoder, optional GSPMD mesh via fleet.

    python examples/bert_pretrain.py            # tiny config, quick
    BERT=base python examples/bert_pretrain.py  # the bench config
"""
import os

import numpy as np

import paddle_tpu.fluid as fluid
import paddle_tpu.fleet as fleet
from paddle_tpu.contrib import mixed_precision as mixed_prec
from paddle_tpu.models.bert import (
    BertConfig, build_bert_pretrain_program, random_pretrain_batch,
)


def main():
    if os.environ.get("BERT") == "base":
        cfg, batch, seq, mp = BertConfig.base(), 48, 512, 76
        cfg.fuse_stack = True
        cfg.remat_ffn = True
    else:
        cfg, batch, seq, mp = BertConfig.tiny(), 8, 64, 8
    m, st, _, loss = build_bert_pretrain_program(cfg, batch, seq, mp)
    with fluid.program_guard(m, st):
        strategy = fleet.DistributedStrategy()
        strategy.mesh_axes = {"dp": -1}   # all local devices
        strategy.amp = True               # bf16
        fleet.init()
        opt = fleet.distributed_optimizer(
            fluid.optimizer.AdamOptimizer(1e-4), strategy)
        opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(st)
    for step in range(10):
        feed = random_pretrain_batch(cfg, batch, seq, mp, seed=step)
        (lv,) = exe.run(m, feed=feed, fetch_list=[loss])
        print(f"step {step}: loss {float(np.asarray(lv).reshape(())):.4f}")


if __name__ == "__main__":
    main()
