"""hapi.text transformer NMT under Model.fit (reference
incubate/hapi/text + the hapi transformer example).

    python examples/hapi_text_nmt.py
"""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.hapi import Input, Model, text

B, S, T, V, H, NH = 16, 24, 20, 200, 64, 4


def main():
    enc = text.TransformerEncoder(n_layer=2, n_head=NH, d_model=H,
                                  d_inner_hid=4 * H, name="enc")
    dec = text.TransformerDecoder(n_layer=2, n_head=NH, d_model=H,
                                  d_inner_hid=4 * H, name="dec")

    def network(src_ids, trg_ids, src_mask):
        semb = layers.add_position_encoding(layers.scale(
            layers.embedding(src_ids, size=[V, H],
                             param_attr=fluid.ParamAttr(name="src_emb")),
            scale=H ** 0.5), alpha=1.0, beta=1.0)
        bias = layers.unsqueeze(layers.unsqueeze(layers.scale(
            layers.cast(src_mask, "float32"), scale=1e4, bias=-1e4),
            [1]), [1])
        temb = layers.add_position_encoding(layers.scale(
            layers.embedding(trg_ids, size=[V, H],
                             param_attr=fluid.ParamAttr(name="trg_emb")),
            scale=H ** 0.5), alpha=1.0, beta=1.0)
        out = dec(temb, enc(semb, bias), bias)
        return layers.fc(out, V, num_flatten_dims=2)

    def loss_fn(logits, label):
        return layers.mean(layers.softmax_with_cross_entropy(logits, label))

    # synthetic reversal task: target = reversed source prefix
    rng = np.random.RandomState(0)
    n = 256
    src = rng.randint(2, V, (n, S)).astype(np.int64)
    trg = src[:, :T][:, ::-1].copy()
    lbl = np.roll(trg, -1, axis=1)[..., None]
    mask = np.ones((n, S), np.int64)

    model = Model(
        network,
        [Input("src", [B, S], "int64"), Input("trg", [B, T], "int64"),
         Input("mask", [B, S], "int64")],
        Input("lbl", [B, T, 1], "int64"))
    model.prepare(fluid.optimizer.AdamOptimizer(learning_rate=3e-3),
                  loss_fn)
    hist = model.fit((src, trg, mask, lbl), batch_size=B, epochs=8,
                     verbose=2)
    print("loss trace:", [round(v, 3) for v in hist["loss"]])


if __name__ == "__main__":
    main()
