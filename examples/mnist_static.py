"""Static-graph MNIST LeNet (the reference book model
test_recognize_digits.py): fluid.nets conv-pool blocks + Adam.

    python examples/mnist_static.py [epochs]
"""
import sys

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import dataset
from paddle_tpu.fluid import layers


def build(batch):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", [batch, 1, 28, 28], "float32")
        label = fluid.data("label", [batch, 1], "int64")
        c1 = fluid.nets.simple_img_conv_pool(img, 6, 5, 2, 2, act="relu")
        c2 = fluid.nets.simple_img_conv_pool(c1, 16, 5, 2, 2, act="relu")
        logits = layers.fc(layers.reshape(c2, [batch, -1]), 10)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    return main, startup, loss, acc


def main(epochs=1, batch=64):
    main_p, startup, loss, acc = build(batch)
    exe = fluid.Executor()
    exe.run(startup)
    reader = paddle.batch(dataset.mnist.train(), batch, drop_last=True)
    for epoch in range(epochs):
        losses, accs = [], []
        for feed_batch in reader():
            imgs = np.stack([b[0] for b in feed_batch]).reshape(batch, 1, 28, 28)
            lbls = np.asarray([[b[1]] for b in feed_batch], "int64")
            lv, av = exe.run(main_p, feed={"img": imgs, "label": lbls},
                             fetch_list=[loss, acc])
            losses.append(float(np.asarray(lv).reshape(())))
            accs.append(float(np.asarray(av).reshape(-1)[0]))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
              f"acc {np.mean(accs[-50:]):.3f}")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 1)
