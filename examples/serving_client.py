"""Minimal serving smoke client (the Python face of the `infer` wire
protocol the Go/R client READMEs document).

Start a replica set first, e.g.:

    python -m paddle_tpu.distributed.launch --serve --nproc_per_node 2 \
        --started_port 8500 /path/to/saved_model

then:

    python examples/serving_client.py --endpoints 127.0.0.1:8500,127.0.0.1:8501

The high-level path uses paddle_tpu.inference.InferenceClient (replica
failover + hedging + typed Overloaded/DeadlineExceeded errors).
--raw instead drives ONE raw socket by hand — the exact framing a
non-Python client implements:

    request :=  8-byte big-endian length  ||  pickle((verb, kwargs))
    reply   :=  8-byte big-endian length  ||  pickle((ok, result))

    verb "infer" kwargs: {"feed": {name: ndarray}, "deadline_ms": float}
    ok=True  -> result = {"outputs": [ndarray...], "fetch_names": [...],
                          "weight_epoch": int, "queue_ms": float}
    ok=False -> result = "ErrorType: message" (strings starting with
                "Overloaded"/"DeadlineExceeded" are deliberate serving
                replies, not transport failures — do not blind-retry)
"""
from __future__ import annotations

import argparse
import pickle
import socket
import struct
import sys

import numpy as np

_LEN = struct.Struct(">Q")


def raw_infer(endpoint: str, feed: dict, deadline_ms: float = 5000.0):
    """One infer over one raw socket — the framing reference."""
    host, port = endpoint.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=30.0) as s:
        payload = pickle.dumps(
            ("infer", {"feed": feed, "deadline_ms": deadline_ms}),
            protocol=pickle.HIGHEST_PROTOCOL)
        s.sendall(_LEN.pack(len(payload)) + payload)
        hdr = b""
        while len(hdr) < _LEN.size:
            hdr += s.recv(_LEN.size - len(hdr))
        (n,) = _LEN.unpack(hdr)
        buf = b""
        while len(buf) < n:
            buf += s.recv(n - len(buf))
    ok, result = pickle.loads(buf)
    if not ok:
        raise RuntimeError(result)
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="serving smoke client")
    p.add_argument("--endpoints", required=True,
                   help="comma-separated replica host:port list")
    p.add_argument("--rows", type=int, default=2)
    p.add_argument("--deadline_ms", type=float, default=5000.0)
    p.add_argument("--raw", action="store_true",
                   help="drive one raw socket (framing reference) "
                        "instead of InferenceClient")
    args = p.parse_args(argv)
    endpoints = [e.strip() for e in args.endpoints.split(",")
                 if e.strip()]

    from paddle_tpu.inference import InferenceClient

    cli = InferenceClient(endpoints)
    info = cli.model_info()
    print("model:", info)
    rng = np.random.RandomState(0)
    feed = {}
    for name, meta in info["feeds"].items():
        shape = [d if d and d > 0 else 1 for d in (meta["shape"] or [1])]
        shape[0] = args.rows
        feed[name] = rng.rand(*shape).astype(meta["dtype"] or "float32")

    if args.raw:
        result = raw_infer(endpoints[0], feed,
                           deadline_ms=args.deadline_ms)
        print("raw infer ok: epoch", result["weight_epoch"],
              "outputs", [np.shape(o) for o in result["outputs"]])
        return 0
    res = cli.infer(feed, deadline_ms=args.deadline_ms)
    print(f"infer ok via {res.replica}: epoch {res.weight_epoch}, "
          f"queue {res.queue_ms}ms, outputs "
          f"{[o.shape for o in res.outputs]}")
    for name, o in zip(res.fetch_names, res.outputs):
        print(f"  {name}: head {np.asarray(o).reshape(-1)[:4]}")
    cli.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
