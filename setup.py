"""Package build for paddle_tpu (reference: the root CMakeLists.txt +
python/setup.py.in pipeline, SURVEY.md §2.7).

The native pieces (native/datafeed.cc, native/capi.cc) are compiled
lazily at import time into a per-user cache with hash-keyed rebuilds
(native/__init__.py), so the wheel itself is pure Python — no compiler
is needed at install time, only at first use of the native feed/C API.
"""
from setuptools import find_packages, setup

setup(
    name="paddle-tpu",
    version="0.1.0",
    description=(
        "TPU-native deep-learning framework with the capabilities of "
        "PaddlePaddle Fluid 1.8: Program IR, whole-block XLA compilation, "
        "GSPMD dp/tp/pp/sp/ep parallelism, Pallas flash attention"
    ),
    packages=find_packages(include=["paddle_tpu", "paddle_tpu.*"]),
    package_data={
        "paddle_tpu.native": ["*.cc", "*.h"],
        # checked-in per-chip autotune winners (tuning/cache.py layer 1)
        "paddle_tpu.tuning": ["defaults/*.json"],
    },
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "numpy",
    ],
    extras_require={
        "checkpoint": ["orbax-checkpoint"],
        "test": ["pytest"],
    },
)
