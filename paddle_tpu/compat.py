"""JAX version compatibility shims.

The codebase targets the current jax API surface; this module absorbs
the renames between releases so every call site imports ONE spelling.

shard_map moved twice upstream:

  jax <= 0.4.x   jax.experimental.shard_map.shard_map(check_rep=...)
  jax >= 0.5     jax.shard_map(...)  (check_rep)
  jax >= 0.6     jax.shard_map(...)  (check_rep renamed check_vma)

`shard_map` below resolves the import once and maps the replication-
check kwarg to whatever the installed jax spells it, defaulting it OFF
(every manual region here uses explicit collectives whose replication
the checker cannot always prove).
"""
from __future__ import annotations

import inspect
from typing import Any, Optional

_shard_map_impl = None
_check_kwarg: Optional[str] = None


def _resolve():
    global _shard_map_impl, _check_kwarg
    if _shard_map_impl is not None:
        return
    try:
        from jax import shard_map as impl  # jax >= 0.5
    except ImportError:
        from jax.experimental.shard_map import shard_map as impl
    params = inspect.signature(impl).parameters
    if "check_vma" in params:
        _check_kwarg = "check_vma"
    elif "check_rep" in params:
        _check_kwarg = "check_rep"
    else:  # future jax that dropped the knob entirely
        _check_kwarg = None
    _shard_map_impl = impl


def shard_map(f, mesh, in_specs, out_specs, check: bool = False) -> Any:
    """Version-stable shard_map. `check` maps onto check_rep/check_vma
    (whichever the installed jax has); call sites here always pass
    False — manual collective regions the checker rejects."""
    _resolve()
    kw = {_check_kwarg: check} if _check_kwarg is not None else {}
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def tpu_compiler_params(**kwargs):
    """pltpu.CompilerParams across the rename (<=0.4.x spells it
    TPUCompilerParams). Fields (vmem_limit_bytes, dimension_semantics,
    ...) are identical; only the class name moved."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")
    return cls(**kwargs)
