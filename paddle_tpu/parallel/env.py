"""Multi-host environment: rank/world discovery + coordination bootstrap.

Replaces the reference's launcher env protocol (PADDLE_TRAINER_ID /
PADDLE_TRAINER_ENDPOINTS, /root/reference/python/paddle/distributed/launch.py:193)
and the NCCL-id gRPC rendezvous (c_gen_nccl_id_op.cc): on TPU the
JAX distributed coordination service is the bootstrap — one
jax.distributed.initialize() call per host, then every chip on every host
appears in jax.devices() and XLA collectives ride ICI/DCN.
"""
from __future__ import annotations

import os

_initialized = False


def get_rank() -> int:
    for k in ("PADDLE_TRAINER_ID", "JAX_PROCESS_ID", "RANK"):
        if k in os.environ:
            return int(os.environ[k])
    return 0


def get_endpoints() -> list:
    """Launcher-provided trainer endpoints (single source of truth for
    PADDLE_TRAINER_ENDPOINTS parsing)."""
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    return [e.strip() for e in eps.split(",") if e.strip()] if eps else []


def get_world_size() -> int:
    if "PADDLE_TRAINERS_NUM" in os.environ:
        return int(os.environ["PADDLE_TRAINERS_NUM"])
    eps = get_endpoints()
    if eps:
        return len(eps)
    if "JAX_NUM_PROCESSES" in os.environ:
        return int(os.environ["JAX_NUM_PROCESSES"])
    return 1


def init_parallel_env() -> None:
    """Initialize the JAX coordination service when launched multi-host
    (paddle launcher env convention); single-process no-op."""
    global _initialized
    if _initialized:
        return
    # liveness stamping for the launcher's hang detection / elastic
    # restart (no-op unless the launcher set PADDLE_HEARTBEAT_DIR)
    from ..distributed.heartbeat import start_heartbeat

    start_heartbeat()
    # per-rank timeline collection for the launcher's merged trace
    # (no-op unless the launcher set PADDLE_TRACE_DIR via --trace_dir)
    from ..fluid.profiler import maybe_start_trace_collection

    maybe_start_trace_collection()
    # live introspection server + metrics push exporter (no-ops unless
    # the launcher set PADDLE_DEBUGZ_PORT / PADDLE_METRICS_PUSH_URL; the
    # executor step loop arms them too, for un-launched processes)
    from ..telemetry import debugz, export

    debugz.maybe_serve()
    export.maybe_start()
    world = get_world_size()
    if world > 1:
        import jax

        # CPU multi-process needs the gloo collectives backend (the TPU
        # path rides ICI/DCN natively). Sniff the env instead of calling
        # jax.default_backend(): that would initialize backends BEFORE
        # the coordination service, which breaks multi-process startup.
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:  # noqa: BLE001 — older jaxlib without gloo
                pass
        eps = get_endpoints()
        coordinator = eps[0] if eps else None
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=world,
            process_id=get_rank(),
        )
    _initialized = True
