"""Distributed front end: device meshes + sharding annotations (GSPMD).

Capability parity with the reference's distributed stack (SURVEY.md §2.5):
where the reference rewrites programs to insert NCCL collective ops
(transpiler/collective.py, ir/multi_devices_graph_pass/) and runs
per-device SSA graphs, the TPU build annotates Variables with
jax.sharding.PartitionSpec and jits the whole train step over a
jax.sharding.Mesh — XLA's SPMD partitioner inserts all collectives
(all-reduce for replicated-param grads, all-gather/reduce-scatter for
tensor parallel) on ICI/DCN automatically. ring_id -> mesh axis name.

Axes convention: "dp" (data), "tp" (tensor/model), "pp" (pipeline stage),
"sp" (sequence/context), "ep" (expert).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from . import env  # noqa: F401
from .env import get_rank, get_world_size  # noqa: F401


def create_mesh(axes: Dict[str, int], devices=None):
    """Build a jax.sharding.Mesh with named axes.

    axes: ordered {axis_name: size}. Product must equal #devices used.
    A size of -1 on exactly one axis means "fill with remaining devices".
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    names = list(axes)
    sizes = [axes[n] for n in names]
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, have {len(devices)}")
    dev_array = np.array(devices[:total]).reshape(sizes)
    return Mesh(dev_array, names)


def partition_spec(*axes):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*axes)


def set_var_sharding(var, spec: Optional[Sequence[Optional[str]]]):
    """Annotate a program Variable with a PartitionSpec (tuple of mesh axis
    names / None per dim). The Executor turns this into NamedSharding on
    the jitted step; unannotated vars default to replicated."""
    from jax.sharding import PartitionSpec

    if spec is not None and not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*spec)
    var._sharding = spec
    var.block.program._bump_version()  # invalidate executor compile cache


def get_var_sharding(var):
    return getattr(var, "_sharding", None)


def shard_program_data_parallel(program, mesh, axis: str = "dp"):
    """Mark every data (feed) variable as batch-sharded along `axis` —
    the GSPMD analog of the reference's GradAllReduce transpile
    (/root/reference/python/paddle/fluid/transpiler/collective.py:178):
    with inputs sharded and parameters replicated, XLA emits the gradient
    all-reduce on its own."""
    for v in program.list_vars():
        if getattr(v, "is_data", False) and v.shape:
            set_var_sharding(v, (axis,) + (None,) * (len(v.shape) - 1))
    program._mesh = mesh


def shard_program_sequence_parallel(program, mesh, axis: str = "sp"):
    """Additionally shard the sequence dim (dim 1) of feed variables over
    `axis` — activations between attention ops then stay sequence-sharded
    and XLA only gathers where an op genuinely needs the full sequence.
    Vars whose dim 1 does not divide by the axis size (labels [B,1] etc.)
    stay replicated on that dim, which is always correct under GSPMD."""
    from jax.sharding import PartitionSpec

    sp_size = mesh.shape[axis]
    for v in program.list_vars():
        if not (getattr(v, "is_data", False) and v.shape and len(v.shape) >= 2):
            continue
        s = v.shape[1]
        if s is None or s <= 1 or (s > 0 and s % sp_size != 0):
            continue
        cur = get_var_sharding(v)
        dims = list(cur) if cur is not None else []
        dims += [None] * (len(v.shape) - len(dims))
        dims[1] = axis
        set_var_sharding(v, PartitionSpec(*dims))
