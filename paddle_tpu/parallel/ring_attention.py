"""Ring attention: sequence/context parallelism over a mesh axis.

A NEW capability relative to the reference (2020-era aiqingma/Paddle has no
sequence parallelism — SURVEY.md §5 "Long-context"): long sequences are
sharded over the "sp" mesh axis; each device holds a contiguous sequence
block of Q, K, V and rotates its K/V block around the ring with
`lax.ppermute` (ICI neighbor exchange) while accumulating flash-attention
style online-softmax partial results. Peak memory per chip is
O(S_local * D) and the K/V transfer overlaps with the matmul of the
previous block (XLA pipelines the ppermute against the einsum).

The loop is a `lax.scan`, so reverse-mode AD works end-to-end: the
backward pass rotates cotangents with the transposed permutation that JAX
derives for ppermute — no custom VJP needed.

When shapes permit (S_local % 128 == 0, D in {64,128,256}), each local
block runs the Pallas flash kernel via `flash_block_with_lse` — an
(o, lse)-returning custom-VJP core — and the ring merges partials by
log-sum-exp. Causal masking rides the kernel's (q_offset, k_offset)
global-position pair and dropout its in-kernel PRNG, so the training
configurations stay on the kernel path; the jnp online-softmax block
math below remains the fallback for non-kernel shapes.
"""
from __future__ import annotations

import math
from typing import Optional

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name: str, bias=None, sm_scale=None,
                   causal: bool = False, dropout_prob: float = 0.0,
                   dropout_key=None):
    """Per-shard attention body (call inside shard_map / pjit manual axes).

    q, k, v: [B, nh, S_local, D] — the local sequence block.
    bias: optional per-key additive bias [B, S_local] (padding mask block),
        sharded like K; rotated around the ring together with K/V.
    dropout_prob/dropout_key: attention-probs dropout. Masking only the
        numerator accumulation (acc), never the normalizer (l), is exactly
        post-softmax dropout: out = sum(mask*p/(1-pr) * v) / sum(p).
    Returns [B, nh, S_local, D].
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, nh, s_loc, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    use_dropout = dropout_prob > 0.0 and dropout_key is not None

    from ..ops.pallas.flash_attention import flash_block_ok

    if flash_block_ok(s_loc, d):
        return _ring_flash(
            q, k, v, axis_name, bias, sm_scale, n,
            causal=causal,
            dropout_prob=dropout_prob if use_dropout else 0.0,
            dropout_key=dropout_key if use_dropout else None,
        )

    qf = q.astype(jnp.float32) * sm_scale
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        kb, vb, bb, m, l, acc = carry
        src = (idx - t) % n  # which rank's block we currently hold
        s = jnp.einsum(
            "bnqd,bnkd->bnqk", qf, kb.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if bb is not None:
            s = s + bb.astype(jnp.float32)[:, None, None, :]
        if causal:
            qpos = idx * s_loc + jnp.arange(s_loc)
            kpos = src * s_loc + jnp.arange(s_loc)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # explicit re-mask: for a fully-masked block m_new stays NEG_INF and
        # exp(s - m_new) would be exp(0)=1; the where() zeroes those rows
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        p_num = p
        if use_dropout:
            # independent mask per (my shard, source block) pair
            kdrop = jax.random.fold_in(jax.random.fold_in(dropout_key, idx), src)
            keep = jax.random.bernoulli(kdrop, 1.0 - dropout_prob, p.shape)
            p_num = jnp.where(keep, p / (1.0 - dropout_prob), 0.0)
        acc = acc * alpha + jnp.einsum(
            "bnqk,bnkd->bnqd", p_num, vb.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        if bb is not None:
            bb = lax.ppermute(bb, axis_name, perm)
        return (kb, vb, bb, m_new, l, acc), None

    m0 = jnp.full((b, nh, s_loc, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, nh, s_loc, 1), jnp.float32)
    acc0 = jnp.zeros((b, nh, s_loc, d), jnp.float32)
    (kb, vb, bb, m, l, acc), _ = jax.lax.scan(
        step, (k, v, bias, m0, l0, acc0), jnp.arange(n)
    )
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def _ring_flash(q, k, v, axis_name, bias, sm_scale, n, causal=False,
                dropout_prob=0.0, dropout_key=None):
    """Ring schedule where each block is the Pallas flash kernel: merge
    per-block (o, lse) partials by log-sum-exp. AD flows through the
    kernel's custom VJP (the lse cotangent folds into delta).

    causal: the kernel masks each visiting block by its GLOBAL positions
    (q_offset = my shard start, k_offset = source shard start); blocks
    entirely in the future produce lse=-inf partials that merge to zero
    weight. dropout: regenerated in-kernel from a per-(shard, source)
    seed (interpret mode precomputes the mask host-side — same math)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops.pallas.flash_attention import _interpret, flash_block_with_lse

    b, nh, s_loc, d = q.shape
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    use_dropout = dropout_prob > 0.0 and dropout_key is not None
    seed_base = None
    if use_dropout and not _interpret():
        seed_base = jax.random.randint(
            dropout_key, (), 0, jnp.iinfo(jnp.int32).max, dtype=jnp.int32
        )

    def step(carry, t):
        kb, vb, bb, m, l, acc = carry
        src = (idx - t) % n  # which rank's block we currently hold
        kw = {}
        if causal:
            kw = dict(causal=True, q_offset=idx * s_loc,
                      k_offset=src * s_loc)
        if use_dropout:
            kw["dropout_prob"] = dropout_prob
            if seed_base is not None:
                kw["dropout_seed"] = (
                    seed_base + idx * jnp.int32(0x632BE59B)
                    + src * jnp.int32(0x1B873593)
                )
            else:
                # interpret (CPU) mode: the TPU in-kernel PRNG is
                # unavailable — draw the same numerator-only mask host-side
                kdrop = jax.random.fold_in(
                    jax.random.fold_in(dropout_key, idx), src
                )
                kw["dropout_mask"] = jax.random.bernoulli(
                    kdrop, 1.0 - dropout_prob, (b, nh, s_loc, s_loc)
                ).astype(jnp.uint8)
        o_b, lse_b = flash_block_with_lse(q, kb, vb, bb, sm_scale, **kw)
        lse_b = lse_b[..., None]  # [B, nh, S, 1]
        m_new = jnp.maximum(m, lse_b)
        scale_old = jnp.exp(m - m_new)
        scale_new = jnp.exp(lse_b - m_new)
        acc = acc * scale_old + o_b.astype(jnp.float32) * scale_new
        l = l * scale_old + scale_new
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        if bb is not None:
            bb = lax.ppermute(bb, axis_name, perm)
        return (kb, vb, bb, m_new, l, acc), None

    m0 = jnp.full((b, nh, s_loc, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, nh, s_loc, 1), jnp.float32)
    acc0 = jnp.zeros((b, nh, s_loc, d), jnp.float32)
    (k, v, bias, m, l, acc), _ = jax.lax.scan(
        step, (k, v, bias, m0, l0, acc0), jnp.arange(n)
    )
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention_global(q, k, v, mesh, axis: str = "sp", bias=None,
                          sm_scale=None, causal: bool = False,
                          batch_axis: Optional[str] = "dp",
                          dropout_prob: float = 0.0, dropout_key=None):
    """Global-array entry: shard [B, nh, S, D] over `axis` on the sequence
    dim (and `batch_axis` on batch if present in the mesh), run the ring
    body per shard. Usable under jit — GSPMD handles everything outside,
    the ring handles attention's cross-shard dependency inside."""
    from jax.sharding import PartitionSpec as P

    from ..compat import shard_map

    ba = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    qkv_spec = P(ba, None, axis, None)
    bias_spec = P(ba, axis)

    if bias is None:
        def body(ql, kl, vl):
            return ring_attention(ql, kl, vl, axis, None, sm_scale, causal,
                                  dropout_prob, dropout_key)

        return shard_map(
            body, mesh=mesh, in_specs=(qkv_spec,) * 3, out_specs=qkv_spec,
            check=False,
        )(q, k, v)

    def body_b(ql, kl, vl, bl):
        return ring_attention(ql, kl, vl, axis, bl, sm_scale, causal,
                              dropout_prob, dropout_key)

    return shard_map(
        body_b, mesh=mesh, in_specs=(qkv_spec,) * 3 + (bias_spec,),
        out_specs=qkv_spec, check=False,
    )(q, k, v, bias)


def use_ring(ctx, attrs) -> bool:
    """Shared enablement predicate: the op asked for sequence parallelism
    AND the emit mesh actually has a populated "sp" axis."""
    return (
        bool(attrs.get("sequence_parallel", False))
        and ctx.mesh is not None
        and "sp" in ctx.mesh.axis_names
        and ctx.mesh.shape["sp"] > 1
    )


def key_bias_from_attn_bias(bias, batch):
    """Validate/convert an additive attention bias to the per-key [B, S]
    form the ring kernel rotates. Only [B,1,1,S] (padding mask) qualifies."""
    if bias is None:
        return None
    if bias.ndim == 4 and bias.shape[1] == 1 and bias.shape[2] == 1:
        return bias.reshape(batch, bias.shape[-1])
    raise ValueError(
        "sequence-parallel ring attention supports per-key bias [B,1,1,S] "
        f"(padding mask); got bias shape {bias.shape}"
    )
