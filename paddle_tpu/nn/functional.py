"""paddle.nn.functional (reference python/paddle/nn/functional/): the
op-level NN API with 2.0 signatures, usable in BOTH modes — static graph
(emits ops into the current Program) and dygraph (runs the same
registered emitters eagerly through the tracer)."""
from __future__ import annotations

from ..fluid.layer_helper import emit_op as _op


def _unary(op_type, **fixed):
    def fn(x, name=None, **kw):
        return _op(op_type, {"X": [x]}, {**fixed, **kw})

    fn.__name__ = op_type
    return fn


relu = _unary("relu")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
gelu = _unary("gelu")
elu = _unary("elu")
silu = _unary("silu")
softplus = _unary("softplus")
mish = _unary("mish")
hardswish = hard_swish = _unary("hard_swish")
hardsigmoid = hard_sigmoid = _unary("hard_sigmoid")
swish = _unary("swish")


def leaky_relu(x, negative_slope=0.01, name=None):
    return _op("leaky_relu", {"X": [x]}, {"alpha": negative_slope})


def softmax(x, axis=-1, name=None):
    return _op("softmax", {"X": [x]}, {"axis": axis})


def log_softmax(x, axis=-1, name=None):
    return _op("log_softmax", {"X": [x]}, {"axis": axis})


def dropout(x, p=0.5, training=True, name=None):
    out = _op(
        "dropout", {"X": [x]},
        {"dropout_prob": p, "is_test": not training,
         "dropout_implementation": "upscale_in_train"},
        out_slots=("Out", "Mask"),
    )
    return out[0]


def linear(x, weight, bias=None, name=None):
    out = _op("matmul", {"X": [x], "Y": [weight]})
    if bias is not None:
        out = _op("elementwise_add", {"X": [out], "Y": [bias]}, {"axis": -1})
    return out


def embedding(x, weight, padding_idx=None, name=None):
    return _op(
        "lookup_table_v2", {"W": [weight], "Ids": [x]},
        {"padding_idx": -1 if padding_idx is None else padding_idx},
    )


def one_hot(x, num_classes, name=None):
    return _op("one_hot_v2", {"X": [x]}, {"depth": num_classes},
               out_dtype="float32")


def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return _op("mean", {"X": [loss]})
    if reduction == "sum":
        return _op("reduce_sum", {"X": [loss]},
                   {"reduce_all": True, "keep_dim": False, "dim": [0]})
    return loss


def cross_entropy(input, label, soft_label=False, ignore_index=-100,
                  reduction="mean", name=None):
    outs = _op(
        "softmax_with_cross_entropy",
        {"Logits": [input], "Label": [label]},
        {"soft_label": soft_label, "ignore_index": ignore_index},
        out_slots=("Softmax", "Loss"),
    )
    return _reduce_loss(outs[1], reduction)


def mse_loss(input, label, reduction="mean", name=None):
    loss = _op("square_error_cost", {"X": [input], "Y": [label]})
    return _reduce_loss(loss, reduction)


def l1_loss(input, label, reduction="mean", name=None):
    diff = _op("elementwise_sub", {"X": [input], "Y": [label]}, {"axis": -1})
    loss = _op("abs", {"X": [diff]})
    return _reduce_loss(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, reduction="mean",
                                     name=None):
    loss = _op(
        "sigmoid_cross_entropy_with_logits",
        {"X": [logit], "Label": [label]}, {},
    )
    return _reduce_loss(loss, reduction)


def kl_div(input, label, reduction="mean", name=None):
    return _op("kldiv_loss", {"X": [input], "Target": [label]},
               {"reduction": reduction}, out_slots=("Loss",))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _op("norm", {"X": [x]}, {"axis": axis, "epsilon": epsilon},
               out_slots=("Out", "Norm"))[0]


def pad(x, paddings, value=0.0, name=None):
    return _op("pad", {"X": [x]},
               {"paddings": list(paddings), "pad_value": value})


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           name=None):
    s = [stride] * 2 if isinstance(stride, int) else list(stride)
    p = [padding] * 2 if isinstance(padding, int) else list(padding)
    d = [dilation] * 2 if isinstance(dilation, int) else list(dilation)
    out = _op(
        "conv2d", {"Input": [x], "Filter": [weight]},
        {"strides": s, "paddings": p, "dilations": d, "groups": groups},
        out_slots=("Output",),
    )
    if bias is not None:
        out = _op("elementwise_add", {"X": [out], "Y": [bias]}, {"axis": 1})
    return out


def _pool2d(x, kernel_size, stride, padding, ptype):
    ks = [kernel_size] * 2 if isinstance(kernel_size, int) else list(kernel_size)
    st = stride if stride is not None else kernel_size
    st = [st] * 2 if isinstance(st, int) else list(st)
    pd = [padding] * 2 if isinstance(padding, int) else list(padding)
    return _op(
        "pool2d", {"X": [x]},
        {"pooling_type": ptype, "ksize": ks, "strides": st, "paddings": pd},
    )


def avg_pool2d(x, kernel_size, stride=None, padding=0, name=None):
    return _pool2d(x, kernel_size, stride, padding, "avg")


def max_pool2d(x, kernel_size, stride=None, padding=0, name=None):
    return _pool2d(x, kernel_size, stride, padding, "max")


def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5,
               name=None):
    ins = {"X": [x]}
    if weight is not None:
        ins["Scale"] = [weight]
    if bias is not None:
        ins["Bias"] = [bias]
    return _op(
        "layer_norm", ins,
        {"epsilon": epsilon, "begin_norm_axis": len(x.shape) - 1},
        out_slots=("Y", "Mean", "Variance"),
    )[0]
