"""paddle.nn 2.0-preview namespace (reference python/paddle/nn/ ~5.3k:
layer classes + functional). Layer classes are the dygraph Layers (which
also reach the static executor via dygraph-to-static); `functional`
exposes the op-level API for both modes.
"""
from __future__ import annotations

from ..fluid.dygraph.layers import Layer  # noqa: F401
from ..fluid.dygraph.nn import (  # noqa: F401
    BatchNorm,
    Conv2D,
    Dropout,
    Embedding,
    LayerNorm,
    Linear,
    Pool2D,
)
from ..fluid.dygraph.parallel import DataParallel  # noqa: F401
from . import functional  # noqa: F401


class Sequential(Layer):
    """Chain of sublayers (reference paddle.nn.Sequential)."""

    def __init__(self, *layers):
        super().__init__()
        self._seq = []
        for i, l in enumerate(layers):
            if isinstance(l, tuple):
                name, l = l
            else:
                name = str(i)
            self.add_sublayer(name, l)
            self._seq.append(l)

    def forward(self, x):
        for l in self._seq:
            x = l(x)
        return x

    def __len__(self):
        return len(self._seq)

    def __getitem__(self, i):
        return self._seq[i]


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class Sigmoid(Layer):
    def forward(self, x):
        return functional.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return functional.tanh(x)


class GELU(Layer):
    def forward(self, x):
        return functional.gelu(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return functional.softmax(x, axis=self._axis)


class CrossEntropyLoss(Layer):
    """softmax_with_cross_entropy + mean (reference nn.CrossEntropyLoss)."""

    def __init__(self, weight=None, reduction="mean", ignore_index=-100):
        super().__init__()
        if weight is not None:
            raise NotImplementedError(
                "CrossEntropyLoss: per-class weight not supported; "
                "multiply per-sample losses by gathered weights instead"
            )
        self._reduction = reduction

    def forward(self, logits, label):
        loss = functional.cross_entropy(logits, label, reduction=self._reduction)
        return loss


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, pred, label):
        return functional.mse_loss(pred, label, reduction=self._reduction)


# ---------------------------------------------------------------------------
# 2.0-preview breadth (reference python/paddle/nn/__init__.py): the 1.8
# preview re-exports the functional surface from fluid.layers at the nn
# top level, plus class layers, initializer aliases, and clip classes.
# ---------------------------------------------------------------------------

from ..fluid.layers import (  # noqa: F401,E402
    adaptive_pool2d, adaptive_pool3d, add_position_encoding, affine_channel,
    affine_grid, anchor_generator, assign, beam_search, beam_search_decode,
    bipartite_match, box_clip, box_coder, box_decoder_and_assign, bpr_loss,
    brelu, case, center_loss, clip, clip_by_norm, collect_fpn_proposals,
    cond, continuous_value_model, conv2d, conv2d_transpose, conv3d,
    conv3d_transpose, cosine_decay, cross_entropy, data,
    deformable_roi_pooling, density_prior_box, detection_output, dice_loss,
    distribute_fpn_proposals, dropout, edit_distance, elu, erf,
    exponential_decay, filter_by_instag, fsp_matrix, gather_tree, gelu,
    generate_mask_labels, generate_proposal_labels, generate_proposals,
    grid_sampler, hard_shrink, hard_sigmoid, hard_swish, hash, hsigmoid,
    huber_loss, image_resize, image_resize_short, inverse_time_decay,
    iou_similarity, kldiv_loss, l2_normalize, label_smooth, leaky_relu,
    linear_lr_warmup, log_loss, log_softmax, logsigmoid, lrn,
    margin_rank_loss, maxout, mse_loss, multiclass_nms, natural_exp_decay,
    noam_decay, npair_loss, one_hot, pad, pad2d, pad_constant_like,
    piecewise_decay, pixel_shuffle, polygon_box_transform, polynomial_decay,
    pool2d, pool3d, prior_box, prroi_pool, psroi_pool, random_crop,
    rank_loss, relu, relu6, resize_bilinear, resize_nearest,
    resize_trilinear, retinanet_detection_output, retinanet_target_assign,
    roi_align, roi_perspective_transform, roi_pool, row_conv,
    rpn_target_assign, sampled_softmax_with_cross_entropy, selu,
    shuffle_channel, sigmoid, sigmoid_cross_entropy_with_logits,
    sigmoid_focal_loss, similarity_focus, smooth_l1, soft_relu, softmax,
    softmax_with_cross_entropy, softplus, softsign, space_to_depth,
    square_error_cost, ssd_loss, swish, switch_case, target_assign,
    teacher_student_sigmoid_loss, temporal_shift, thresholded_relu, unfold,
    warpctc, while_loop, yolo_box, yolov3_loss,
)
from ..fluid.layers import soft_shrink as softshrink  # noqa: F401,E402
from ..fluid.clip import (  # noqa: F401,E402
    GradientClipByGlobalNorm,
    GradientClipByNorm,
    GradientClipByValue,
)
from ..fluid.dygraph.nn import (  # noqa: F401,E402
    BilinearTensorProduct,
    Conv2DTranspose,
    Conv3D,
    Conv3DTranspose,
    GroupNorm,
    InstanceNorm,
    RowConv,
    SpectralNorm,
)
from ..fluid.initializer import (  # noqa: F401,E402
    ConstantInitializer as Constant,
    MSRAInitializer as MSRA,
    NormalInitializer as Normal,
    TruncatedNormalInitializer as TruncatedNormal,
    UniformInitializer as Uniform,
    XavierInitializer as Xavier,
)

Bilinear = BilinearTensorProduct
interpolate = image_resize


def tanh_shrink(x, name=None):
    """x - tanh(x) (reference ops.py tanh_shrink)."""
    from ..fluid.layer_helper import emit_op

    return emit_op("tanh_shrink", {"X": [x]})


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    """Batched diagonal embed (reference nn/functional/extension.py)."""
    if offset != 0 or (dim1, dim2) != (-2, -1):
        raise NotImplementedError("diag_embed: main-diagonal form only")
    from ..fluid.layer_helper import emit_op

    return emit_op("diag_embed", {"X": [input]})


class LeakyReLU(Layer):
    def __init__(self, alpha=0.01):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return functional.leaky_relu(x, negative_slope=self._alpha)


class LogSoftmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return functional.log_softmax(x, axis=self._axis)


class HSigmoid(Layer):
    """2.0-preview HSigmoid layer over the hsigmoid composition
    (static-graph mode: the composition builds program ops)."""

    def __init__(self, feature_size, num_classes, param_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False):
        super().__init__()
        if is_custom:
            raise NotImplementedError("HSigmoid: default tree only")
        self._num_classes = num_classes

    def forward(self, input, label):
        from ..fluid.layers import hsigmoid as _h

        return _h(input, label, self._num_classes)


class Pad2D(Layer):
    def __init__(self, paddings=0, mode="constant", pad_value=0.0,
                 data_format="NCHW"):
        super().__init__()
        if data_format != "NCHW":
            raise NotImplementedError("Pad2D: NCHW only")
        p = [paddings] * 4 if isinstance(paddings, int) else list(paddings)
        self._attrs = {"paddings": p, "mode": mode, "pad_value": pad_value}

    def forward(self, x):
        from ..fluid.layer_helper import emit_op

        return emit_op("pad2d", {"X": [x]}, dict(self._attrs))


class UpSample(Layer):
    def __init__(self, out_shape=None, scale=None, resample="BILINEAR",
                 align_corners=True, align_mode=1, data_format="NCHW"):
        super().__init__()
        if data_format != "NCHW":
            raise NotImplementedError("UpSample: NCHW only")
        if out_shape is None and scale is None:
            raise ValueError("UpSample: need out_shape or scale")
        self._args = (out_shape, scale, resample, align_corners, align_mode)

    def forward(self, x):
        out_shape, scale, resample, ac, am = self._args
        op = {"BILINEAR": "bilinear_interp", "NEAREST": "nearest_interp",
              "TRILINEAR": "trilinear_interp"}[resample.upper()]
        spatial = list(x.shape[2:])
        if out_shape is None:
            out_shape = [int(d * scale) for d in spatial]
        from ..fluid.layer_helper import emit_op

        attrs = {"align_corners": ac, "align_mode": am}
        if len(out_shape) == 2:
            attrs["out_h"], attrs["out_w"] = out_shape
        else:
            attrs["out_d"], attrs["out_h"], attrs["out_w"] = out_shape
        return emit_op(op, {"X": [x]}, attrs)


class BCELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        from ..fluid.layer_helper import emit_op
        from .functional import _reduce_loss

        loss = emit_op("bce_loss", {"X": [input], "Label": [label]})
        return _reduce_loss(loss, self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return functional.l1_loss(input, label, reduction=self._reduction)


class NLLLoss(Layer):
    """Negative log likelihood over LOG-probability inputs; label [N] or
    [N, 1] int."""

    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        from ..fluid.layer_helper import emit_op
        from .functional import _reduce_loss

        depth = input.shape[-1]
        if len(label.shape) == len(input.shape):
            # [N, 1] -> [N]: one_hot on the trailing singleton would
            # broadcast to [N, N, C] and silently average cross terms
            label = emit_op("reshape", {"X": [label]},
                            {"shape": list(label.shape[:-1])})
        oh = emit_op("one_hot_v2", {"X": [label]}, {"depth": depth})
        picked = emit_op(
            "reduce_sum",
            {"X": [emit_op("elementwise_mul",
                           {"X": [input], "Y": [oh]})]},
            {"dim": [-1], "keep_dim": False})
        return _reduce_loss(
            emit_op("scale", {"X": [picked]}, {"scale": -1.0}),
            self._reduction)
