"""paddle.nn 2.0-preview namespace (reference python/paddle/nn/ ~5.3k:
layer classes + functional). Layer classes are the dygraph Layers (which
also reach the static executor via dygraph-to-static); `functional`
exposes the op-level API for both modes.
"""
from __future__ import annotations

from ..fluid.dygraph.layers import Layer  # noqa: F401
from ..fluid.dygraph.nn import (  # noqa: F401
    BatchNorm,
    Conv2D,
    Dropout,
    Embedding,
    LayerNorm,
    Linear,
    Pool2D,
)
from ..fluid.dygraph.parallel import DataParallel  # noqa: F401
from . import functional  # noqa: F401


class Sequential(Layer):
    """Chain of sublayers (reference paddle.nn.Sequential)."""

    def __init__(self, *layers):
        super().__init__()
        self._seq = []
        for i, l in enumerate(layers):
            if isinstance(l, tuple):
                name, l = l
            else:
                name = str(i)
            self.add_sublayer(name, l)
            self._seq.append(l)

    def forward(self, x):
        for l in self._seq:
            x = l(x)
        return x

    def __len__(self):
        return len(self._seq)

    def __getitem__(self, i):
        return self._seq[i]


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class Sigmoid(Layer):
    def forward(self, x):
        return functional.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return functional.tanh(x)


class GELU(Layer):
    def forward(self, x):
        return functional.gelu(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return functional.softmax(x, axis=self._axis)


class CrossEntropyLoss(Layer):
    """softmax_with_cross_entropy + mean (reference nn.CrossEntropyLoss)."""

    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, logits, label):
        loss = functional.cross_entropy(logits, label, reduction=self._reduction)
        return loss


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, pred, label):
        return functional.mse_loss(pred, label, reduction=self._reduction)
