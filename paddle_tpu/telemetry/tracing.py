"""Distributed step tracing: causal span propagation (ISSUE 9).

The cross-process answer to "why did this sync round stall": every hop
we own — an Executor step, a client RPC (each retry, backoff sleep and
hedge attempt individually), the server-side verb handling it lands in,
the sync-barrier wait, a primary→backup `replicate` forward, a
coordinator lease renewal — becomes a SPAN carrying W3C-traceparent-
style identity (trace_id / span_id / parent_id), so one trace_id
connects trainer → primary → backup → coordinator and per-hop wall time
is evidence, not inference.

Design contract (mirrors the rest of the telemetry package):

  gate        PADDLE_TRACING=1 arms the layer. Off (the default) every
              entry point returns None after one cached bool read, the
              RPC payload gains NO key (wire bytes bit-identical — the
              CI drill asserts it) and nothing allocates.
  spans       in-process bounded ring buffer (PADDLE_TRACE_RING spans,
              default 4096) of finished-span dicts; timestamps are
              time.time() for cross-process ordering and
              perf_counter deltas for durations.
  context     thread-local span stack; `bound()` re-binds the caller's
              context inside worker-pool threads (RemoteTable fan-out,
              hedges) and the `_trace` payload key carries it across
              the wire ("00-<trace>-<span>-01", W3C traceparent).
  flight rec  dump_flight()/flight recorder: the span ring + recent
              step records written atomically to PADDLE_TRACE_DIR as
              flightrec.<tag>.json on SIGTERM, BadStepError,
              lease-expiry eviction, fault-injected kill/crash,
              unhandled crash, and process exit — the post-mortem
              input tools/tracetop.py merges into a causal trace.
  live        debugz /tracez serves tracez() — recent traces,
              slowest-first, per-hop durations.

Module is stdlib-only (the pserver, coordinator and launcher import it
without jax).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

ENV_GATE = "PADDLE_TRACING"
ENV_DIR = "PADDLE_TRACE_DIR"  # shared with the profiler's chrome dumps
ENV_RING = "PADDLE_TRACE_RING"

_enabled: Optional[bool] = None
_lock = threading.Lock()
_tls = threading.local()

# finished spans, oldest dropped first; each carries a process-monotone
# `seq` so the push exporter can drain "everything since my cursor"
_ring: deque = deque(maxlen=int(os.environ.get(ENV_RING, 4096) or 4096))
_seq = 0

# the last Executor step's (trace_id, span_id): joined onto heartbeat
# stamps (straggler episodes cite it) and checkpoint-save spans
_last_step_ctx: Optional[Tuple[str, str]] = None

_hooks_installed = False
_dumped_reasons: set = set()

# per-request serving flight records (ISSUE 19): one dict per retired
# generation, bounded; rides along in flight_dump payloads so
# tools/reqtop.py can reconstruct where a slow request's wall time went
_REQ_RECORDS: deque = deque(maxlen=256)


def enabled() -> bool:
    """PADDLE_TRACING gate, resolved once per process (one bool read on
    the hot path afterwards)."""
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get(ENV_GATE, "") not in ("", "0", "false")
    return _enabled


def process_tag() -> str:
    """This process's stable identity in dumps: the pserver tag ("ps0"),
    the launcher trainer rank ("trainer1"), else the pid."""
    t = os.environ.get("PADDLE_PS_RANK_TAG")
    if t:
        return t
    r = os.environ.get("PADDLE_TRAINER_ID")
    if r is not None:
        return f"trainer{r}"
    return f"pid{os.getpid()}"


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class Span:
    """One in-flight span. Finished spans are stored as plain dicts in
    the ring; the object itself never outlives its scope."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "attrs", "status", "t0", "start", "tid")

    def __init__(self, name: str, kind: str, trace_id: str,
                 parent_id: Optional[str], attrs: Optional[dict]):
        self.trace_id = trace_id
        self.span_id = _new_id(8)
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.attrs = dict(attrs) if attrs else {}
        self.status = "ok"
        self.t0 = time.perf_counter()
        self.start = time.time()
        self.tid = threading.get_ident() % 100_000

    def to_dict(self, dur_ms: float) -> dict:
        d = {
            "trace": self.trace_id, "span": self.span_id,
            "parent": self.parent_id, "name": self.name,
            "kind": self.kind, "ts": round(self.start, 6),
            "dur_ms": round(dur_ms, 3), "status": self.status,
            "proc": process_tag(), "tid": self.tid,
        }
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class _Ctx:
    """A remote/captured context re-bound in this thread (no new span):
    just enough identity for children to parent under."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current():
    """Innermost active span/context in this thread, or None."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def current_ctx() -> Optional[Tuple[str, str]]:
    c = current()
    return (c.trace_id, c.span_id) if c is not None else None


def begin(name: str, kind: str = "internal", parent: Any = "auto",
          attrs: Optional[dict] = None) -> Optional[Span]:
    """Open a span WITHOUT pushing it on the thread-local stack (manual
    parenting — the RPC attempt loop). parent: "auto" (innermost active),
    None (new root trace), a Span/_Ctx, or a (trace_id, span_id) tuple.
    Returns None when tracing is off."""
    if not enabled():
        return None
    if parent == "auto":
        parent = current()
    if parent is None:
        return Span(name, kind, _new_id(16), None, attrs)
    if isinstance(parent, tuple):
        return Span(name, kind, parent[0], parent[1], attrs)
    return Span(name, kind, parent.trace_id, parent.span_id, attrs)


def finish(span: Optional[Span], status: Optional[str] = None) -> None:
    """Close a begin() span and record it in the ring. None-safe."""
    global _seq
    if span is None:
        return
    if status is not None:
        span.status = status
    d = span.to_dict((time.perf_counter() - span.t0) * 1e3)
    with _lock:
        _seq += 1
        d["seq"] = _seq
        _ring.append(d)


class _SpanScope:
    """Context manager: begin() + thread-local push, finish on exit
    (error status when the body raised). Yields the Span or None."""

    __slots__ = ("_span",)

    def __init__(self, span: Optional[Span]):
        self._span = span

    def __enter__(self):
        if self._span is not None:
            _stack().append(self._span)
        return self._span

    def __exit__(self, etype, evalue, tb):
        if self._span is not None:
            st = _stack()
            if st and st[-1] is self._span:
                st.pop()
            finish(self._span,
                   status=(f"error:{etype.__name__}" if etype else None))
        return False


def span(name: str, kind: str = "internal", parent: Any = "auto",
         attrs: Optional[dict] = None) -> _SpanScope:
    """`with tracing.span("apply", attrs=...)` — children started in the
    body (this thread) parent under it. No-op scope when tracing is off."""
    return _SpanScope(begin(name, kind, parent, attrs))


class _AttachScope:
    __slots__ = ("_ctx",)

    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        if self._ctx is not None:
            _stack().append(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        if self._ctx is not None:
            st = _stack()
            if st and st[-1] is self._ctx:
                st.pop()
        return False


def attach(ctx: Optional[Tuple[str, str]]) -> _AttachScope:
    """Re-bind a captured (trace_id, span_id) context in this thread —
    worker-pool threads are not the caller's thread."""
    return _AttachScope(_Ctx(*ctx) if ctx is not None else None)


def bound(fn: Callable) -> Callable:
    """Capture the CALLER's current context now; return a wrapper that
    re-binds it where the pool runs fn. Identity when tracing is off or
    no context is active (zero overhead on the R=1 hot path)."""
    if not enabled():
        return fn
    ctx = current_ctx()
    if ctx is None:
        return fn

    def wrapper(*a, **kw):
        with attach(ctx):
            return fn(*a, **kw)

    return wrapper


def child_span(name: str, ctx: Optional[Tuple[str, str]],
               attrs: Optional[dict] = None,
               kind: str = "internal") -> _SpanScope:
    """Open a span parented under a CAPTURED (trace_id, span_id) context
    from any thread — the async checkpoint writer records its
    `checkpoint_write` span under the step's `checkpoint_save` span this
    way, even though the write runs later on the writer thread. A None
    context roots a fresh trace; tracing off = no-op scope."""
    if not enabled():
        return _SpanScope(None)
    return _SpanScope(begin(name, kind=kind, parent=ctx, attrs=attrs))


def annotate(**attrs) -> None:
    """Set attributes on the innermost active SPAN (contexts re-bound
    from another thread are skipped — they are not ours to mutate)."""
    c = current()
    if isinstance(c, Span):
        c.attrs.update(attrs)


# ---------------------------------------------------------------------------
# wire format (W3C traceparent)
# ---------------------------------------------------------------------------


def header_for(span: Optional[Span]) -> Optional[str]:
    if span is None:
        return None
    return f"00-{span.trace_id}-{span.span_id}-01"


def parse_header(header) -> Optional[Tuple[str, str]]:
    if not isinstance(header, str):
        return None
    parts = header.split("-")
    if len(parts) != 4 or not parts[1] or not parts[2]:
        return None
    return parts[1], parts[2]


def server_span(name: str, header, attrs: Optional[dict] = None,
                kind: str = "server") -> _SpanScope:
    """Reopen a propagated context server-side around verb handling.
    With no header (client untraced) the server still roots a local
    trace; tracing off = no-op scope either way."""
    if not enabled():
        return _SpanScope(None)
    ctx = parse_header(header)
    return _SpanScope(begin(name, kind=kind, parent=ctx, attrs=attrs))


# ---------------------------------------------------------------------------
# executor step join
# ---------------------------------------------------------------------------


class _StepScope(_SpanScope):
    def __enter__(self):
        sp = super().__enter__()
        if sp is not None:
            global _last_step_ctx
            _last_step_ctx = (sp.trace_id, sp.span_id)
        return sp


def step_span(attrs: Optional[dict] = None) -> _StepScope:
    """Root span for one Executor.run step; publishes its context as the
    process's "latest step" (heartbeat stamps, checkpoint-save joins,
    straggler episode citations)."""
    return _StepScope(begin("step", kind="step", parent=None, attrs=attrs))


def last_step_trace_id() -> Optional[str]:
    return _last_step_ctx[0] if _last_step_ctx is not None else None


def last_step_ctx() -> Optional[Tuple[str, str]]:
    return _last_step_ctx


# ---------------------------------------------------------------------------
# read side: ring, tracez, export batches
# ---------------------------------------------------------------------------


def finished_spans() -> List[dict]:
    with _lock:
        return list(_ring)


def export_batch(after_seq: int) -> Tuple[List[dict], int]:
    """Spans with seq > after_seq (the push exporter's drain cursor) and
    the new cursor. Ring eviction bounds what a slow collector can ever
    replay — bounded memory, bounded loss."""
    with _lock:
        out = [s for s in _ring if s.get("seq", 0) > after_seq]
    return out, (out[-1]["seq"] if out else after_seq)


def tracez(limit: int = 50) -> dict:
    """Recent traces, slowest-first: per trace the root name, total
    duration, and every hop with its own duration — the debugz /tracez
    payload."""
    spans = finished_spans()
    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)
    traces = []
    for tid, ss in by_trace.items():
        ss.sort(key=lambda s: s["ts"])
        ids = {s["span"] for s in ss}
        roots = [s for s in ss if not s.get("parent")
                 or s["parent"] not in ids]
        t_begin = min(s["ts"] for s in ss)
        t_end = max(s["ts"] + s["dur_ms"] / 1e3 for s in ss)
        traces.append({
            "trace": tid,
            "root": (roots[0]["name"] if roots else ss[0]["name"]),
            "dur_ms": round((t_end - t_begin) * 1e3, 3),
            "n_spans": len(ss),
            "spans": [{k: s.get(k) for k in
                       ("span", "parent", "name", "kind", "proc",
                        "dur_ms", "status", "attrs")} for s in ss],
        })
    traces.sort(key=lambda t: -t["dur_ms"])
    return {"process": process_tag(), "enabled": enabled(),
            "traces": traces[:limit]}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def note_request(rec: dict) -> None:
    """Record one per-request serving flight record (retired generation:
    trace id, outcome, tokens, queue/ttft/total ms, preempts...). Kept in
    a bounded deque and included in flight_dump payloads under
    "requests". No-op when tracing is off."""
    if not enabled():
        return
    with _lock:
        _REQ_RECORDS.append(dict(rec))


def request_records() -> List[dict]:
    with _lock:
        return list(_REQ_RECORDS)


def _recent_steps() -> List[dict]:
    try:
        from ..fluid import monitor

        return monitor.recent_steps()
    except Exception:  # noqa: BLE001 — pservers have no executor
        return []


def _atomic_write(path: str, blob: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def flight_dump(reason: str, directory: Optional[str] = None,
                tag: Optional[str] = None) -> Optional[str]:
    """Dump the span ring + recent step records atomically to
    `<PADDLE_TRACE_DIR>/flightrec.<tag>.json`. One dump per reason per
    process; a later trigger REWRITES the same file with a fresher span
    ring and `reasons` accumulates every trigger so far (a BadStepError
    followed by the atexit dump reads ["bad_step", "exit"]). No-op
    (None) when tracing is off or no directory is configured."""
    if not enabled():
        return None
    directory = directory or os.environ.get(ENV_DIR)
    if not directory:
        return None
    with _lock:
        if reason in _dumped_reasons:
            return None
        _dumped_reasons.add(reason)
        reasons = sorted(_dumped_reasons)
    tag = tag or process_tag()
    payload = {
        "format": 1,
        "process": tag,
        "pid": os.getpid(),
        "reason": reason,
        "reasons": reasons,
        "ts": round(time.time(), 6),
        "spans": finished_spans(),
        "steps": _recent_steps(),
        "requests": request_records(),
    }
    path = os.path.join(directory, f"flightrec.{tag}.json")
    try:
        os.makedirs(directory, exist_ok=True)
        _atomic_write(path, json.dumps(payload).encode())
    except OSError:
        return None  # a full disk must not mask the original failure
    return path


def to_chrome_events(spans: List[dict]) -> List[dict]:
    """Finished spans as chrome-trace complete events (host pid 0, one
    tid lane per originating thread) — the per-process file
    telemetry.timeline merges next to the jax profiler dumps."""
    events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": 0,
        "args": {"name": f"spans ({process_tag()})"},
    }]
    for s in spans:
        ev = {
            "name": s["name"], "cat": s.get("kind", "span"), "ph": "X",
            "pid": 0, "tid": s.get("tid", 0),
            "ts": s["ts"] * 1e6, "dur": max(s["dur_ms"], 1e-3) * 1e3,
            "args": {"trace": s["trace"], "span": s["span"],
                     "status": s.get("status", "ok"),
                     **(s.get("attrs") or {})},
        }
        if s.get("parent"):
            ev["args"]["parent"] = s["parent"]
        events.append(ev)
    return events


def dump_chrome(directory: Optional[str] = None,
                tag: Optional[str] = None) -> Optional[str]:
    """Write this process's spans as `trace.<tag>.json` chrome trace in
    PADDLE_TRACE_DIR, so the launcher's timeline merge shows pserver and
    coordinator lanes next to the trainer ranks'."""
    if not enabled():
        return None
    directory = directory or os.environ.get(ENV_DIR)
    if not directory:
        return None
    tag = tag or process_tag()
    path = os.path.join(directory, f"trace.{tag}.json")
    try:
        os.makedirs(directory, exist_ok=True)
        _atomic_write(path, json.dumps(
            {"traceEvents": to_chrome_events(finished_spans()),
             "displayTimeUnit": "ms"}).encode())
    except OSError:
        return None
    return path


def shutdown_dump(tag: Optional[str] = None) -> None:
    """Clean-exit dump: flight record + chrome spans (idempotent per
    reason). Called from server teardown paths and the atexit hook."""
    flight_dump("exit", tag=tag)
    dump_chrome(tag=tag)


def maybe_install_hooks() -> None:
    """Arm the flight-recorder triggers once per process: SIGTERM
    (chained — the checkpoint preemption handler and launcher grace
    protocol keep working), unhandled-exception hook, and atexit. Safe
    to call from any thread (signal install silently skipped off the
    main thread) and a no-op when tracing is off."""
    global _hooks_installed
    if not enabled() or _hooks_installed:
        return
    with _lock:
        if _hooks_installed:
            return
        _hooks_installed = True

    import atexit
    import signal
    import sys

    atexit.register(shutdown_dump)

    prev_hook = sys.excepthook

    def _excepthook(etype, evalue, tb):
        flight_dump(f"crash:{etype.__name__}")
        dump_chrome()
        prev_hook(etype, evalue, tb)

    sys.excepthook = _excepthook

    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _handler(sig, frame):
            flight_dump("sigterm")
            dump_chrome()
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(sig, frame)
            else:
                # restore the default disposition and re-deliver so the
                # process still dies with the conventional 143
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _handler)
    except ValueError:  # not the main thread: atexit/excepthook remain
        pass


def _reset_for_tests() -> None:
    """Drop the ring, cursors and the cached gate (unit tests re-arm
    with monkeypatched env)."""
    global _enabled, _seq, _last_step_ctx, _hooks_installed
    with _lock:
        _ring.clear()
        _dumped_reasons.clear()
        _REQ_RECORDS.clear()
        _seq = 0
    _enabled = None
    _last_step_ctx = None
    _tls.stack = []
