"""Unified telemetry (ISSUE 4): metrics registry + JSONL sink +
distributed timeline + straggler detection.

Layering:

  telemetry.registry   process-wide counters/gauges/histograms with a
                       Prometheus text exposition (scrape or dump)
  telemetry.sink       per-step JSONL records (PADDLE_METRICS_PATH)
  telemetry.timeline   merge per-rank chrome traces (launcher)
  telemetry.straggler  per-rank step-rate comparison (launcher)
  fluid/monitor.py     the executor-facing step-time breakdown built on
                       the registry + sink

Everything here is dependency-free (stdlib only) so the pserver and
launcher processes can import it without pulling jax.
"""
from __future__ import annotations

from . import sink, straggler, timeline  # noqa: F401
from .registry import (  # noqa: F401
    BYTE_BUCKETS,
    DEFAULT_MS_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from .sink import emit, enabled  # noqa: F401


def to_prometheus() -> str:
    """One-call text exposition of the process registry (serve it from
    any HTTP handler, or dump to a file for node-exporter's textfile
    collector)."""
    return get_registry().to_prometheus()


def snapshot() -> dict:
    """JSON-ready dump of the process registry."""
    return get_registry().snapshot()
