"""Unified telemetry (ISSUE 4 + ISSUE 6): metrics registry + JSONL sink
+ distributed timeline + straggler detection + per-op device-time
attribution + live introspection.

Layering:

  telemetry.registry   process-wide counters/gauges/histograms with a
                       Prometheus text exposition (scrape or dump)
  telemetry.sink       per-step JSONL records (PADDLE_METRICS_PATH)
  telemetry.timeline   merge per-rank chrome traces (launcher)
  telemetry.straggler  per-rank step-rate comparison (launcher)
  telemetry.cost       per-op device-time attribution: xplane events
                       joined back to Program IR ops via FLAGS_op_profile
                       named scopes; CostReport + measured-MFU gauge
  telemetry.memory     per-op / per-variable HBM attribution: the static
                       live-range pass (fluid/analysis/liverange.py)
                       joined against XLA's buffer assignment; /memz,
                       the OOM doctor, memtop (FLAGS_mem_profile,
                       PADDLE_HBM_BUDGET_BYTES)
  telemetry.debugz     introspection HTTP server (PADDLE_DEBUGZ_PORT):
                       /metrics /statusz /steps /proftop /memz /healthz
  telemetry.export     periodic push exporter (PADDLE_METRICS_PUSH_URL):
                       OTLP-shaped snapshot() JSON or pushgateway text;
                       span batches too (PADDLE_TRACES_PUSH_URL)
  telemetry.tracing    causal span propagation across the RPC plane
                       (PADDLE_TRACING): trace_id/span_id per hop,
                       bounded span ring, flight recorder, /tracez
  telemetry.numerics   training numerics: in-graph tensor stats
                       (FLAGS_tensor_stats -> numstat__* vars sampled
                       every PADDLE_NUMERICS_EVERY steps), the
                       NaN-provenance doctor (numrec dumps behind
                       BadStepError), cross-replica SDC fingerprints
                       (PADDLE_SDC_CHECK_EVERY via the coordinator),
                       /numericz, tools/numtop.py
  telemetry.goodput    job-lifetime goodput/badput ledger: every rank
                       wall-clock second classified into buckets
                       (PADDLE_GOODPUT), per-incarnation JSONL files
                       restarts stitch across, bounded fleet payloads
                       on lease renewals (PADDLE_FLEET_METRICS), the
                       coordinator-side merge behind debugz /fleetz,
                       tools/goodtop.py
  fluid/monitor.py     the executor-facing step-time breakdown built on
                       the registry + sink

Module tops are dependency-free (stdlib only) so the pserver and
launcher processes can import the package without pulling jax; cost.py
imports jax/protobuf inside functions for the same reason.
"""
from __future__ import annotations

from . import (  # noqa: F401
    cost,
    debugz,
    export,
    goodput,
    memory,
    numerics,
    sink,
    straggler,
    timeline,
    tracing,
)
from .registry import (  # noqa: F401
    BYTE_BUCKETS,
    DEFAULT_MS_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from .sink import emit, enabled  # noqa: F401


def to_prometheus() -> str:
    """One-call text exposition of the process registry (serve it from
    any HTTP handler, or dump to a file for node-exporter's textfile
    collector)."""
    return get_registry().to_prometheus()


def snapshot() -> dict:
    """JSON-ready dump of the process registry."""
    return get_registry().snapshot()
