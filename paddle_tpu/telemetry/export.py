"""Metrics push exporter (ISSUE 6 satellite; closes the ROADMAP OTLP/
pushgateway follow-on).

Periodically POSTs the process registry to PADDLE_METRICS_PUSH_URL:

  * JSON mode (default): the registry's snapshot() — already OTLP-shaped
    ({name: {type, series: [{labels, value|summary}]}}) — wrapped with a
    resource block (rank/pid/job), for OTLP-ish JSON collectors.
  * Prometheus mode: the text exposition, for a Prometheus pushgateway.
    Selected when the URL contains "/metrics/job" (the pushgateway path
    convention) or PADDLE_METRICS_PUSH_FORMAT=prom; pushgateway merges
    by job/instance labels in the URL, so the caller encodes those.

Delivery contract: one POST per interval (PADDLE_METRICS_PUSH_SECS,
default 15s), bounded retry on failure — PADDLE_METRICS_PUSH_RETRIES
attempts (default 3) with exponential backoff + jitter — then the
sample is DROPPED and counted (metrics_push_failures_total); the next
interval pushes fresh state, so a dead collector costs bounded work and
zero unbounded queueing. Flag-off (env unset) = zero network, zero
threads, one env read per process.

Span batches (ISSUE 9): PADDLE_TRACES_PUSH_URL arms a SECOND exporter
instance pushing OTLP-trace-shaped JSON (resourceSpans/scopeSpans with
traceId/spanId/parentSpanId and unix-nano timestamps) drained from the
tracing ring since the last successful cursor — same bounded-retry
sender, same drop-and-count contract (PADDLE_TRACES_PUSH_SECS /
_RETRIES). Env unset = zero network; tracing off = the batch is always
empty and no POST is issued.

stdlib-only (urllib) by design: the pserver and launcher can push too.
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Optional

from .registry import get_registry

ENV_URL = "PADDLE_METRICS_PUSH_URL"
ENV_SECS = "PADDLE_METRICS_PUSH_SECS"
ENV_RETRIES = "PADDLE_METRICS_PUSH_RETRIES"
ENV_FORMAT = "PADDLE_METRICS_PUSH_FORMAT"

ENV_TRACES_URL = "PADDLE_TRACES_PUSH_URL"
ENV_TRACES_SECS = "PADDLE_TRACES_PUSH_SECS"
ENV_TRACES_RETRIES = "PADDLE_TRACES_PUSH_RETRIES"

_exporter: Optional["PushExporter"] = None
_checked = False
_trace_exporter: Optional["PushExporter"] = None
_trace_checked = False
_lock = threading.Lock()


class PushExporter:
    """Daemon-thread periodic pusher. start() is idempotent; flush()
    pushes one sample synchronously (tests and atexit-style final
    pushes). body_fn overrides the payload builder (the span exporter
    plugs its OTLP-trace batches in; returning None skips the POST —
    nothing new to ship this interval)."""

    def __init__(self, url: str, interval_s: float = 15.0,
                 retries: int = 3, fmt: Optional[str] = None,
                 timeout_s: float = 5.0, backoff_s: float = 0.2,
                 body_fn=None, counter_prefix: str = "metrics"):
        self.url = url
        self.interval_s = max(0.05, float(interval_s))
        self.retries = max(1, int(retries))
        self.timeout_s = timeout_s
        self.backoff_s = backoff_s
        if fmt is None:
            fmt = "prom" if "/metrics/job" in url else "json"
        self.fmt = fmt
        self.body_fn = body_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = get_registry()
        self._pushed = reg.counter(
            f"{counter_prefix}_push_total",
            f"successful {counter_prefix} pushes")
        self._failed = reg.counter(
            f"{counter_prefix}_push_failures_total",
            f"{counter_prefix} samples dropped after the bounded "
            f"retry budget")

    # -- payload ---------------------------------------------------------
    def _body(self):
        if self.body_fn is not None:
            return self.body_fn()
        if self.fmt == "prom":
            return (get_registry().to_prometheus().encode(),
                    "text/plain; version=0.0.4; charset=utf-8")
        payload = {
            "resource": {
                "job": os.environ.get("PADDLE_JOB_NAME", "paddle_tpu"),
                "rank": os.environ.get("PADDLE_TRAINER_ID"),
                "role": os.environ.get("PADDLE_TRAINING_ROLE"),
                "pid": os.getpid(),
            },
            "ts": round(time.time(), 6),
            "metrics": get_registry().snapshot(),
        }
        return json.dumps(payload).encode(), "application/json"

    # -- delivery --------------------------------------------------------
    def _post_once(self, body: bytes, ctype: str) -> None:
        import urllib.request

        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": ctype})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            resp.read()

    def flush(self) -> bool:
        """Push one sample now; True on delivery, False when the retry
        budget is exhausted (the sample is dropped and counted). A
        body_fn returning None means nothing to ship — no POST, still
        True."""
        built = self._body()
        if built is None:
            return True
        body, ctype = built
        for attempt in range(self.retries):
            try:
                self._post_once(body, ctype)
                self._pushed.inc()
                return True
            except Exception:  # noqa: BLE001 — collector down/unreachable
                if attempt + 1 >= self.retries:
                    break
                # exp backoff + jitter: a fleet of ranks whose collector
                # hiccuped must not retry in lockstep
                delay = self.backoff_s * (2 ** attempt)
                self._stop.wait(delay * (0.5 + random.random()))
                if self._stop.is_set():
                    break
        self._failed.inc()
        return False

    # -- lifecycle -------------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.flush()

    def start(self) -> "PushExporter":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="paddle-tpu-metrics-push")
            self._thread.start()
        return self

    def stop(self, final_flush: bool = False):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if final_flush:
            self.flush()


def start(url: str, **kwargs) -> PushExporter:
    """Explicit start (programmatic alternative to the env contract)."""
    global _exporter, _checked
    with _lock:
        if _exporter is not None:
            _exporter.stop()
        _exporter = PushExporter(url, **kwargs).start()
        _checked = True
        return _exporter


def maybe_start() -> Optional[PushExporter]:
    """Arm from PADDLE_METRICS_PUSH_URL; resolved once per process.
    Unset = None and never another env read."""
    global _exporter, _checked
    if _checked:
        return _exporter
    with _lock:
        if _checked:
            return _exporter
        _checked = True
        url = os.environ.get(ENV_URL)
        if not url:
            return None
        _exporter = PushExporter(
            url,
            interval_s=float(os.environ.get(ENV_SECS, "15") or 15),
            retries=int(os.environ.get(ENV_RETRIES, "3") or 3),
            fmt=(os.environ.get(ENV_FORMAT) or None),
        ).start()
        return _exporter


def active() -> Optional[PushExporter]:
    return _exporter


# ---------------------------------------------------------------------------
# span batches (ISSUE 9)
# ---------------------------------------------------------------------------


def spans_to_otlp(spans, resource: Optional[dict] = None) -> dict:
    """Ring-format span dicts -> OTLP/JSON trace shape (resourceSpans /
    scopeSpans; ids hex, times unix-nano, attrs as key/value pairs) —
    what an OTLP-JSON collector ingests."""
    def attr(k, v):
        if isinstance(v, bool):
            return {"key": k, "value": {"boolValue": v}}
        if isinstance(v, int):
            return {"key": k, "value": {"intValue": str(v)}}
        if isinstance(v, float):
            return {"key": k, "value": {"doubleValue": v}}
        return {"key": k, "value": {"stringValue": str(v)}}

    res = {
        "job": os.environ.get("PADDLE_JOB_NAME", "paddle_tpu"),
        "rank": os.environ.get("PADDLE_TRAINER_ID"),
        "role": os.environ.get("PADDLE_TRAINING_ROLE"),
        "pid": os.getpid(),
    }
    res.update(resource or {})
    otlp_spans = []
    for s in spans:
        start_ns = int(s["ts"] * 1e9)
        span = {
            "traceId": s["trace"],
            "spanId": s["span"],
            "name": s["name"],
            "kind": s.get("kind", "internal"),
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(start_ns + int(s["dur_ms"] * 1e6)),
            "attributes": [attr(k, v)
                           for k, v in (s.get("attrs") or {}).items()],
            "status": {"code": ("STATUS_CODE_OK"
                                if s.get("status", "ok") == "ok"
                                else "STATUS_CODE_ERROR"),
                       "message": s.get("status", "ok")},
        }
        if s.get("parent"):
            span["parentSpanId"] = s["parent"]
        otlp_spans.append(span)
    return {
        "resourceSpans": [{
            "resource": {"attributes": [attr(k, v) for k, v in res.items()
                                        if v is not None]},
            "scopeSpans": [{
                "scope": {"name": "paddle_tpu.telemetry.tracing"},
                "spans": otlp_spans,
            }],
        }],
    }


def _traces_body_fn():
    """Stateful payload builder: drains spans recorded since the last
    build. The cursor advances at BUILD time — a batch the retry budget
    then drops is gone (bounded loss, matching the metrics contract)."""
    state = {"seq": 0}

    def body():
        from . import tracing

        spans, state["seq"] = tracing.export_batch(state["seq"])
        if not spans:
            return None  # nothing new: skip the POST entirely
        return (json.dumps(spans_to_otlp(spans)).encode(),
                "application/json")

    return body


def start_traces(url: str, **kwargs) -> PushExporter:
    """Explicit span-exporter start (tests / programmatic)."""
    global _trace_exporter, _trace_checked
    with _lock:
        if _trace_exporter is not None:
            _trace_exporter.stop()
        _trace_exporter = PushExporter(
            url, body_fn=_traces_body_fn(), counter_prefix="traces",
            **kwargs).start()
        _trace_checked = True
        return _trace_exporter


def maybe_start_traces() -> Optional[PushExporter]:
    """Arm span pushing from PADDLE_TRACES_PUSH_URL; resolved once per
    process. Unset = None, zero network, and never another env read."""
    global _trace_exporter, _trace_checked
    if _trace_checked:
        return _trace_exporter
    with _lock:
        if _trace_checked:
            return _trace_exporter
        _trace_checked = True
        url = os.environ.get(ENV_TRACES_URL)
        if not url:
            return None
        _trace_exporter = PushExporter(
            url,
            interval_s=float(os.environ.get(ENV_TRACES_SECS, "15") or 15),
            retries=int(os.environ.get(ENV_TRACES_RETRIES, "3") or 3),
            body_fn=_traces_body_fn(), counter_prefix="traces",
        ).start()
        return _trace_exporter


def active_traces() -> Optional[PushExporter]:
    return _trace_exporter


# ---------------------------------------------------------------------------
# fleet push (ISSUE 15 satellite)
# ---------------------------------------------------------------------------


_fleet_exporter: Optional[PushExporter] = None


def _fleet_body_fn(status_fn, metrics_fn=None):
    """Payload builder for the launcher-side fleet exporter: ONE
    aggregated snapshot — the coordinator's merged fleet rollup plus
    (optionally) the fleet Prometheus text — instead of N per-rank
    POSTs."""

    def body():
        fleet = status_fn()
        if not fleet or not fleet.get("ranks"):
            return None  # nothing renewed yet: skip the POST
        payload = {
            "resource": {
                "job": os.environ.get("PADDLE_JOB_NAME", "paddle_tpu"),
                "role": "launcher",
                "pid": os.getpid(),
            },
            "ts": round(time.time(), 6),
            "fleet": fleet,
        }
        if metrics_fn is not None:
            try:
                payload["exposition"] = metrics_fn()
            except Exception:  # noqa: BLE001 — rollup still ships
                pass
        return json.dumps(payload, default=str).encode(), "application/json"

    return body


def start_fleet(url: str, status_fn, metrics_fn=None,
                **kwargs) -> PushExporter:
    """Launcher-side aggregated push: when PADDLE_METRICS_PUSH_URL is
    set fleet-wide, launch.py calls this with the coordinator's
    fleet_status/fleet_metrics and STRIPS the env from the children —
    one coordinator POST per interval replaces N per-rank pushes
    (per-rank mode is unchanged when fleet aggregation is not armed;
    env unset = zero network, as today)."""
    global _fleet_exporter
    with _lock:
        if _fleet_exporter is not None:
            _fleet_exporter.stop()
        _fleet_exporter = PushExporter(
            url, body_fn=_fleet_body_fn(status_fn, metrics_fn),
            counter_prefix="fleet_metrics", **kwargs).start()
        return _fleet_exporter


def active_fleet() -> Optional[PushExporter]:
    return _fleet_exporter


def stop():
    """Tests: tear down and allow re-arming (all exporters)."""
    global _exporter, _checked, _trace_exporter, _trace_checked
    global _fleet_exporter
    with _lock:
        if _exporter is not None:
            _exporter.stop()
        _exporter = None
        _checked = False
        if _trace_exporter is not None:
            _trace_exporter.stop()
        _trace_exporter = None
        _trace_checked = False
        if _fleet_exporter is not None:
            _fleet_exporter.stop()
        _fleet_exporter = None
