"""Metrics push exporter (ISSUE 6 satellite; closes the ROADMAP OTLP/
pushgateway follow-on).

Periodically POSTs the process registry to PADDLE_METRICS_PUSH_URL:

  * JSON mode (default): the registry's snapshot() — already OTLP-shaped
    ({name: {type, series: [{labels, value|summary}]}}) — wrapped with a
    resource block (rank/pid/job), for OTLP-ish JSON collectors.
  * Prometheus mode: the text exposition, for a Prometheus pushgateway.
    Selected when the URL contains "/metrics/job" (the pushgateway path
    convention) or PADDLE_METRICS_PUSH_FORMAT=prom; pushgateway merges
    by job/instance labels in the URL, so the caller encodes those.

Delivery contract: one POST per interval (PADDLE_METRICS_PUSH_SECS,
default 15s), bounded retry on failure — PADDLE_METRICS_PUSH_RETRIES
attempts (default 3) with exponential backoff + jitter — then the
sample is DROPPED and counted (metrics_push_failures_total); the next
interval pushes fresh state, so a dead collector costs bounded work and
zero unbounded queueing. Flag-off (env unset) = zero network, zero
threads, one env read per process.

stdlib-only (urllib) by design: the pserver and launcher can push too.
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Optional

from .registry import get_registry

ENV_URL = "PADDLE_METRICS_PUSH_URL"
ENV_SECS = "PADDLE_METRICS_PUSH_SECS"
ENV_RETRIES = "PADDLE_METRICS_PUSH_RETRIES"
ENV_FORMAT = "PADDLE_METRICS_PUSH_FORMAT"

_exporter: Optional["PushExporter"] = None
_checked = False
_lock = threading.Lock()


class PushExporter:
    """Daemon-thread periodic pusher. start() is idempotent; flush()
    pushes one sample synchronously (tests and atexit-style final
    pushes)."""

    def __init__(self, url: str, interval_s: float = 15.0,
                 retries: int = 3, fmt: Optional[str] = None,
                 timeout_s: float = 5.0, backoff_s: float = 0.2):
        self.url = url
        self.interval_s = max(0.05, float(interval_s))
        self.retries = max(1, int(retries))
        self.timeout_s = timeout_s
        self.backoff_s = backoff_s
        if fmt is None:
            fmt = "prom" if "/metrics/job" in url else "json"
        self.fmt = fmt
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = get_registry()
        self._pushed = reg.counter(
            "metrics_push_total", "successful metrics pushes")
        self._failed = reg.counter(
            "metrics_push_failures_total",
            "metrics samples dropped after the bounded retry budget")

    # -- payload ---------------------------------------------------------
    def _body(self):
        if self.fmt == "prom":
            return (get_registry().to_prometheus().encode(),
                    "text/plain; version=0.0.4; charset=utf-8")
        payload = {
            "resource": {
                "job": os.environ.get("PADDLE_JOB_NAME", "paddle_tpu"),
                "rank": os.environ.get("PADDLE_TRAINER_ID"),
                "role": os.environ.get("PADDLE_TRAINING_ROLE"),
                "pid": os.getpid(),
            },
            "ts": round(time.time(), 6),
            "metrics": get_registry().snapshot(),
        }
        return json.dumps(payload).encode(), "application/json"

    # -- delivery --------------------------------------------------------
    def _post_once(self, body: bytes, ctype: str) -> None:
        import urllib.request

        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": ctype})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            resp.read()

    def flush(self) -> bool:
        """Push one sample now; True on delivery, False when the retry
        budget is exhausted (the sample is dropped and counted)."""
        body, ctype = self._body()
        for attempt in range(self.retries):
            try:
                self._post_once(body, ctype)
                self._pushed.inc()
                return True
            except Exception:  # noqa: BLE001 — collector down/unreachable
                if attempt + 1 >= self.retries:
                    break
                # exp backoff + jitter: a fleet of ranks whose collector
                # hiccuped must not retry in lockstep
                delay = self.backoff_s * (2 ** attempt)
                self._stop.wait(delay * (0.5 + random.random()))
                if self._stop.is_set():
                    break
        self._failed.inc()
        return False

    # -- lifecycle -------------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.flush()

    def start(self) -> "PushExporter":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="paddle-tpu-metrics-push")
            self._thread.start()
        return self

    def stop(self, final_flush: bool = False):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if final_flush:
            self.flush()


def start(url: str, **kwargs) -> PushExporter:
    """Explicit start (programmatic alternative to the env contract)."""
    global _exporter, _checked
    with _lock:
        if _exporter is not None:
            _exporter.stop()
        _exporter = PushExporter(url, **kwargs).start()
        _checked = True
        return _exporter


def maybe_start() -> Optional[PushExporter]:
    """Arm from PADDLE_METRICS_PUSH_URL; resolved once per process.
    Unset = None and never another env read."""
    global _exporter, _checked
    if _checked:
        return _exporter
    with _lock:
        if _checked:
            return _exporter
        _checked = True
        url = os.environ.get(ENV_URL)
        if not url:
            return None
        _exporter = PushExporter(
            url,
            interval_s=float(os.environ.get(ENV_SECS, "15") or 15),
            retries=int(os.environ.get(ENV_RETRIES, "3") or 3),
            fmt=(os.environ.get(ENV_FORMAT) or None),
        ).start()
        return _exporter


def active() -> Optional[PushExporter]:
    return _exporter


def stop():
    """Tests: tear down and allow re-arming."""
    global _exporter, _checked
    with _lock:
        if _exporter is not None:
            _exporter.stop()
        _exporter = None
        _checked = False
