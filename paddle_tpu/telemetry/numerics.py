"""Training numerics observability (ISSUE 12).

The observability stack answers "where did the TIME go" (cost.py) and
"where did the MEMORY go" (memory.py); this module answers "where did
the NUMBERS go" — the question behind every NaN'd run, every AMP
loss-scale collapse, and every silently-corrupted replica:

  1. In-graph tensor stats (FLAGS_tensor_stats): graph construction
     (Optimizer.apply_gradients, fluid/clip.py, the AMP decorator)
     appends one cheap `tensor_stats` reduction per watched variable —
     per-layer gradients, parameters, the gradient-clip global norm —
     writing [nan_count, inf_count, max_abs, l2] into persistable
     `numstat__*` vars that ride the step's state outputs. XLA fuses
     the reductions into the step program; the host reads them every
     PADDLE_NUMERICS_EVERY steps (the only per-step cost is that
     sampled device->host copy) and publishes kind="numerics" sink
     records, numerics_* gauges, and a bounded in-process history ring
     (the per-layer grad-norm series the doctor and /numericz serve).
  2. NaN-provenance doctor: when the FLAGS_check_numerics bad-step
     guard fires, the Executor hands the un-committed step here; the
     doctor re-runs the SAME ops eagerly (same feed, same scope state,
     same RNG key) with a per-op finiteness probe and bisects to the
     FIRST op that produced a non-finite value — naming its IR op
     index, type, the PR-5 user-layer callstack, its operand stats and
     the grad-norm history leading in. The report dumps atomically to
     PADDLE_TRACE_DIR/numrec.<tag>.json (the PR-9 flight-recorder
     path; no PADDLE_TRACING needed) and rides the BadStepError.
  3. Cross-replica SDC detection: every PADDLE_SDC_CHECK_EVERY steps
     each dp rank publishes a params+merged-grad fingerprint (l2 norm
     + crc32 checksum) to the job coordinator (`numerics_report` verb
     over the PS RPC transport). Replicated dp state must be
     BIT-identical across ranks, so a checksum mismatch is a silent
     data corruption: the coordinator emits a structured `divergence`
     event naming the odd-rank-out (majority vote; with two ranks the
     publisher's self-consistency bit arbitrates), every rank that
     sees the verdict dumps its flight record, and PADDLE_SDC_EVICT=1
     routes the corrupted rank to the elastic eviction path. Drilled
     deterministically with the `bitflip:<phase>:<nth>` fault rule
     (distributed/faults.py), which flips one bit of one gradient
     value on one tagged rank.

Cost contract (the established flag-off bar): FLAGS_tensor_stats unset
means NO stat vars or ops are built (programs are bit-identical to a
build without this module — asserted by test), the flag rides the
Executor compile-cache key, and the step path pays one flag read plus
one attribute read. SDC publishing is off unless PADDLE_SDC_CHECK_EVERY
is set AND the coordinator endpoint is armed; the doctor only ever runs
on the failure path (opt out: PADDLE_NUMERICS_DOCTOR=0).

Everything heavier than stdlib+numpy (jax, fluid) is imported inside
functions: the coordinator/launcher import this module without an
accelerator runtime (the FingerprintTable is stdlib-only).
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .registry import get_registry

STAT_PREFIX = "numstat__"

ENV_EVERY = "PADDLE_NUMERICS_EVERY"
ENV_HISTORY = "PADDLE_NUMERICS_HISTORY"
ENV_DOCTOR = "PADDLE_NUMERICS_DOCTOR"
ENV_SDC_EVERY = "PADDLE_SDC_CHECK_EVERY"
ENV_SDC_EVICT = "PADDLE_SDC_EVICT"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default) or default)
    except ValueError:
        return default


def stats_every() -> int:
    """Sample cadence for the host-side read of the in-graph stat vars
    (default: every step while the flag is armed)."""
    return max(1, _env_int(ENV_EVERY, 1))


def doctor_enabled() -> bool:
    return os.environ.get(ENV_DOCTOR, "1") not in ("0", "false", "")


# ---------------------------------------------------------------------------
# graph-build side: watch installation (FLAGS_tensor_stats)
# ---------------------------------------------------------------------------


def stats_enabled() -> bool:
    from ..fluid.flags import flag

    return bool(flag("FLAGS_tensor_stats"))


def _ensure_persistable(name: str, shape) -> Any:
    """Create the persistable stat var in the CURRENT default main +
    startup programs (the same placement contract as the check_numerics
    guard and the AMP scaling state — callers run under program_guard)."""
    from ..fluid import framework
    from ..fluid.initializer import ConstantInitializer

    main_block = framework.default_main_program().global_block()
    v = main_block.create_var(name=name, shape=tuple(shape),
                              dtype="float32", persistable=True,
                              stop_gradient=True)
    sblock = framework.default_startup_program().global_block()
    sv = sblock.create_var(name=name, shape=tuple(shape),
                           dtype="float32", persistable=True)
    ConstantInitializer(0.0)(sv, sblock)
    return v


def _register_watch(program, stat_name: str, kind: str, var_name: str,
                    label: str, **extra) -> None:
    watches = program.__dict__.setdefault("_numerics_watch", {})
    watches[stat_name] = dict(kind=kind, var=var_name, label=label,
                              **extra)


def watch_variable(var, kind: str, label: Optional[str] = None):
    """Append a `tensor_stats` reduction over `var` into a persistable
    `numstat__*` var and register it on the program. Returns the stat
    var. Caller context must hold program_guard over var's program."""
    from ..fluid import unique_name

    program = var.block.program
    block = program.global_block()
    stat_name = unique_name.generate(f"{STAT_PREFIX}{kind}")
    stat = _ensure_persistable(stat_name, (4,))
    op = block.append_op(
        type="tensor_stats",
        inputs={"X": [var]},
        outputs={"Out": [stat]},
    )
    # the stat op inherits the watched var's build-site callstack so
    # diagnostics point at the user layer, not at this module
    from ..fluid.framework import OP_CALLSTACK_ATTR

    src = getattr(var, "op", None)
    if src is not None and src.attrs.get(OP_CALLSTACK_ATTR):
        op.attrs[OP_CALLSTACK_ATTR] = src.attrs[OP_CALLSTACK_ATTR]
    _register_watch(program, stat_name, kind, var.name,
                    label or var.name)
    return stat


def install_grad_stats(params_grads) -> None:
    """FLAGS_tensor_stats hook in Optimizer.apply_gradients: one stat
    reduction per applied gradient (labeled by its parameter — the
    per-LAYER series) and one per parameter. Runs after clip +
    regularization so the watched gradient is the one the update op
    actually consumes."""
    if not stats_enabled():
        return
    for p, g in params_grads:
        if g is None:
            continue
        watch_variable(g, "grad", label=p.name)
        watch_variable(p, "param", label=p.name)


def install_global_norm_stat(gnorm_var, clip_norm: float,
                             group: str) -> None:
    """FLAGS_tensor_stats hook in GradientClipByGlobalNorm: persist the
    already-computed global gradient norm instead of discarding it
    (grad_global_norm gauge + clip-trigger accounting at the sample
    cadence)."""
    if not stats_enabled():
        return
    from ..fluid import layers, unique_name

    program = gnorm_var.block.program
    stat_name = unique_name.generate(f"{STAT_PREFIX}clip_gnorm")
    stat = _ensure_persistable(stat_name, (1,))
    layers.assign(gnorm_var, stat)
    _register_watch(program, stat_name, "clip_gnorm", gnorm_var.name,
                    f"global_norm@{group}", clip_norm=float(clip_norm))


# AMP dynamic loss scaling: the scale var already exists (flag-off
# programs included), so observing it needs no graph change — the
# decorator registers the var names and the step hook reads them.
_amp_states: Dict[str, dict] = {}
_amp_lock = threading.Lock()


def register_amp_scale(scale_name: str, good_name: Optional[str] = None,
                       bad_name: Optional[str] = None) -> None:
    """Called by the AMP decorator when dynamic loss scaling is armed:
    scale growth/backoff becomes countable host-side."""
    with _amp_lock:
        _amp_states[scale_name] = {"good": good_name, "bad": bad_name,
                                   "last": None}


# ---------------------------------------------------------------------------
# host side: sampling, history, step hook
# ---------------------------------------------------------------------------

_history: deque = deque(maxlen=max(8, _env_int(ENV_HISTORY, 128)))
_history_lock = threading.Lock()
_stat_step = 0
_last_sample: Optional[dict] = None
_last_watches: Dict[str, dict] = {}  # roster of the last sampled program


def history() -> List[dict]:
    with _history_lock:
        return list(_history)


def last_sample() -> Optional[dict]:
    return _last_sample


def _sdc_every() -> int:
    return _env_int(ENV_SDC_EVERY, 0)


_exec_reporter = None
_exec_reporter_failed = False


def on_step_commit(program, new_state: Dict[str, Any]) -> None:
    """Called by Executor.run after a step's state is committed to the
    scope. Flag-off AND nothing registered: two attribute reads, no
    allocation (the bit-identity contract). Armed: sample the in-graph
    stat vars every PADDLE_NUMERICS_EVERY steps, count AMP loss-scale
    growth/backoff transitions, and publish the SDC fingerprint every
    PADDLE_SDC_CHECK_EVERY steps."""
    watches = getattr(program, "_numerics_watch", None)
    if not watches and not _amp_states:
        if not _sdc_every():
            return
    global _stat_step
    reg = get_registry()
    if watches and stats_enabled():
        _stat_step += 1
        if _stat_step % stats_every() == 0:
            try:
                _sample_stats(watches, new_state, reg)
            except Exception:  # noqa: BLE001 — diagnostics never fail
                pass           # the step that just trained fine
    if _amp_states:
        try:
            _sample_amp(new_state, reg)
        except Exception:  # noqa: BLE001
            pass
    k = _sdc_every()
    if k:
        try:
            _maybe_publish_fingerprint(new_state, k)
        except Exception:  # noqa: BLE001 — a flapping coordinator must
            pass           # not take the trainer down


def _sample_stats(watches: Dict[str, dict], new_state, reg) -> None:
    import numpy as np

    from . import sink

    sample: Dict[str, dict] = {}
    nonfinite = 0
    max_abs_grad = 0.0
    grad_sq = 0.0
    for stat_name, meta in watches.items():
        v = new_state.get(stat_name)
        if v is None:
            continue
        a = np.asarray(v, dtype=np.float64).reshape(-1)
        if meta["kind"] == "clip_gnorm":
            gn = float(a[0])
            row = {"kind": meta["kind"], "value": gn,
                   "clip_norm": meta.get("clip_norm")}
            reg.gauge("grad_global_norm",
                      help="gradient-clip global norm (sampled)").set(gn)
            if meta.get("clip_norm") and gn > meta["clip_norm"]:
                row["clipped"] = True
                reg.counter(
                    "numerics_clip_triggered_total",
                    help="sampled steps where the global norm exceeded "
                         "clip_norm (clipping actually fired)").inc()
        else:
            row = {"kind": meta["kind"], "nan": int(a[0]),
                   "inf": int(a[1]), "max_abs": float(a[2]),
                   "l2": float(a[3])}
            if row["nan"] or row["inf"]:
                nonfinite += 1
            if meta["kind"] == "grad":
                max_abs_grad = max(max_abs_grad, row["max_abs"])
                grad_sq += row["l2"] ** 2
        sample[meta["label"] if meta["kind"] != "param"
               else f"param:{meta['label']}"] = row
    if not sample:
        return
    global _last_sample, _last_watches
    _last_watches = dict(watches)
    record = {"kind": "numerics", "event": "stats", "step": _stat_step,
              "watch": sample}
    _last_sample = record
    with _history_lock:
        _history.append(record)
    reg.gauge("numerics_nonfinite_watches",
              help="watched tensors holding NaN/Inf at the last sample"
              ).set(nonfinite)
    reg.gauge("numerics_max_abs_grad",
              help="max |g| over watched gradients (sampled)"
              ).set(max_abs_grad)
    reg.gauge("numerics_grad_l2_total",
              help="l2 norm over ALL watched gradients (sampled)"
              ).set(math.sqrt(grad_sq))
    reg.counter("numerics_samples_total",
                help="host-side stat samples taken").inc()
    sink.emit(record)


def _sample_amp(new_state, reg) -> None:
    import numpy as np

    from . import sink
    from ..fluid import monitor

    with _amp_lock:
        items = list(_amp_states.items())
    for scale_name, st in items:
        v = new_state.get(scale_name)
        if v is None:
            continue
        s = float(np.asarray(v).reshape(-1)[0])
        last = st["last"]
        st["last"] = s
        reg.gauge("numerics_amp_loss_scale",
                  help="current AMP dynamic loss scale").set(s)
        if last is None or s == last:
            continue
        change = "growth" if s > last else "backoff"
        reg.counter(f"numerics_amp_scale_{change}s_total",
                    help=f"AMP loss-scale {change} events").inc()
        sink.emit({"kind": "numerics", "event": "amp_scale",
                   "step": monitor.global_step(), "change": change,
                   "old": last, "new": s, "scale_var": scale_name})


def _maybe_publish_fingerprint(new_state, k: int) -> None:
    """Executor-path SDC publishing: every k committed steps fingerprint
    the float state (params + optimizer moments + the merged-grad stat
    vars when FLAGS_tensor_stats is armed) and report it. Lazily builds
    one process-wide reporter; unreachable coordinator disables it for
    the process rather than stalling every k-th step."""
    global _exec_reporter, _exec_reporter_failed
    if _exec_reporter_failed:
        return
    from ..fluid import monitor

    step = monitor.global_step()
    if step % k:
        return
    if _exec_reporter is None:
        rep = SDCReporter()
        if not rep.armed:
            _exec_reporter_failed = True
            return
        _exec_reporter = rep
    _exec_reporter.maybe_report(step, named_arrays=new_state)


# ---------------------------------------------------------------------------
# NaN-provenance doctor
# ---------------------------------------------------------------------------


class _FirstBadFound(Exception):
    """Internal control flow: stop the instrumented replay at the first
    non-finite producer."""


def _array_stats(v) -> Optional[dict]:
    import numpy as np

    try:
        a = np.asarray(v)
    except Exception:  # noqa: BLE001
        return None
    if a.dtype.kind != "f":
        return {"dtype": str(a.dtype), "shape": list(a.shape)}
    finite = np.isfinite(a)
    af = np.where(finite, a, 0.0).astype(np.float64)
    return {
        "dtype": str(a.dtype), "shape": list(a.shape),
        "nan": int(np.isnan(a).sum()), "inf": int(np.isinf(a).sum()),
        "max_abs": float(np.abs(af).max()) if a.size else 0.0,
        "l2": float(np.sqrt(np.square(af).sum())),
    }


def _callstack_json(op) -> Tuple[Optional[list], Optional[list]]:
    """(full callstack as [[file, line, fn], ...], user frame) for an
    op's __op_callstack__ attr."""
    from ..fluid.framework import OP_CALLSTACK_ATTR
    from ..fluid.analysis import user_frame

    cs = op.attrs.get(OP_CALLSTACK_ATTR) if op is not None else None
    if not cs:
        return None, None
    uf = user_frame(cs)
    return [list(f) for f in cs], (list(uf) if uf else None)


def bisect_first_nonfinite(program, feed_arrays: Dict[str, Any],
                           scope) -> Optional[dict]:
    """The instrumented replay: re-run the block's ops EAGERLY (outside
    jit) from the exact pre-step state — same feeds, same scope arrays,
    same RNG key, so the functional RNG threading reproduces the step's
    randomness — probing every op's outputs for NaN/Inf, and stop at
    the FIRST producer. Returns the provenance dict, or None when the
    replay stays finite (an XLA-fusion rounding edge the eager math
    does not hit — reported honestly instead of guessing).

    Mesh programs are not replayable on one host; callers gate on
    program._mesh is None."""
    import numpy as np

    from ..ops import registry as op_registry

    block = program.global_block()
    ops = list(block.ops)
    env: Dict[str, Any] = dict(feed_arrays)

    # pre-step inputs: anything read before written comes from the scope
    written = set(feed_arrays)
    needed: List[str] = []
    for op in ops:
        for n in op.input_names():
            if n not in written and n not in needed:
                needed.append(n)
        written.update(op.output_names())
    for n in needed:
        v = scope.find_var(n)
        if v is None:
            return None  # startup not run here; nothing to replay
        env[n] = v

    # bad INPUTS are a provenance answer of their own: the step did not
    # produce the poison, the feed/state carried it in
    for name, v in list(env.items()):
        st = _array_stats(v)
        if st and (st.get("nan") or st.get("inf")):
            return {"provenance": "input", "var": name, "stats": st}

    found: Dict[str, Any] = {}

    def probe(op_idx, op, outs):
        for slot, names in op.outputs.items():
            vals = (outs or {}).get(slot)
            if vals is None:
                continue
            for name, v in zip(names, vals):
                if v is None or not hasattr(v, "dtype"):
                    continue
                if np.dtype(v.dtype).kind != "f":
                    continue
                st = _array_stats(v)
                if st and (st["nan"] or st["inf"]):
                    found.update(op_index=op_idx, slot=slot,
                                 var=name, stats=st)
                    raise _FirstBadFound()

    ctx = op_registry.EmitContext(rng_key=scope._rng_key, mesh=None)
    try:
        op_registry.emit_ops(ctx, ops, env, on_op=probe)
    except _FirstBadFound:
        pass
    if not found:
        return None
    op = ops[found["op_index"]]
    callstack, uf = _callstack_json(op)
    operands = []
    for slot, names in op.inputs.items():
        for name in names:
            if name in env:
                operands.append({"slot": slot, "var": name,
                                 "stats": _array_stats(env[name])})
    return {
        "provenance": "op",
        "op_index": found["op_index"],
        "op_type": op.type,
        "output_var": found["var"],
        "output_slot": found["slot"],
        "output_stats": found["stats"],
        "operands": operands,
        "callstack": callstack,
        "user_frame": uf,
    }


def maybe_run_doctor(program, feed_arrays, scope, reason: str
                     ) -> Tuple[Optional[dict], Optional[str]]:
    """The bad-step guard's post-mortem: bisect the un-committed step
    to its first non-finite producer, attach the sampled grad-norm
    history leading in, dump numrec.<tag>.json through the flight-
    recorder path, and return (report, dump_path). Never raises — a
    broken doctor must not mask the BadStepError. Opt out with
    PADDLE_NUMERICS_DOCTOR=0."""
    if not doctor_enabled():
        return None, None
    reg = get_registry()
    reg.counter("numerics_doctor_runs_total",
                help="NaN-provenance doctor invocations").inc()
    report: Dict[str, Any] = {
        "format": 1,
        "kind": "numrec",
        "reason": reason,
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "grad_history": history()[-16:],
    }
    try:
        if getattr(program, "_mesh", None) is not None:
            report["bisect_skipped"] = "mesh program (not replayable " \
                                       "on one host)"
        else:
            prov = bisect_first_nonfinite(program, feed_arrays, scope)
            if prov is None:
                report["bisect_skipped"] = \
                    "replay stayed finite (fusion rounding edge?)"
            else:
                report.update(prov)
    except Exception as e:  # noqa: BLE001 — doctor must not mask
        report["bisect_error"] = f"{type(e).__name__}: {e}"
    try:
        from . import sink

        rec = {"kind": "numerics", "event": "doctor", "reason": reason}
        if report.get("provenance") == "op":
            rec.update(op_index=report["op_index"],
                       op_type=report["op_type"],
                       output_var=report["output_var"])
        sink.emit(rec)
    except Exception:  # noqa: BLE001
        pass
    path = dump_numrec(report)
    global _last_doctor
    _last_doctor = report
    try:
        from . import tracing

        if report.get("provenance") == "op":
            tracing.annotate(numerics_op=f"op{report['op_index']}:"
                                         f"{report['op_type']}")
    except Exception:  # noqa: BLE001
        pass
    return report, path


_last_doctor: Optional[dict] = None


def last_doctor_report() -> Optional[dict]:
    return _last_doctor


def dump_numrec(payload: dict, directory: Optional[str] = None
                ) -> Optional[str]:
    """Atomically write the numerics flight-record next to the tracing
    and memory flight recorders: PADDLE_TRACE_DIR/numrec.<tag>.json.
    Like memrec, this does NOT require PADDLE_TRACING — a NaN
    post-mortem is useful without causal tracing armed. None when no
    directory is configured or the disk refuses."""
    from . import tracing

    directory = directory or os.environ.get(tracing.ENV_DIR)
    if not directory:
        return None
    path = os.path.join(directory,
                        f"numrec.{tracing.process_tag()}.json")
    try:
        os.makedirs(directory, exist_ok=True)
        tracing._atomic_write(path, json.dumps(payload).encode())
    except OSError:
        return None
    return path


# ---------------------------------------------------------------------------
# cross-replica SDC detection
# ---------------------------------------------------------------------------


def fingerprint_arrays(named: Dict[str, Any]) -> dict:
    """Deterministic fingerprint of a name->array mapping: crc32 over
    (name, bytes) in sorted-name order + the float l2 norm. Replicated
    dp state is bit-identical across ranks, so equal state means equal
    fingerprints and a mismatch is evidence of corruption."""
    import numpy as np

    crc = 0
    sq = 0.0
    n = 0
    for name in sorted(named):
        a = np.asarray(named[name])
        if a.dtype.kind not in "fiu":
            continue
        crc = zlib.crc32(str(name).encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
        if a.dtype.kind == "f":
            af = a.astype(np.float64)
            sq += float(np.square(np.where(np.isfinite(af), af, 0.0)
                                  ).sum())
            n += int(a.size)
    return {"crc": crc & 0xFFFFFFFF, "norm": math.sqrt(sq), "n": n}


class FingerprintTable:
    """Coordinator-side detector (stdlib-only: hosted by the launcher).

    Ranks report (step, tag, fingerprint) every K steps; once two or
    more reports exist for a step the checksums are compared:

      all equal        -> agreement, nothing to say
      strict majority  -> the minority ranks are the odd-rank-out
      tie (2 ranks)    -> the publisher's self-consistency bit
                          arbitrates: a rank that reports
                          consistent=False (its applied merged-grad
                          checksum no longer matches the checksum it
                          derived from the shared PS state — the
                          in-flight corruption window) indicts itself;
                          with no such bit the event is flagged
                          ambiguous and names every diverged rank

    Divergence LATCHES: every later report (any step) gets the latest
    event back, so all ranks learn the verdict — and flight-dump —
    within one reporting period."""

    _KEEP = 64

    def __init__(self):
        self.lock = threading.Lock()
        # step -> {tag: fingerprint}
        self.steps: Dict[int, Dict[str, dict]] = {}
        self.world: Dict[int, int] = {}  # step -> expected reports
        self.events: List[dict] = []
        self.last_event: Optional[dict] = None

    def record(self, step: int, tag: str, fingerprint: dict,
               world_size: int = 0) -> dict:
        step = int(step)
        with self.lock:
            ent = self.steps.setdefault(step, {})
            ent[str(tag)] = dict(fingerprint or {})
            self.world[step] = max(self.world.get(step, 0),
                                   int(world_size or 0))
            while len(self.steps) > self._KEEP:
                s = min(self.steps)
                self.steps.pop(s)
                self.world.pop(s, None)
            event = self._check_locked(step)
            out: Dict[str, Any] = {
                "step": step,
                "reports": len(ent),
                "diverged": self.last_event is not None,
            }
            if event is not None:
                out["event"] = event
            elif self.last_event is not None:
                out["event"] = self.last_event
            return out

    def _check_locked(self, step: int) -> Optional[dict]:
        reports = self.steps.get(step) or {}
        # the verdict waits for every expected rank (a 2-of-3 mismatch
        # may still resolve to a strict majority); unknown world sizes
        # compare as soon as two reports exist
        if len(reports) < max(2, self.world.get(step, 0)):
            return None
        groups: Dict[int, List[str]] = {}
        for tag, fp in reports.items():
            groups.setdefault(int(fp.get("crc", -1)), []).append(tag)
        if len(groups) == 1:
            return None
        if any(e["step"] == step for e in self.events):
            return next(e for e in self.events if e["step"] == step)
        sizes = sorted((len(t) for t in groups.values()), reverse=True)
        if len(sizes) > 1 and sizes[0] > sizes[1]:
            majority = max(groups.values(), key=len)
            odd = sorted(t for ts in groups.values() for t in ts
                         if ts is not majority)
            method = "majority"
        else:
            odd = sorted(t for t, fp in reports.items()
                         if fp.get("consistent") is False)
            if odd:
                method = "self_check"
            else:
                odd = sorted(reports)
                method = "ambiguous"
        event = {
            "event": "divergence",
            "step": step,
            "odd_rank_out": odd,
            "method": method,
            "groups": {f"{crc:#010x}": sorted(tags)
                       for crc, tags in groups.items()},
            "norms": {t: fp.get("norm") for t, fp in reports.items()},
            "ts": round(time.time(), 6),
        }
        self.events.append(event)
        self.last_event = event
        return event

    def status(self) -> dict:
        with self.lock:
            return {
                "steps": {s: {t: dict(fp) for t, fp in ent.items()}
                          for s, ent in sorted(self.steps.items())},
                "events": [dict(e) for e in self.events],
                "diverged": self.last_event is not None,
            }


class SDCReporter:
    """Trainer-side publisher: fingerprint the replicated state every K
    steps and report it through the coordinator transport. On a
    divergence verdict: counter + kind="numerics" divergence record +
    flight dump (the "flight-dumps all ranks" leg — every rank sees the
    latched verdict within one reporting period)."""

    def __init__(self, endpoint: Optional[str] = None,
                 tag: Optional[str] = None,
                 world_size: Optional[int] = None,
                 every: Optional[int] = None):
        self.endpoint = endpoint or os.environ.get(
            "PADDLE_COORDINATOR_ENDPOINT")
        self.every = every if every is not None else _sdc_every()
        if world_size is None:
            world_size = _env_int("PADDLE_TRAINERS_NUM", 0)
        self.world_size = int(world_size)
        if tag is None:
            from ..distributed import coordinator as coord

            tag = coord.member_tag()
        self.tag = tag
        self._client = None
        self._dumped = False
        self.armed = bool(self.endpoint and self.every > 0)

    def _conn(self):
        if self._client is None:
            from ..distributed import coordinator as coord

            self._client = coord.CoordinatorClient(
                self.endpoint, tag=self.tag, kind="trainer")
        return self._client

    def maybe_report(self, step: int,
                     named_arrays: Optional[Dict[str, Any]] = None,
                     fingerprint: Optional[dict] = None,
                     consistent: Optional[bool] = None
                     ) -> Optional[dict]:
        """Report when armed and step is on the K-cadence; returns the
        coordinator verdict (or None when skipped)."""
        if not self.armed or (self.every and step % self.every):
            return None
        fp = dict(fingerprint) if fingerprint is not None \
            else fingerprint_arrays(named_arrays or {})
        if consistent is not None:
            fp["consistent"] = bool(consistent)
        get_registry().counter("numerics_sdc_reports_total",
                               help="SDC fingerprints published").inc()
        out = self._conn().numerics_report(step, fp, self.world_size)
        if isinstance(out, dict) and out.get("diverged"):
            self._on_divergence(step, out.get("event") or {})
        return out

    def _on_divergence(self, step: int, ev: dict) -> None:
        get_registry().counter("numerics_sdc_divergence_total",
                               help="divergence verdicts received").inc()
        from . import sink, tracing

        sink.emit({"kind": "numerics", "event": "divergence",
                   "step": step,
                   "odd_rank_out": ev.get("odd_rank_out"),
                   "method": ev.get("method"),
                   "detected_step": ev.get("step")})
        if not self._dumped:
            self._dumped = True
            tracing.annotate(
                sdc_odd_rank_out=",".join(ev.get("odd_rank_out") or []))
            tracing.flight_dump("sdc_divergence")

    def poll_verdict(self, step: int, timeout: float = 10.0
                     ) -> Optional[dict]:
        """Wait (bounded) until every rank's fingerprint for `step` has
        landed on the coordinator, then return the divergence verdict —
        the detector-side stand-in for the dp sync barrier that
        lock-steps real ranks. Triggers the same divergence handling
        (counter + record + flight dump) maybe_report does, so a rank
        running AHEAD of a slow peer still learns the verdict within
        its reporting period."""
        if not self.armed:
            return None
        deadline = time.monotonic() + timeout
        while True:
            try:
                st = self._conn().numerics_status()
            except Exception:  # noqa: BLE001 — coordinator flap
                st = None
            if isinstance(st, dict):
                reports = (st.get("steps") or {}).get(step) or {}
                done = (self.world_size
                        and len(reports) >= self.world_size)
                if st.get("diverged"):
                    ev = (st.get("events") or [{}])[-1]
                    self._on_divergence(step, ev)
                    return {"diverged": True, "event": ev}
                if done:
                    return {"diverged": False}
            if time.monotonic() > deadline:
                return {"diverged": False, "timeout": True}
            time.sleep(0.05)

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


# ---------------------------------------------------------------------------
# debugz /numericz
# ---------------------------------------------------------------------------


def numericz(limit: int = 32) -> dict:
    """The /numericz payload: flag state + watch roster + recent sampled
    series + AMP scale state + the last doctor report + the local SDC
    view (reporting cadence; the authoritative divergence table lives on
    the coordinator's `numerics_status` verb)."""
    from ..fluid.flags import flag

    # prefer the roster of the program that actually SAMPLED last (a
    # user's program_guard-built program is usually not the default)
    watches = dict(_last_watches)
    if not watches:
        try:
            from ..fluid import framework

            watches = dict(getattr(framework.default_main_program(),
                                   "_numerics_watch", None) or {})
        except Exception:  # noqa: BLE001 — report pages never crash
            pass
    with _amp_lock:
        amp = {name: {"last_scale": st["last"]}
               for name, st in _amp_states.items()}
    return {
        "enabled": bool(flag("FLAGS_tensor_stats")),
        "every": stats_every(),
        "sdc_every": _sdc_every() or None,
        "watches": watches,
        "history": history()[-limit:],
        "amp": amp or None,
        "doctor": _last_doctor,
    }


def _reset_for_tests() -> None:
    global _stat_step, _last_sample, _last_doctor
    global _exec_reporter, _exec_reporter_failed
    _last_watches.clear()
    with _history_lock:
        _history.clear()
    with _amp_lock:
        _amp_states.clear()
    _stat_step = 0
    _last_sample = None
    _last_doctor = None
    if _exec_reporter is not None:
        try:
            _exec_reporter.close()
        except Exception:  # noqa: BLE001
            pass
    _exec_reporter = None
    _exec_reporter_failed = False
