"""Job-lifetime goodput/badput ledger + fleet-level aggregation (ISSUE 15).

Every observability plane so far (per-op device time, causal traces,
HBM attribution, numerics) is per-rank and per-incarnation. This module
answers the question production operators actually ask — "what fraction
of this job's wall-clock was productive training, and where did the
rest go?" — across ranks, restarts and evictions:

  ledger      each rank classifies EVERY wall-clock second of its life
              into one of the BUCKETS below, derived from the step-time
              breakdown fluid/monitor.py already measures. The window
              between consecutive classification points is authoritative
              (measured phase times are scaled down if they overlap it,
              and the un-measured remainder is `idle`), so per-rank
              totals always sum to wall time exactly — "unclassified
              residual" exists only across process-death gaps, which
              the stitcher (goodtop) classifies as `restart_recovery`.
  persistence one JSONL file per incarnation —
              `<PADDLE_GOODPUT_DIR|PADDLE_TRACE_DIR>/goodput.<tag>.<inc>.jsonl`
              (inc = PADDLE_ELASTIC_RESTART) — appended line-at-a-time
              like the metrics sink, so an eviction loses at most the
              in-flight line and the JOB total survives as the sum over
              incarnation files.
  fleet       when PADDLE_FLEET_METRICS=1, every lease renewal
              (heartbeat stamps / LeaseWorker payloads) carries a
              BOUNDED registry snapshot + the ledger summary; the
              launcher-hosted coordinator merges them (`merge_fleet`)
              and serves one fleet-level scrape: debugz `/fleetz`
              (JSON rollup) and `/fleetz/metrics` (Prometheus text with
              per-rank labels) — operators scrape ONE endpoint, not N.

Buckets:

  productive_step   compiled step execution + fetch (the work)
  data_wait         input pipeline: feed materialization + iterator wait
  compile           trace + XLA compile (cache misses / retraces)
  checkpoint_save   CheckpointManager.save windows
  restart_recovery  detection -> respawn -> recompile -> replay after a
                    death (restore() charges here rank-side; the
                    cross-incarnation gap is stitched in by goodtop)
  bad_step_replay   steps that raised BadStepError (work discarded)
  stall             straggler episodes / failed steps (work happened,
                    nothing committed)
  idle              everything else (gaps between Executor.run calls)

Env contract:

  PADDLE_GOODPUT=1          arm the ledger (off = zero cost, no files,
                            step records / wire bytes bit-identical)
  PADDLE_GOODPUT_DIR        ledger directory (default PADDLE_TRACE_DIR;
                            neither set = in-memory totals only)
  PADDLE_GOODPUT_EVERY      kind="goodput" sink-record cadence (steps,
                            default 20)
  PADDLE_FLEET_METRICS=1    ride bounded snapshots + ledger summaries on
                            lease renewals (fleet aggregation)
  PADDLE_FLEET_METRICS_MAX  bounded-snapshot series cap (default 120)

Module is stdlib-only: the launcher, coordinator and tools/goodtop.py
import it without jax.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from . import sink as _sink
from .registry import get_registry

ENV_GATE = "PADDLE_GOODPUT"
ENV_DIR = "PADDLE_GOODPUT_DIR"
ENV_EVERY = "PADDLE_GOODPUT_EVERY"
ENV_FLEET = "PADDLE_FLEET_METRICS"
ENV_FLEET_MAX = "PADDLE_FLEET_METRICS_MAX"

BUCKETS = (
    "productive_step",
    "data_wait",
    "compile",
    "checkpoint_save",
    "restart_recovery",
    "bad_step_replay",
    "stall",
    "idle",
    # serving replicas: wall-clock a request burned before being shed at
    # admission / expiring mid-decode.  Same ledger, so merge_fleet and
    # /fleetz attribute serving badput exactly like training badput.
    "serve_shed",
    "serve_deadline",
    # r22 preemption ladder: time a preempted generation spent off the
    # device waiting to re-admit, and the extra prefill the resume cost
    "serve_preempt",
    "serve_resume",
)

# wall time of module import: recorded in the birth row so the stitcher
# can see how much of the respawn gap was interpreter/jax import
_IMPORT_TS = time.time()

_enabled: Optional[bool] = None
_fleet_enabled: Optional[bool] = None
_ledger: Optional["GoodputLedger"] = None
_lock = threading.Lock()


def enabled() -> bool:
    """PADDLE_GOODPUT gate, resolved once per process."""
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get(ENV_GATE, "") not in ("", "0", "false")
    return _enabled


def fleet_enabled() -> bool:
    """PADDLE_FLEET_METRICS gate, resolved once per process."""
    global _fleet_enabled
    if _fleet_enabled is None:
        _fleet_enabled = os.environ.get(ENV_FLEET, "") not in (
            "", "0", "false")
    return _fleet_enabled


def _process_tag() -> str:
    # the STABLE membership identity survives elastic resizes where the
    # rank numbering does not — ledger files must keep accumulating
    # under one tag across incarnations
    t = os.environ.get("PADDLE_TRAINER_TAG")
    if t:
        return t
    from . import tracing

    return tracing.process_tag()


class GoodputLedger:
    """Per-process interval classifier + per-incarnation JSONL file.

    The classification point is `_commit_window`: given the measured
    phase milliseconds since the previous point, the wall window is
    decomposed so the bucket totals sum to wall EXACTLY — measured
    phases are scaled down when they overlap the window (async writers),
    and the remainder lands in `residual_bucket` (normally `idle`)."""

    def __init__(self, tag: Optional[str] = None,
                 incarnation: Optional[int] = None,
                 directory: Optional[str] = None,
                 now: Optional[float] = None):
        self.tag = tag or _process_tag()
        if incarnation is None:
            try:
                incarnation = int(
                    os.environ.get("PADDLE_ELASTIC_RESTART", 0) or 0)
            except ValueError:
                incarnation = 0
        self.incarnation = int(incarnation)
        if directory is None:
            directory = (os.environ.get(ENV_DIR)
                         or os.environ.get("PADDLE_TRACE_DIR"))
        self.path = (os.path.join(
            directory, f"goodput.{self.tag}.{self.incarnation}.jsonl")
            if directory else None)
        now = time.time() if now is None else now
        self.t0 = now
        self._last_ts = now
        self.totals: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self.steps = 0
        self._events = 0
        try:
            self._every = int(os.environ.get(ENV_EVERY, 20) or 20)
        except ValueError:
            self._every = 20
        self._lock = threading.Lock()
        self._f = None
        self._write({"event": "birth", "tag": self.tag,
                     "incarnation": self.incarnation, "pid": os.getpid(),
                     "ts": round(now, 6),
                     "import_ts": round(_IMPORT_TS, 6)})

    # -- persistence -----------------------------------------------------
    def _write(self, row: dict) -> None:
        if self.path is None:
            return
        try:
            if self._f is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._f = open(self.path, "a", buffering=1)
            self._f.write(json.dumps(row) + "\n")
        except OSError:
            # a full disk must never fail a training step; totals and
            # gauges keep accumulating in memory
            self.path = None

    # -- classification --------------------------------------------------
    def _commit_window(self, measured: Dict[str, float],
                       now: Optional[float] = None, event: str = "step",
                       residual_bucket: str = "idle", **extra) -> dict:
        now = time.time() if now is None else now
        with self._lock:
            # a caller may capture `now` BEFORE this lazily-constructed
            # ledger stamps its own birth (monitor.py takes now_wall,
            # emits the step record, then commits here) — clamp so no
            # row ever runs backwards and windows stay wall-exact
            now = max(now, self._last_ts)
            wall = max(0.0, (now - self._last_ts) * 1e3)
            t_start = self._last_ts
            self._last_ts = now
            buckets = {b: max(0.0, float(measured.get(b, 0.0)))
                       for b in BUCKETS}
            s = sum(buckets.values())
            if s > wall:
                if s > 0:
                    # measured phases overlap the wall window (async
                    # overlap / coarse timers): scale down so the
                    # ledger stays wall-exact
                    k = wall / s
                    buckets = {b: v * k for b, v in buckets.items()}
            else:
                buckets[residual_bucket] += wall - s
            for b, v in buckets.items():
                self.totals[b] += v
            if event == "step":
                self.steps += 1
            self._events += 1
            row = {
                "event": event,
                "t0": round(t_start, 6),
                "t1": round(now, 6),
                "buckets": {b: round(v, 3)
                            for b, v in buckets.items() if v > 0},
            }
            row.update(extra)
            emit_summary = (self._events % self._every == 0)
        self._write(row)
        self._update_gauges()
        if emit_summary:
            _sink.emit(dict(self.summary(), kind="goodput",
                            event="summary"))
        return row

    def _update_gauges(self) -> None:
        reg = get_registry()
        total = sum(self.totals.values())
        prod = self.totals["productive_step"]
        reg.gauge("goodput_ratio",
                  help="productive fraction of classified wall-clock "
                       "(job-lifetime goodput, this incarnation)").set(
            prod / total if total > 0 else 0.0)
        for b in BUCKETS:
            if b == "productive_step":
                continue
            reg.gauge("badput_seconds_total",
                      help="classified non-productive wall-clock by "
                           "cause (seconds)",
                      cause=b).set(round(self.totals[b] / 1e3, 3))

    # -- entry points ----------------------------------------------------
    def on_step_commit(self, payload: dict,
                       now: Optional[float] = None) -> None:
        """One committed Executor step: classify the window since the
        previous point from the kind="step" breakdown."""
        measured = {
            "data_wait": payload.get("data_wait_ms", 0.0),
            "compile": payload.get("compile_ms", 0.0),
            "checkpoint_save": payload.get("ckpt_save_ms", 0.0),
            "productive_step": (payload.get("device_ms", 0.0)
                                + payload.get("fetch_ms", 0.0)),
        }
        self._commit_window(measured, now=now, event="step",
                            step=payload.get("step"))

    def on_abandoned_step(self, bad: bool,
                          now: Optional[float] = None) -> None:
        """A step raised without committing: BadStepError windows are
        `bad_step_replay` (discarded work), any other failure `stall`."""
        self._commit_window(
            {}, now=now, event="bad_step" if bad else "failed_step",
            residual_bucket="bad_step_replay" if bad else "stall")

    def on_restore(self, ms: float, now: Optional[float] = None) -> None:
        """CheckpointManager.restore window — recovery cost."""
        self._commit_window({"restart_recovery": float(ms)}, now=now,
                            event="restore")

    def note_stall(self, ms: float, cause: str = "straggler",
                   trace_id: Optional[str] = None,
                   now: Optional[float] = None) -> None:
        """An externally observed stall charged to this rank."""
        extra = {"cause": cause}
        if trace_id:
            extra["trace_id"] = trace_id
        self._commit_window({"stall": float(ms)}, now=now, event="stall",
                            **extra)

    def note_serving_badput(self, ms: float, cause: str,
                            now: Optional[float] = None) -> None:
        """Serving-side SLO badput: wall-clock a request spent in the
        replica before being shed at admission (`cause="shed"`),
        expiring mid-decode (`cause="deadline"`), waiting off-device
        after a KV-pressure preemption (`cause="preempt"`), or
        re-prefilling a resumed prefix (`cause="resume"`)."""
        bucket = {
            "deadline": "serve_deadline",
            "preempt": "serve_preempt",
            "resume": "serve_resume",
        }.get(cause, "serve_shed")
        self._commit_window({bucket: float(ms)}, now=now,
                            event="serve_badput", cause=cause)

    # -- read side -------------------------------------------------------
    def summary(self) -> dict:
        with self._lock:
            total = sum(self.totals.values())
            prod = self.totals["productive_step"]
            return {
                "tag": self.tag,
                "incarnation": self.incarnation,
                "t0": round(self.t0, 6),
                "t1": round(self._last_ts, 6),
                "steps": self.steps,
                "goodput_ratio": round(prod / total, 6) if total else None,
                "buckets_ms": {b: round(v, 3)
                               for b, v in self.totals.items()},
            }

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# ---------------------------------------------------------------------------
# module-level hooks (the executor/monitor/checkpoint call these; every
# one is a no-op costing one cached bool read when PADDLE_GOODPUT is off)
# ---------------------------------------------------------------------------


def get_ledger() -> Optional[GoodputLedger]:
    global _ledger
    if not enabled():
        return None
    if _ledger is None:
        with _lock:
            if _ledger is None:
                _ledger = GoodputLedger()
    return _ledger


def on_step_commit(payload: dict, now: Optional[float] = None) -> None:
    led = get_ledger()
    if led is not None:
        led.on_step_commit(payload, now=now)


def on_abandoned_step(bad: bool, now: Optional[float] = None) -> None:
    led = get_ledger()
    if led is not None:
        led.on_abandoned_step(bad, now=now)


def on_restore(ms: float, now: Optional[float] = None) -> None:
    led = get_ledger()
    if led is not None:
        led.on_restore(ms, now=now)


def note_stall(ms: float, cause: str = "straggler",
               trace_id: Optional[str] = None) -> None:
    led = get_ledger()
    if led is not None:
        led.note_stall(ms, cause=cause, trace_id=trace_id)


def note_serving_badput(ms: float, cause: str) -> None:
    led = get_ledger()
    if led is not None:
        led.note_serving_badput(ms, cause=cause)


def summary() -> Optional[dict]:
    led = get_ledger()
    return led.summary() if led is not None else None


def reset_for_tests() -> None:
    global _enabled, _fleet_enabled, _ledger
    with _lock:
        if _ledger is not None:
            _ledger.close()
        _ledger = None
    _enabled = None
    _fleet_enabled = None


# ---------------------------------------------------------------------------
# fleet payload: what one rank ships on each lease renewal
# ---------------------------------------------------------------------------


def bounded_snapshot(max_series: Optional[int] = None) -> dict:
    """Registry snapshot bounded to `max_series` series (deterministic:
    names sorted, first N kept, the rest counted as `truncated`).
    Histograms ship summaries only — the full buckets stay scrape-side."""
    if max_series is None:
        try:
            max_series = int(os.environ.get(ENV_FLEET_MAX, 120) or 120)
        except ValueError:
            max_series = 120
    snap = get_registry().snapshot()
    out: Dict[str, dict] = {}
    n = 0
    truncated = 0
    for name in sorted(snap):
        ent = snap[name]
        rows = []
        for row in ent["series"]:
            if n >= max_series:
                truncated += 1
                continue
            n += 1
            if ent["type"] == "histogram":
                rows.append({"labels": row["labels"],
                             "count": row["count"],
                             "sum": row["sum"], "avg": row["avg"]})
            else:
                rows.append({"labels": row["labels"],
                             "value": row["value"]})
        if rows:
            out[name] = {"type": ent["type"], "series": rows}
    return {"series_limit": max_series, "truncated": truncated,
            "metrics": out}


def fleet_payload() -> Optional[dict]:
    """The extra keys a lease renewal carries when fleet aggregation is
    armed; None (payload unchanged, wire bytes bit-identical) otherwise."""
    if not fleet_enabled():
        return None
    out: dict = {"metrics": bounded_snapshot()}
    s = summary()
    if s is not None:
        out["goodput"] = s
    return out


# ---------------------------------------------------------------------------
# coordinator-side merge (stdlib only — runs in the launcher)
# ---------------------------------------------------------------------------


def merge_fleet(members: Dict[str, Optional[dict]]) -> dict:
    """Merge per-member renewal payloads into the fleet rollup: one row
    per rank plus the job-level goodput ratio and badput-by-cause."""
    ranks: Dict[str, dict] = {}
    job_buckets = {b: 0.0 for b in BUCKETS}
    for tag in sorted(members):
        p = members[tag] or {}
        g = p.get("goodput") or {}
        row = {
            "step": p.get("step"),
            "avg_step_s": p.get("avg_step_s"),
            "data_frac": p.get("data_frac"),
            "incarnation": g.get("incarnation"),
            "goodput_ratio": g.get("goodput_ratio"),
            "buckets_ms": g.get("buckets_ms"),
            "has_metrics": bool(p.get("metrics")),
        }
        ranks[tag] = row
        for b, v in (g.get("buckets_ms") or {}).items():
            if b in job_buckets:
                job_buckets[b] += float(v)
    total = sum(job_buckets.values())
    prod = job_buckets["productive_step"]
    worst = sorted(
        ((b, v) for b, v in job_buckets.items()
         if b != "productive_step" and v > 0),
        key=lambda kv: -kv[1])
    return {
        "ranks": ranks,
        "job": {
            "total_ms": round(total, 3),
            "goodput_ratio": round(prod / total, 6) if total else None,
            "badput_ms": {b: round(v, 3) for b, v in worst},
        },
    }


def fleet_prometheus(members: Dict[str, Optional[dict]]) -> str:
    """One Prometheus text exposition for the whole fleet: every
    member's bounded snapshot re-emitted with a `rank="<tag>"` label,
    plus fleet-level goodput rollup lines — the single scrape target."""
    # name -> (type, [(labelkey, value_lines...)])
    by_name: Dict[str, dict] = {}
    for tag in sorted(members):
        p = members[tag] or {}
        metrics = (p.get("metrics") or {}).get("metrics") or {}
        for name in sorted(metrics):
            ent = metrics[name]
            slot = by_name.setdefault(name, {"type": ent["type"],
                                             "samples": []})
            for row in ent["series"]:
                labels = dict(row.get("labels") or {})
                labels["rank"] = tag
                lab = "{" + ",".join(
                    f'{k}="{_escape(v)}"'
                    for k, v in sorted(labels.items())) + "}"
                if ent["type"] == "histogram":
                    slot["samples"].append(
                        (f"{name}_sum{lab}", row.get("sum", 0)))
                    slot["samples"].append(
                        (f"{name}_count{lab}", row.get("count", 0)))
                else:
                    slot["samples"].append(
                        (f"{name}{lab}", row.get("value", 0)))
    lines: List[str] = []
    for name in sorted(by_name):
        ent = by_name[name]
        kind = ("untyped" if ent["type"] == "histogram" else ent["type"])
        lines.append(f"# TYPE {name} {kind}")
        for sample, value in ent["samples"]:
            lines.append(f"{sample} {value}")
    merged = merge_fleet(members)
    lines.append("# TYPE fleet_goodput_ratio gauge")
    for tag, row in sorted(merged["ranks"].items()):
        if row.get("goodput_ratio") is not None:
            lines.append(
                f'fleet_goodput_ratio{{rank="{_escape(tag)}"}} '
                f'{row["goodput_ratio"]}')
    job = merged["job"]
    if job.get("goodput_ratio") is not None:
        lines.append("# TYPE job_goodput_ratio gauge")
        lines.append(f"job_goodput_ratio {job['goodput_ratio']}")
    lines.append("# TYPE job_badput_seconds_total gauge")
    for b, v in sorted(job.get("badput_ms", {}).items()):
        lines.append(
            f'job_badput_seconds_total{{cause="{_escape(b)}"}} '
            f'{round(v / 1e3, 3)}')
    return "\n".join(lines) + ("\n" if lines else "")


def _escape(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


# ---------------------------------------------------------------------------
# launcher-side lifecycle ledger (restart/stall events goodtop stitches)
# ---------------------------------------------------------------------------


LAUNCHER_FILE = "goodput.launcher.jsonl"


class LauncherLedger:
    """Append-only JSONL of job lifecycle events the launcher observes:
    job_start, restart (detect_ts -> respawn_ts per death) and straggler
    stall episodes — the cross-incarnation evidence goodtop joins with
    the per-rank ledgers to decompose restart_recovery."""

    def __init__(self, directory: str):
        self.path = os.path.join(directory, LAUNCHER_FILE)
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()

    def event(self, **row) -> None:
        row.setdefault("ts", round(time.time(), 6))
        try:
            with self._lock, open(self.path, "a", buffering=1) as f:
                f.write(json.dumps(row) + "\n")
        except OSError:
            pass  # lifecycle bookkeeping must never kill the launcher


# ---------------------------------------------------------------------------
# offline load + restart stitching (tools/goodtop.py and tests)
# ---------------------------------------------------------------------------


def load_job(directory: str) -> dict:
    """Parse every goodput.<tag>.<inc>.jsonl (+ the launcher ledger) in
    `directory` into {"ranks": {tag: {inc: {...}}}, "launcher": [...]}"""
    ranks: Dict[str, Dict[int, dict]] = {}
    launcher: List[dict] = []
    for name in sorted(os.listdir(directory)):
        path = os.path.join(directory, name)
        if name == LAUNCHER_FILE:
            with open(path) as f:
                launcher = [json.loads(ln) for ln in f if ln.strip()]
            continue
        if not (name.startswith("goodput.") and name.endswith(".jsonl")):
            continue
        stem = name[len("goodput."):-len(".jsonl")]
        tag, _, inc_s = stem.rpartition(".")
        try:
            inc = int(inc_s)
        except ValueError:
            continue
        rows: List[dict] = []
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if ln:
                    try:
                        rows.append(json.loads(ln))
                    except ValueError:
                        pass  # in-flight torn line (killed process)
        birth = next((r for r in rows if r.get("event") == "birth"), None)
        windows = [r for r in rows if "buckets" in r]
        totals = {b: 0.0 for b in BUCKETS}
        for w in windows:
            for b, v in w["buckets"].items():
                if b in totals:
                    totals[b] += float(v)
        steps = [w for w in windows if w.get("event") == "step"]
        ckpt_steps = [w.get("step") for w in steps
                      if w["buckets"].get("checkpoint_save", 0) > 0
                      and w.get("step") is not None]
        ranks.setdefault(tag, {})[inc] = {
            "rows": rows,
            "birth": birth,
            "t0": (birth or {}).get("ts",
                                    windows[0]["t0"] if windows else None),
            "t1": windows[-1]["t1"] if windows else
            (birth or {}).get("ts"),
            "totals_ms": totals,
            "n_steps": len(steps),
            "last_step": max((w.get("step") for w in steps
                              if w.get("step") is not None), default=None),
            "last_ckpt_step": max(ckpt_steps, default=None),
        }
    return {"ranks": ranks, "launcher": launcher}


def _match_restart(launcher: List[dict], death_ts: float,
                   birth_ts: float) -> Optional[dict]:
    """The launcher restart event covering [death_ts, birth_ts] — any
    group restart in the window counts (a group restart respawns every
    tag, not just the culprit's; the event's own `tag` names the
    culprit)."""
    best = None
    for ev in launcher:
        if ev.get("event") != "restart":
            continue
        det = ev.get("detect_ts")
        if det is None:
            continue
        # allow one watch-poll of slack on both sides
        if death_ts - 2.0 <= det <= birth_ts + 2.0:
            if best is None or abs(det - death_ts) < abs(
                    best["detect_ts"] - death_ts):
                best = ev
    return best


def stitch_job(directory: str) -> dict:
    """The job-lifetime view: per-rank and job totals with every
    cross-incarnation gap classified as restart_recovery, each restart
    incident decomposed into detection / respawn / recompile / replay,
    and launcher-observed stall episodes cited. This is what
    tools/goodtop.py renders."""
    job = load_job(directory)
    per_rank: Dict[str, dict] = {}
    incidents: List[dict] = []
    for tag, incs in sorted(job["ranks"].items()):
        order = sorted(incs)
        totals = {b: 0.0 for b in BUCKETS}
        for inc in order:
            for b, v in incs[inc]["totals_ms"].items():
                totals[b] += v
        wall_t0 = incs[order[0]]["t0"]
        wall_t1 = incs[order[-1]]["t1"]
        # stitch each gap between incarnation k and k+1
        for a, b_ in zip(order, order[1:]):
            prev, nxt = incs[a], incs[b_]
            death = prev["t1"]
            birth = nxt["t0"]
            if death is None or birth is None:
                continue
            gap_ms = max(0.0, (birth - death) * 1e3)
            totals["restart_recovery"] += gap_ms
            ev = _match_restart(job["launcher"], death, birth)
            detect_ts = (ev or {}).get("detect_ts")
            respawn_ts = (ev or {}).get("respawn_ts")
            detection_s = (max(0.0, detect_ts - death)
                           if detect_ts is not None else None)
            respawn_s = (max(0.0, birth - detect_ts)
                         if detect_ts is not None else None)
            # recompile: compile time up to and including the first
            # productive step of the new incarnation
            recompile_ms = 0.0
            replay_ms = 0.0
            replay_steps = 0
            steps = [w for w in nxt["rows"]
                     if w.get("event") == "step" and "buckets" in w]
            for w in steps:
                recompile_ms += w["buckets"].get("compile", 0.0)
                if w["buckets"].get("productive_step", 0) > 0:
                    break
            if (prev["last_step"] is not None
                    and prev["last_ckpt_step"] is not None):
                replay_steps = max(
                    0, prev["last_step"] - prev["last_ckpt_step"])
                for w in steps[:replay_steps]:
                    replay_ms += w["buckets"].get("productive_step", 0.0)
            restore_ms = sum(
                w["buckets"].get("restart_recovery", 0.0)
                for w in nxt["rows"] if "buckets" in w)
            incidents.append({
                "kind": "restart",
                "tag": tag,
                "from_incarnation": a,
                "to_incarnation": b_,
                "death_ts": round(death, 6),
                "birth_ts": round(birth, 6),
                "gap_s": round(gap_ms / 1e3, 3),
                "detection_s": (round(detection_s, 3)
                                if detection_s is not None else None),
                "respawn_s": (round(respawn_s, 3)
                              if respawn_s is not None else None),
                "recompile_s": round(recompile_ms / 1e3, 3),
                "restore_s": round(restore_ms / 1e3, 3),
                "replay_steps": replay_steps,
                "replay_s": round(replay_ms / 1e3, 3),
                "reason": (ev or {}).get("reason"),
                "culprit": (ev or {}).get("tag"),
            })
        wall_ms = (max(0.0, (wall_t1 - wall_t0) * 1e3)
                   if wall_t0 is not None and wall_t1 is not None else 0.0)
        classified = sum(totals.values())
        total_ms = classified
        per_rank[tag] = {
            "incarnations": len(order),
            "wall_s": round(wall_ms / 1e3, 3),
            "classified_s": round(classified / 1e3, 3),
            "unclassified_s": round(
                max(0.0, wall_ms - classified) / 1e3, 3),
            "unclassified_frac": round(
                max(0.0, wall_ms - classified) / wall_ms, 4)
            if wall_ms > 0 else 0.0,
            "goodput_ratio": round(
                totals["productive_step"] / total_ms, 6)
            if total_ms else None,
            "buckets_s": {b: round(v / 1e3, 3)
                          for b, v in totals.items()},
            "n_steps": sum(incs[i]["n_steps"] for i in order),
        }
    # launcher stall episodes (straggler detector) are incidents too
    for ev in job["launcher"]:
        if ev.get("event") == "stall":
            incidents.append(dict(ev, kind="stall"))
        elif ev.get("event") == "coord_outage":
            # control-plane outage (ISSUE 18): the coordinator died and
            # was respawned/promoted — labeled distinctly from rank
            # deaths because NO rank died: trainers rode it out in
            # grace mode and the gap charges no trainer badput bucket
            inc = dict(ev, kind="coord_outage")
            if inc.get("gap_s") is None and (
                    ev.get("detect_ts") is not None
                    and ev.get("respawn_ts") is not None):
                inc["gap_s"] = round(
                    float(ev["respawn_ts"]) - float(ev["detect_ts"]), 3)
            incidents.append(inc)
    job_buckets = {b: 0.0 for b in BUCKETS}
    for row in per_rank.values():
        for b, v in row["buckets_s"].items():
            job_buckets[b] += v
    total_s = sum(job_buckets.values())
    prod_s = job_buckets["productive_step"]
    incidents.sort(
        key=lambda i: (i["gap_s"] if i.get("gap_s") is not None
                       else (i.get("excess_ms") or 0.0) / 1e3),
        reverse=True)  # costliest first, one unit (seconds)
    return {
        "ranks": per_rank,
        "incidents": incidents,
        "job": {
            "total_s": round(total_s, 3),
            "goodput_ratio": round(prod_s / total_s, 6)
            if total_s else None,
            "badput_s": {b: round(v, 3)
                         for b, v in sorted(job_buckets.items(),
                                            key=lambda kv: -kv[1])
                         if b != "productive_step" and v > 0},
            "unclassified_frac": round(
                sum(r["unclassified_s"] for r in per_rank.values())
                / max(1e-9, sum(max(r["wall_s"], r["classified_s"])
                                for r in per_rank.values())), 4)
            if per_rank else 0.0,
        },
    }
