"""debugz: live introspection HTTP server (ISSUE 6).

The in-process answer to "what is this trainer doing RIGHT NOW" —
borgmon-style status pages served straight from the process state, no
sidecar, no log scraping:

  /metrics   Prometheus text exposition of the process registry
             (point a scraper at it, or curl it)
  /statusz   build + flags + mesh + step summary + (when the job
             control plane is armed) the coordinator's membership
             table (JSON)
  /steps     recent per-step breakdown records (JSON list; the same
             schema the PADDLE_METRICS_PATH JSONL sink writes)
  /proftop   last per-op cost report built in this process (JSON;
             404-shaped {} until telemetry.cost builds one)
  /memz      memory observability (ISSUE 11): live per-device allocator
             stats always, plus — once FLAGS_mem_profile (or memtop /
             the bench hook) has built one — the last memory report:
             per-category breakdown (params / optimizer_state /
             gradients / feeds / activations), top-K buffers with user
             callstacks, static-vs-measured peak, what-ifs (JSON)
  /numericz  training numerics (ISSUE 12): FLAGS_tensor_stats state,
             the watch roster (per-layer gradients / params / clip
             global norm), the recent sampled stat series (nan/inf
             counts, max-abs, l2 per watch), AMP loss-scale state, the
             last NaN-provenance doctor report, and the local SDC
             reporting cadence (JSON; the authoritative divergence
             table lives on the coordinator's numerics_status verb)
  /tracez    recent causal traces from the span ring (PADDLE_TRACING),
             slowest-first with per-hop durations — the live view of
             what the flight recorder would dump (JSON)
  /servez    per-request LLM serving view (ISSUE 19): active decode
             slots (age / tokens / pages / phase / trace id), queued +
             resume-queued requests, recent completions slowest-first
             (JSON; 404-shaped when no generation engine is attached)
  /fleetz    fleet goodput rollup (ISSUE 15): per-rank rows merged
             from lease-renewal payloads, job goodput ratio, badput by
             cause, worst incidents (JSON; needs the job coordinator —
             launch.py --fleetz_port serves it launcher-side), and
             /fleetz/metrics — the fleet-wide Prometheus exposition
             with per-rank labels (scrape ONE endpoint, not N)
  /flagz     GET: the runtime-mutable flag whitelist + every flag's
             current value. POST {"name": ..., "value": ...}: flip one
             whitelisted flag live (FLAGS_check_numerics and friends;
             PADDLE_* knobs set the env for next-use readers), with an
             audit record in the metrics sink and a registry counter —
             non-whitelisted names are 403, never silently applied
  /healthz   "ok" — liveness for orchestration probes

Arming: PADDLE_DEBUGZ_PORT=<port> starts the server on first executor
step (fluid/monitor.mark_step calls maybe_serve once), or call serve()
explicitly. launch.py --debugz_port B arms every trainer with a
deterministic per-rank offset (rank r serves on B + r), so a fleet's
pages are addressable without discovery. Port 0 binds an ephemeral port
(tests); the bound port is on `server.server_address`. Unset = nothing
listens and nothing is imported — the flag-off cost is one env read.

The server is a daemon-threaded stdlib ThreadingHTTPServer: requests
never block training, and the thread dies with the process.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Optional

ENV_PORT = "PADDLE_DEBUGZ_PORT"

# /flagz mutation whitelist — runtime knobs that are SAFE to flip on a
# live trainer: guards and diagnostics, never anything that changes the
# numerics of committed steps. FLAGS_* route through fluid.flags;
# PADDLE_* entries are env-backed knobs read at next use.
FLAGZ_MUTABLE = (
    "FLAGS_check_numerics",
    "FLAGS_check_numerics_max_bad_steps",
    "FLAGS_check_nan_inf",
    "FLAGS_tensor_stats",
    "FLAGS_mem_profile",
    "FLAGS_benchmark",
    "FLAGS_enable_unused_var_check",
    "PADDLE_STRAGGLER_FACTOR",
    "PADDLE_LOG_VERBOSITY",
)

_server = None
_checked = False
_lock = threading.Lock()


def _statusz() -> dict:
    """Build + flags + mesh + step summary. Imports stay inside: the
    page reports whatever is importable and never takes the process
    down with it."""
    out: dict = {"pid": os.getpid(),
                 "rank": os.environ.get("PADDLE_TRAINER_ID"),
                 "role": os.environ.get("PADDLE_TRAINING_ROLE"),
                 "endpoint": os.environ.get("PADDLE_CURRENT_ENDPOINT")}
    try:
        import paddle_tpu

        out["build"] = {"paddle_tpu": getattr(paddle_tpu, "__version__",
                                              "dev")}
    except Exception:  # noqa: BLE001
        out["build"] = {}
    try:
        import jax

        out["build"]["jax"] = jax.__version__
        out["build"]["backend"] = jax.default_backend()
        out["build"]["device_count"] = jax.device_count()
    except Exception:  # noqa: BLE001 — report pages must never crash
        pass
    try:
        from ..fluid import flags as fl

        out["flags"] = dict(fl._values)
    except Exception:  # noqa: BLE001
        out["flags"] = {}
    try:
        from ..fluid import framework

        mesh = framework.default_main_program()._mesh
        out["mesh"] = (
            {"axes": dict(zip(mesh.axis_names,
                              (int(s) for s in mesh.devices.shape)))}
            if mesh is not None else None)
    except Exception:  # noqa: BLE001
        out["mesh"] = None
    try:
        from ..fluid import monitor

        n, avg = monitor.step_rate_sample()
        out["steps"] = {"completed": n, "avg_step_s": avg}
    except Exception:  # noqa: BLE001
        out["steps"] = None
    try:
        # replicated PS tables: per-partition role/epoch/seq/lag (the
        # client-side view of failovers and backup health)
        from ..distributed import ps as _ps

        reps = {}
        for name, t in list(_ps._tables.items()):
            status = getattr(t, "replica_status", None) or getattr(
                getattr(t, "server", None), "replica_status", None)
            if callable(status):
                rows = status()
                if rows:
                    reps[name] = rows
        out["ps_replication"] = reps or None
    except Exception:  # noqa: BLE001
        out["ps_replication"] = None
    try:
        # PS table memory (ISSUE 11 satellite): per-table resident bytes
        # — the capacity-planning row. Hosted tables fan the `stats`
        # verb out to their pservers; in-process tables report locally.
        from ..distributed import ps as _ps

        mem = {}
        for name, t in list(_ps._tables.items()):
            target = t if hasattr(t, "memory_stats") else getattr(
                t, "server", None)
            ms = getattr(target, "memory_stats", None)
            if callable(ms):
                mem[name] = ms()
        out["ps_memory"] = mem or None
    except Exception:  # noqa: BLE001
        out["ps_memory"] = None
    try:
        # job control plane (ISSUE 8): the coordinator's membership
        # table — epoch, world size, per-member lease state — when the
        # launcher armed leases; None otherwise
        from ..distributed import coordinator as _coord

        out["membership"] = _coord.query_membership(timeout=1.0)
    except Exception:  # noqa: BLE001
        out["membership"] = None
    try:
        # control-plane HA (ISSUE 18): the coordinator's own health row
        # — incarnation, role (primary/standby), durable on/off,
        # snapshot seq + last-snapshot age, reconciliation-window
        # remaining; None when no coordinator endpoint is armed
        from ..distributed import coordinator as _coord

        out["coordinator"] = _coord.query_coord_status(timeout=1.0)
    except Exception:  # noqa: BLE001
        out["coordinator"] = None
    try:
        # inference serving (ISSUE 14): the active replica's SLO row —
        # queue depth, served/shed/deadline_exceeded, p50/p99, weight
        # epoch; None when this process serves no model
        import sys as _sys

        _srv = _sys.modules.get("paddle_tpu.inference.server")
        out["serving"] = (_srv.current_status()
                          if _srv is not None else None)
    except Exception:  # noqa: BLE001
        out["serving"] = None
    return out


def _flagz_state() -> dict:
    from ..fluid import flags as fl

    current = {}
    for name in FLAGZ_MUTABLE:
        if name.startswith("FLAGS_"):
            current[name] = fl._values.get(name)
        else:
            current[name] = os.environ.get(name)
    return {"mutable": list(FLAGZ_MUTABLE), "values": current}


def _flagz_post(body: bytes):
    """(status, content_type, body) for POST /flagz. One mutation per
    request: {"name": <whitelisted knob>, "value": <new value>}."""
    import json as _json

    from ..fluid import flags as fl
    from . import sink as _sink
    from .registry import get_registry

    try:
        req = _json.loads(body.decode() or "{}")
        name, value = req["name"], req["value"]
    except (ValueError, KeyError, UnicodeDecodeError) as e:
        return (400, "application/json", _json.dumps(
            {"error": f"bad request: {type(e).__name__}: {e}; want "
                      f'{{"name": ..., "value": ...}}'}).encode())
    if name not in FLAGZ_MUTABLE:
        return (403, "application/json", _json.dumps(
            {"error": f"{name!r} is not runtime-mutable",
             "mutable": list(FLAGZ_MUTABLE)}).encode())
    if name.startswith("FLAGS_"):
        old = fl._values.get(name)
        try:
            fl.set_flags({name: value})
        except (ValueError, TypeError) as e:
            return (400, "application/json", _json.dumps(
                {"error": f"cannot set {name}: {e}"}).encode())
        new = fl._values.get(name)
    else:
        old = os.environ.get(name)
        os.environ[name] = str(value)
        new = str(value)
    # the audit trail: one JSONL record (when the sink is armed) + a
    # counter either way, so a scrape shows that flags were touched
    get_registry().counter("debugz_flagz_mutations_total",
                           help="runtime flag mutations via POST /flagz",
                           flag=name).inc()
    _sink.emit({"kind": "flagz_audit", "flag": name,
                "old": old, "new": new})
    return (200, "application/json", _json.dumps(
        {"ok": True, "flag": name, "old": old, "new": new}).encode())


def _route(path: str):
    """(status, content_type, body bytes) for a request path."""
    from .registry import get_registry

    if path in ("/healthz", "/health"):
        return 200, "text/plain; charset=utf-8", b"ok\n"
    if path == "/metrics":
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                get_registry().to_prometheus().encode())
    if path == "/statusz":
        return (200, "application/json",
                json.dumps(_statusz(), default=str).encode())
    if path == "/steps":
        try:
            from ..fluid import monitor

            body = json.dumps(monitor.recent_steps()).encode()
        except Exception:  # noqa: BLE001
            body = b"[]"
        return 200, "application/json", body
    if path == "/proftop":
        from . import cost

        rep = cost.last_report()
        if rep is None:
            return (404, "application/json",
                    json.dumps({"error": "no cost report built yet; run "
                                "with FLAGS_op_profile (tools/proftop.py "
                                "or telemetry.cost.profile_executor_run)"
                                }).encode())
        return 200, "application/json", json.dumps(rep.to_json()).encode()
    if path == "/memz":
        from . import memory

        return (200, "application/json",
                json.dumps(memory.memz(), default=str).encode())
    if path == "/numericz":
        from . import numerics

        return (200, "application/json",
                json.dumps(numerics.numericz(), default=str).encode())
    if path == "/tracez":
        from . import tracing

        return (200, "application/json",
                json.dumps(tracing.tracez(), default=str).encode())
    if path == "/servez":
        # per-request serving view (ISSUE 19): imports stay lazy AND
        # optional — a trainer process with no serving plane loaded
        # reports the 404 shape instead of importing inference
        import sys as _sys

        _srv = _sys.modules.get("paddle_tpu.inference.server")
        payload = _srv.current_servez() if _srv is not None else None
        if payload is None:
            return (404, "application/json", json.dumps(
                {"error": "no generation engine attached in this "
                          "process (PADDLE_SERVE_GEN=1 arms one)"}
            ).encode())
        return (200, "application/json",
                json.dumps(payload, default=str).encode())
    if path == "/flagz":
        return (200, "application/json",
                json.dumps(_flagz_state()).encode())
    if path == "/fleetz":
        # fleet goodput rollup (ISSUE 15): one page for the whole job —
        # per-rank rows, job goodput %, worst badput incidents. Served
        # from the coordinator's merged renewal payloads; available on
        # ANY process that knows PADDLE_COORDINATOR_ENDPOINT (the
        # launcher serves it at --fleetz_port)
        from ..distributed import coordinator as _coord

        fleet = _coord.query_fleet(timeout=2.0)
        if fleet is None:
            return (404, "application/json", json.dumps(
                {"error": "no job coordinator reachable; arm the "
                          "control plane (launch.py --lease_secs / "
                          "--fleetz_port) so renewals carry fleet "
                          "payloads"}).encode())
        return (200, "application/json",
                json.dumps(fleet, default=str).encode())
    if path == "/fleetz/metrics":
        from ..distributed import coordinator as _coord

        text = _coord.query_fleet_metrics(timeout=2.0)
        if text is None:
            return (404, "text/plain; charset=utf-8",
                    b"no job coordinator reachable\n")
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                text.encode())
    if path in ("", "/", "/index.html"):
        return (200, "text/plain; charset=utf-8",
                b"paddle_tpu debugz: /metrics /statusz /steps /proftop "
                b"/memz /numericz /tracez /servez /fleetz "
                b"/fleetz/metrics /flagz /healthz\n")
    return 404, "text/plain; charset=utf-8", b"not found\n"


def serve(port: Optional[int] = None, host: str = "0.0.0.0"):
    """Start the introspection server (idempotent per process) and
    return it; `server.server_address[1]` is the bound port (useful with
    port 0). The serving thread is a daemon — no shutdown bookkeeping
    needed, but stop() exists for tests."""
    global _server
    with _lock:
        if _server is not None:
            return _server
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                try:
                    status, ctype, body = _route(self.path.split("?")[0])
                except Exception as e:  # noqa: BLE001 — never take the
                    # trainer down for a status page
                    status, ctype = 500, "text/plain; charset=utf-8"
                    body = f"debugz error: {type(e).__name__}: {e}\n".encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
                try:
                    n = int(self.headers.get("Content-Length", 0) or 0)
                    body = self.rfile.read(n) if n else b""
                    path = self.path.split("?")[0]
                    if path == "/flagz":
                        status, ctype, out = _flagz_post(body)
                    else:
                        status, ctype = 404, "text/plain; charset=utf-8"
                        out = b"not found\n"
                except Exception as e:  # noqa: BLE001
                    status, ctype = 500, "text/plain; charset=utf-8"
                    out = f"debugz error: {type(e).__name__}: {e}\n".encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def log_message(self, fmt, *args):  # quiet by default
                pass

        if port is None:
            port = int(os.environ.get(ENV_PORT, "0") or 0)
        srv = ThreadingHTTPServer((host, port), Handler)
        srv.daemon_threads = True
        threading.Thread(target=srv.serve_forever, daemon=True,
                         name="paddle-tpu-debugz").start()
        _server = srv
        return srv


def maybe_serve():
    """Arm from PADDLE_DEBUGZ_PORT (launch.py sets it per rank with
    deterministic offsets). No-op — one env read — when unset; resolved
    once per process."""
    global _checked
    if _checked:
        return _server
    _checked = True
    raw = os.environ.get(ENV_PORT)
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    try:
        return serve(port)
    except OSError as e:
        import sys

        print(f"[debugz] could not bind port {port}: {e}; introspection "
              f"server disabled", file=sys.stderr)
        return None


def armed() -> bool:
    return _server is not None


def stop():
    """Tests only: shut the server down and allow a re-serve."""
    global _server, _checked
    with _lock:
        if _server is not None:
            _server.shutdown()
            _server.server_close()
        _server = None
        _checked = False
