"""Per-op / per-variable HBM attribution + OOM doctor (ISSUE 11).

The observability stack answers "where did the TIME go" (cost.py /
proftop); this module answers "where did the MEMORY go" — the question
behind every OOM, every remat decision, and the SPMD/autotuner items
(both must rank candidates by fit before ranking them by speed):

  1. The static side: fluid/analysis/liverange.py computes first-def/
     last-use, byte size and category (params / optimizer_state /
     gradients / feeds / activations) per Variable, plus the peak
     simultaneous-bytes estimate with donation awareness.
  2. The measured side: Executor.aot_step(...).memory_analysis() is
     XLA's buffer-assignment truth (argument/output/temp/alias bytes,
     peak), and the optimized HLO text — compiled under FLAGS_op_profile
     so instruction metadata carries "op<idx>:<type>" scopes — lets temp
     buffers join back to IR ops through cost.py's scope machinery
     (parse_hlo_metadata: fusion splits + neighborhood propagation).
  3. The join: build_memory_report cross-checks static vs measured
     (documented tolerance below), computes attribution COVERAGE
     (fraction of XLA's peak the layer can assign to IR ops), and ranks
     buffers with PR-5 user callstacks.

Surfaces: debugz /memz (live per-category breakdown + per-device
allocator stats), tools/memtop.py (CLI, --budget gate), bench.py
(peak_hbm_bytes / hbm_model_bytes row fields), and the OOM DOCTOR —
Executor catches RESOURCE_EXHAUSTED at compile and run time (plus the
deterministic `oom:<phase>:<nth>` fault rule and the
PADDLE_HBM_BUDGET_BYTES proactive gate), builds a memory flight-record
(largest live buffers at the static high-water point, owning op + user
layer, concrete what-ifs) and dumps it through the PR-9 flight-recorder
path (PADDLE_TRACE_DIR/memrec.<tag>.json) before raising HBMOOMError.

Cost contract: with FLAGS_mem_profile unset (the default) nothing here
runs on the step path — step records, wire bytes and the loss trace are
bit-identical (asserted by test). Flag on: one static live-range pass
per (program, feed-signature) compile miss — microseconds of host time,
no device work, no extra compile. The measured join (one AOT compile)
is diagnostics pricing: memtop, bench hooks, explicit calls.

Static-vs-measured tolerance (documented contract): XLA fusion deletes
activations the IR names (an elementwise chain never materializes) and
buffer assignment reuses dead buffers, so the static estimate runs HIGH
on activation-heavy graphs; XLA also pads and adds workspace the IR
cannot see, which runs it LOW on tiny graphs. The cross-check asserts
static/measured within [0.3, 3.0] on the bench models; coverage (the
CI bar) is measured-side and must be >= 0.9.

Everything heavier than stdlib+numpy (jax) is imported inside
functions: pservers and the launcher import paddle_tpu.telemetry
without an accelerator runtime.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .registry import get_registry

ENV_BUDGET = "PADDLE_HBM_BUDGET_BYTES"

# "f32[8,16]{1,0}" / "bf16[2,3,4]" / "u32[]" — the result shape an HLO
# instruction materializes; element bit-widths for buffer sizing
_SHAPE_RE = re.compile(r"^\s*(?:ROOT\s+)?%[^\s=]+\s*=\s*"
                       r"(?:\()?([a-z0-9]+)\[([0-9,]*)\]")
_DTYPE_BITS = {
    "pred": 8, "s8": 8, "u8": 8, "s16": 16, "u16": 16, "f16": 16,
    "bf16": 16, "s32": 32, "u32": 32, "f32": 32, "s64": 64, "u64": 64,
    "f64": 64, "c64": 64, "c128": 128, "f8e4m3fn": 8, "f8e5m2": 8,
}

# substrings that identify an allocator / compile-time OOM across jax
# versions and backends (XlaRuntimeError stringifies the status code)
_OOM_MARKS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
              "Out of memory", "out of memory", "OOM",
              "Attempting to allocate")


class HBMOOMError(RuntimeError):
    """An HBM out-of-memory, enriched by the OOM doctor: carries the
    structured report (largest live buffers at the high-water point,
    owning op + user layer, what-ifs) and the memrec dump path."""

    def __init__(self, message: str, report: Optional[dict] = None,
                 dump_path: Optional[str] = None):
        super().__init__(message)
        self.report = report or {}
        self.dump_path = dump_path


def is_oom(exc: BaseException) -> bool:
    """Does this exception smell like an allocator/compile-time OOM?"""
    s = f"{type(exc).__name__}: {exc}"
    return any(m in s for m in _OOM_MARKS)


def hbm_budget_bytes() -> Optional[int]:
    """PADDLE_HBM_BUDGET_BYTES — the operator's declared per-device
    ceiling (CI gates, shared-chip etiquette). None when unset."""
    raw = os.environ.get(ENV_BUDGET)
    if not raw:
        return None
    try:
        v = int(float(raw))
    except ValueError:
        return None
    return v if v > 0 else None


# ---------------------------------------------------------------------------
# measured side: HLO buffer attribution
# ---------------------------------------------------------------------------


def _instr_bytes(line: str) -> int:
    """Byte size of the buffer an HLO instruction line defines; 0 for
    unparseable/tuple shapes (tuples own no bytes themselves)."""
    m = _SHAPE_RE.match(line)
    if m is None:
        return 0
    bits = _DTYPE_BITS.get(m.group(1))
    if bits is None:
        return 0
    n = 1
    dims = m.group(2)
    if dims:
        for d in dims.split(","):
            n *= int(d)
    return (n * bits + 7) // 8


def attribute_hlo_buffers(hlo_text: str) -> Dict[str, Any]:
    """Join every HLO instruction's output-buffer size to its IR op
    scope (cost.parse_hlo_metadata: op_name metadata, fusion splits,
    operand/user propagation). Returns per-op byte rollups plus the
    scoped fraction — the number that says how much of XLA's temp
    traffic the attribution layer can NAME. Entry parameters are
    excluded (they are the argument buffers, attributed by name on the
    static side)."""
    from . import cost

    instrs = cost.parse_hlo_metadata(hlo_text) if hlo_text else {}
    # size only ENTRY-computation instructions: a fused computation's
    # internals live in registers/scratch — its ROOT is the fusion
    # instruction's own buffer, already sized at the call site (sizing
    # both would double-count every fusion)
    sizes: Dict[str, int] = {}
    in_entry = False
    for line in (hlo_text or "").splitlines():
        if line and not line[0].isspace():
            in_entry = line.lstrip().startswith("ENTRY")
            continue
        m = re.match(r"^\s*(?:ROOT\s+)?%([^\s=]+)\s*=", line)
        if m is None or "parameter(" in line or not in_entry:
            continue
        sizes[m.group(1)] = _instr_bytes(line)

    per_op: Dict[str, Dict[str, Any]] = {}
    scoped = 0
    total = 0
    for name, nbytes in sizes.items():
        if not nbytes:
            continue
        total += nbytes
        meta = instrs.get(name)
        scopes = [s for s in (meta["scopes"] if meta else ())
                  if s and s[0] == "op"]
        if not scopes:
            continue
        scoped += nbytes
        w = nbytes / len(scopes)
        for _kind, idx, typ in scopes:
            key = f"op{idx}:{typ}"
            row = per_op.setdefault(key, {"op_index": idx, "op_type": typ,
                                          "bytes": 0.0, "instrs": 0})
            row["bytes"] += w
            row["instrs"] += 1
    for row in per_op.values():
        row["bytes"] = int(row["bytes"])
    return {
        "per_op": per_op,
        "scoped_bytes": int(scoped),
        "total_bytes": int(total),
        "scoped_fraction": (scoped / total) if total else 0.0,
    }


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MemoryReport:
    """The joined picture: static live ranges + measured buffer
    assignment + attribution coverage + what-ifs."""

    model: Optional[str]
    static: Any                       # LiveRangeAnalysis
    measured: Optional[dict] = None   # Executor.memory_analysis() dict
    hlo_attr: Optional[dict] = None   # attribute_hlo_buffers() result
    coverage: Optional[float] = None  # attributed / measured peak
    static_over_measured: Optional[float] = None
    what_ifs: List[dict] = dataclasses.field(default_factory=list)
    budget_bytes: Optional[int] = None

    @property
    def peak_bytes(self) -> int:
        """The best available peak: measured when present, else static."""
        if self.measured and self.measured.get("peak_bytes"):
            return int(self.measured["peak_bytes"])
        return int(self.static.peak_bytes)

    def over_budget(self) -> bool:
        return (self.budget_bytes is not None
                and self.static.peak_bytes > self.budget_bytes)

    def top(self, k: int = 20, live_at_peak_only: bool = False):
        return self.static.top(k, live_at_peak_only=live_at_peak_only)

    def to_json(self, topk: Optional[int] = None) -> dict:
        st = self.static
        out = {
            "model": self.model,
            "static_peak_bytes": int(st.peak_bytes),
            "measured_peak_bytes": (int(self.measured["peak_bytes"])
                                    if self.measured else None),
            "static_over_measured": self.static_over_measured,
            "coverage": (round(self.coverage, 4)
                         if self.coverage is not None else None),
            "budget_bytes": self.budget_bytes,
            "over_budget": self.over_budget(),
            "model_bytes": int(st.model_bytes),
            "resident_bytes": int(st.resident_bytes),
            "batch_hint": st.batch_hint,
            "n_ops": st.n_ops,
            "peak_op_index": st.peak_op_index,
            "peak_op_type": st.peak_op_type,
            "peak_layer": st.peak_layer,
            "categories": dict(st.categories),
            "categories_at_peak": dict(st.categories_at_peak),
            "unsized": list(st.unsized),
            "what_ifs": list(self.what_ifs),
            "buffers": [b.to_json() for b in st.top(topk or 20)],
            "live_at_peak": [b.to_json()
                             for b in st.top(topk or 20,
                                             live_at_peak_only=True)],
        }
        if self.measured:
            out["measured"] = {k: int(v) for k, v in self.measured.items()}
        if self.hlo_attr:
            out["hlo_temp_attribution"] = {
                "scoped_fraction": round(
                    self.hlo_attr["scoped_fraction"], 4),
                "per_op": dict(sorted(
                    self.hlo_attr["per_op"].items(),
                    key=lambda kv: -kv[1]["bytes"])[:topk or 20]),
            }
        return out

    def format_table(self, topk: int = 20) -> str:
        st = self.static
        lines = [
            f"memtop: static peak {_fmt_bytes(st.peak_bytes)}"
            + (f", measured peak {_fmt_bytes(self.measured['peak_bytes'])}"
               f" (static/measured "
               f"{self.static_over_measured:.2f}x)"
               if self.measured and self.static_over_measured else "")
            + (f", coverage {100 * self.coverage:.1f}%"
               if self.coverage is not None else ""),
            "-- categories (total / live at peak) --",
        ]
        for c, v in sorted(st.categories.items(), key=lambda kv: -kv[1]):
            lines.append(f"{c:<18}{_fmt_bytes(v):>12}"
                         f"{_fmt_bytes(st.categories_at_peak[c]):>12}")
        if self.budget_bytes is not None:
            verdict = "OVER" if self.over_budget() else "ok"
            lines.append(f"budget {_fmt_bytes(self.budget_bytes)}: "
                         f"{verdict}")
        lines.append(
            f"high-water at op#{st.peak_op_index}"
            f" [{st.peak_op_type or '?'}]"
            + (f" ({st.peak_layer})" if st.peak_layer else ""))
        lines.append(f"{'buffer':<34}{'bytes':>12}{'cat':>17}"
                     f"{'range':>12}  layer")
        for b in st.top(topk, live_at_peak_only=True):
            lines.append(
                f"{b.name[:33]:<34}{_fmt_bytes(b.bytes):>12}"
                f"{b.category:>17}{f'{b.first_def}..{b.last_use}':>12}"
                f"  {b.layer or '-'}")
        for w in self.what_ifs:
            lines.append(f"what-if: {w['text']}")
        return "\n".join(lines)


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


# last report built in this process — the debugz /memz endpoint
_last_report: Optional[MemoryReport] = None
_last_lock = threading.Lock()
_memz_key = None  # (serial, version, feed-sig) the last report covers


def last_report() -> Optional[MemoryReport]:
    return _last_report


def _set_last(report: MemoryReport) -> None:
    global _last_report
    with _last_lock:
        _last_report = report


def _reset_for_tests() -> None:
    global _last_report, _memz_key
    with _last_lock:
        _last_report = None
        _memz_key = None


# ---------------------------------------------------------------------------
# what-ifs
# ---------------------------------------------------------------------------


def _local_device_count() -> int:
    try:
        import jax

        return max(1, jax.local_device_count())
    except Exception:  # noqa: BLE001 — doctor must work without a device
        return 1


def compute_what_ifs(static, limit_bytes: Optional[int] = None
                     ) -> List[dict]:
    """Concrete levers, ranked by saved bytes: remat the fattest
    activation block, shard the fattest parameter, shrink the batch to
    fit. Estimates ride the static model (documented: upper-bound
    flavored), which is exactly what an OOM victim needs first."""
    out: List[dict] = []
    live = {b.name for b in static.buffers} & set(static.live_at_peak)
    by_name = static.by_name()
    peak = static.peak_bytes

    # remat: group live-at-peak activations by user layer; recomputing
    # the fattest block frees its bytes at the high-water point
    layers: Dict[str, int] = {}
    for n in live:
        b = by_name[n]
        if b.category == "activations" and b.first_def >= 0:
            layers[b.layer or "<unattributed>"] = (
                layers.get(b.layer or "<unattributed>", 0) + b.bytes)
    if layers:
        layer, saved = max(layers.items(), key=lambda kv: kv[1])
        out.append({
            "action": "remat", "target": layer, "saves_bytes": int(saved),
            "text": f"remat the block at {layer} "
                    f"(saves ~{_fmt_bytes(saved)} at the high-water "
                    f"point)"})

    # shard: the fattest parameter split over the local devices
    params = [b for b in static.buffers if b.category == "params"]
    n_dev = _local_device_count()
    shard_over = n_dev if n_dev > 1 else 2
    if params:
        fat = max(params, key=lambda b: b.bytes)
        saved = fat.bytes * (shard_over - 1) // shard_over
        if saved > 0:
            out.append({
                "action": "shard", "target": fat.name,
                "saves_bytes": int(saved),
                "text": f"shard param {fat.name!r} axis 0 over "
                        f"{shard_over} devices (saves "
                        f"~{_fmt_bytes(saved)} per device)"})

    # batch: solve fixed + (N/B) * batch_dep <= limit for N
    if limit_bytes and static.batch_hint:
        batch_dep = sum(b.bytes for n in live
                        if (b := by_name[n]).batch_scaled)
        fixed = peak - batch_dep
        if batch_dep > 0 and fixed < limit_bytes:
            n_fit = int(static.batch_hint
                        * (limit_bytes - fixed) / batch_dep)
            if 0 < n_fit < static.batch_hint:
                out.append({
                    "action": "batch", "target": n_fit,
                    "saves_bytes": int(peak - fixed
                                       - batch_dep * n_fit
                                       / static.batch_hint),
                    "text": f"batch {n_fit} fits the "
                            f"{_fmt_bytes(limit_bytes)} budget "
                            f"(currently {static.batch_hint})"})
    out.sort(key=lambda w: -(w.get("saves_bytes") or 0))
    return out


# ---------------------------------------------------------------------------
# building reports
# ---------------------------------------------------------------------------


def build_memory_report(
    program,
    feed_shapes: Optional[Dict[str, Any]] = None,
    fetch_names=(),
    measured: Optional[dict] = None,
    hlo_text: Optional[str] = None,
    model: Optional[str] = None,
    budget_bytes: Optional[int] = None,
    publish: bool = True,
) -> MemoryReport:
    """Pure join of the static pass with whatever measured inputs the
    caller has (tests drive it with synthetic pieces). Publishes the
    gauges + /memz report and emits a kind="mem_report" sink record
    unless publish=False.

    Coverage definition (the CI bar): of XLA's measured peak
    (arguments + outputs + temps - aliased), the argument/output slice
    is attributed by NAME through the static pass (state and feed vars
    are exactly sizeable), and the temp slice is attributed through the
    HLO op-scope join — coverage = (min(args+outs-alias, static
    name-attributed bytes) + scoped_temp_fraction * temps) / peak."""
    from ..fluid.analysis import analyze_live_ranges

    shapes = {}
    batch_hint = None
    for n, a in (feed_shapes or {}).items():
        shp = tuple(getattr(a, "shape", a) or ())
        shapes[n] = shp
    static = analyze_live_ranges(
        program, feed_names=set(shapes), fetch_names=set(fetch_names),
        shapes=shapes, batch_hint=batch_hint)

    hlo_attr = attribute_hlo_buffers(hlo_text) if hlo_text else None
    coverage = None
    ratio = None
    if measured and measured.get("peak_bytes"):
        peak = int(measured["peak_bytes"])
        # argument/output buffers ARE named program variables (feeds,
        # state, fetches) — attributed by name via the static pass by
        # construction; the temp slice is attributed op-by-op through
        # the HLO scope join, discounted by its unscoped fraction
        args_outs = (measured.get("argument_size_in_bytes", 0)
                     + measured.get("output_size_in_bytes", 0)
                     - measured.get("alias_size_in_bytes", 0))
        covered = float(args_outs)
        temps = measured.get("temp_size_in_bytes", 0)
        if hlo_attr is not None:
            covered += temps * hlo_attr["scoped_fraction"]
        coverage = min(1.0, covered / peak) if peak else 0.0
        ratio = round(static.peak_bytes / peak, 4) if peak else None

    report = MemoryReport(
        model=model, static=static, measured=measured, hlo_attr=hlo_attr,
        coverage=coverage, static_over_measured=ratio,
        budget_bytes=budget_bytes if budget_bytes is not None
        else hbm_budget_bytes(),
    )
    report.what_ifs = compute_what_ifs(
        static, limit_bytes=report.budget_bytes
        or (measured or {}).get("peak_bytes"))
    if publish:
        _publish(report)
    return report


def _publish(report: MemoryReport) -> None:
    reg = get_registry()
    st = report.static
    reg.gauge("hbm_static_peak_bytes",
              help="static live-range peak estimate (bytes)"
              ).set(st.peak_bytes)
    reg.gauge("hbm_model_bytes",
              help="params + optimizer state (bytes)").set(st.model_bytes)
    for cat, v in st.categories.items():
        reg.gauge("hbm_category_bytes",
                  help="static bytes per category",
                  category=cat).set(v)
    if report.coverage is not None:
        reg.gauge("hbm_attribution_coverage",
                  help="fraction of XLA's measured peak attributed to "
                       "IR ops / named state").set(report.coverage)
    _set_last(report)
    try:
        from . import sink

        sink.emit({"kind": "mem_report",
                   "model": report.model,
                   "static_peak_bytes": int(st.peak_bytes),
                   "measured_peak_bytes": (
                       int(report.measured["peak_bytes"])
                       if report.measured else None),
                   "model_bytes": int(st.model_bytes),
                   "coverage": report.coverage,
                   "categories": dict(st.categories)})
    except Exception:  # noqa: BLE001 — diagnostics never fail the caller
        pass


def profile_executor_memory(exe, program, feed, fetch_list, scope=None,
                            model: Optional[str] = None,
                            budget_bytes: Optional[int] = None,
                            ) -> MemoryReport:
    """The full measured join for a runnable step: XLA memory_analysis
    + optimized-HLO buffer attribution (compiled under FLAGS_op_profile
    so instructions carry op scopes) + the static pass. One extra AOT
    compile — diagnostics pricing (memtop, bench hooks), never the step
    path."""
    from ..fluid import flags

    if hasattr(program, "_program"):
        program = program._program
    prev = flags.get_flags("FLAGS_op_profile")["FLAGS_op_profile"]
    flags.set_flags({"FLAGS_op_profile": True})
    try:
        compiled = exe.aot_step(program, feed=feed, fetch_list=fetch_list,
                                scope=scope)
        hlo_text = compiled.as_text()
        measured = exe.memory_analysis(program, feed=feed,
                                       fetch_list=fetch_list, scope=scope)
    finally:
        flags.set_flags({"FLAGS_op_profile": prev})
    from ..fluid import framework as _fw

    fetch_names = [v.name if isinstance(v, _fw.Variable) else str(v)
                   for v in (fetch_list or [])]
    return build_memory_report(
        program, feed_shapes=dict(feed or {}), fetch_names=fetch_names,
        measured=measured, hlo_text=hlo_text, model=model,
        budget_bytes=budget_bytes)


# ---------------------------------------------------------------------------
# executor hooks: FLAGS_mem_profile + budget gate + OOM doctor
# ---------------------------------------------------------------------------


def on_compile(program, feed_arrays, fetch_names) -> None:
    """Called by Executor._ensure_compiled on every compile-cache MISS.
    Flag-off AND budget-unset: one flag read + one env read, nothing
    else (the bit-identity contract). FLAGS_mem_profile on: run the
    static pass, publish gauges + /memz + the kind="mem_report" record.
    PADDLE_HBM_BUDGET_BYTES set: gate the static estimate against the
    budget BEFORE paying (or failing) the XLA compile."""
    from ..fluid.flags import flag

    budget = hbm_budget_bytes()
    if not flag("FLAGS_mem_profile") and budget is None:
        return
    global _memz_key
    try:
        report = build_memory_report(
            program, feed_shapes=feed_arrays, fetch_names=fetch_names,
            budget_bytes=budget)
        _memz_key = (program._serial, program._version)
    except Exception:  # noqa: BLE001 — diagnostics never fail a compile
        return
    if budget is not None and report.static.peak_bytes > budget:
        raise_oom(
            program, feed_arrays, phase="budget", report=report,
            message=(
                f"static HBM estimate "
                f"{_fmt_bytes(report.static.peak_bytes)} exceeds "
                f"PADDLE_HBM_BUDGET_BYTES={_fmt_bytes(budget)}"))


def raise_oom(program, feed_arrays, phase: str,
              error: Optional[BaseException] = None,
              report: Optional[MemoryReport] = None,
              message: Optional[str] = None) -> None:
    """The OOM doctor: build the static report (no device work — the
    device just refused us), dump the memory flight-record through the
    PR-9 flight-recorder path, and raise HBMOOMError naming the largest
    live buffer at the high-water point and the concrete what-ifs."""
    if report is None:
        try:
            report = build_memory_report(
                program, feed_shapes=feed_arrays, publish=False)
        except Exception:  # noqa: BLE001 — a broken doctor must not mask
            report = None  # the original OOM
    doc = _doctor_payload(report, phase, error, message)
    path = dump_memrec(doc)
    get_registry().counter(
        "hbm_oom_total", help="OOMs caught by the doctor",
        phase=phase).inc()
    try:
        from . import tracing

        tracing.annotate(oom_phase=phase)
        tracing.flight_dump(f"oom:{phase}")
    except Exception:  # noqa: BLE001
        pass
    lines = [message or f"HBM out of memory at {phase}"]
    if report is not None:
        st = report.static
        lines.append(
            f"  static peak {_fmt_bytes(st.peak_bytes)} at "
            f"op#{st.peak_op_index} [{st.peak_op_type or '?'}]"
            + (f" ({st.peak_layer})" if st.peak_layer else ""))
        for b in st.top(3, live_at_peak_only=True):
            lines.append(
                f"  {b.name}: {_fmt_bytes(b.bytes)} [{b.category}]"
                + (f" at {b.layer}" if b.layer else ""))
        for w in report.what_ifs[:3]:
            lines.append(f"  what-if: {w['text']}")
    if path:
        lines.append(f"  memory flight-record: {path}")
    raise HBMOOMError("\n".join(lines),
                      report=doc, dump_path=path) from error


def _doctor_payload(report: Optional[MemoryReport], phase: str,
                    error: Optional[BaseException],
                    message: Optional[str]) -> dict:
    doc: Dict[str, Any] = {
        "format": 1,
        "kind": "oom",
        "phase": phase,
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "message": message or (f"{type(error).__name__}: {error}"
                               if error else "out of memory"),
        "budget_bytes": hbm_budget_bytes(),
    }
    if report is not None:
        st = report.static
        doc["report"] = report.to_json(topk=20)
        top = st.top(1, live_at_peak_only=True)
        if top:
            doc["culprit"] = top[0].to_json()
    try:
        from ..fluid import monitor

        doc["devices"] = monitor.device_memory_stats()
    except Exception:  # noqa: BLE001
        doc["devices"] = []
    return doc


def dump_memrec(payload: dict, directory: Optional[str] = None
                ) -> Optional[str]:
    """Atomically write the memory flight-record next to the tracing
    flight recorder's dumps: PADDLE_TRACE_DIR/memrec.<tag>.json. Unlike
    span dumps this does NOT require PADDLE_TRACING — an OOM post-mortem
    is useful without causal tracing armed. None when no directory is
    configured (nothing to do) or the disk refuses (a full disk must
    not mask the OOM)."""
    from . import tracing

    directory = directory or os.environ.get(tracing.ENV_DIR)
    if not directory:
        return None
    path = os.path.join(directory,
                        f"memrec.{tracing.process_tag()}.json")
    try:
        os.makedirs(directory, exist_ok=True)
        tracing._atomic_write(path, json.dumps(payload).encode())
    except OSError:
        return None
    return path


# ---------------------------------------------------------------------------
# debugz /memz
# ---------------------------------------------------------------------------


#: extra /memz sections registered by subsystems that own big standing
#: allocations (e.g. the serving KV pool) — name -> zero-arg callable
#: returning a JSON-able dict.  A section that raises is reported as an
#: error string instead of killing the page.
_MEMZ_SECTIONS: Dict[str, Callable[[], dict]] = {}


def register_memz_section(name: str, fn: Callable[[], dict]) -> None:
    """Attach a named section to the /memz payload (idempotent: the
    latest registration under a name wins)."""
    _MEMZ_SECTIONS[name] = fn


def unregister_memz_section(name: str) -> None:
    _MEMZ_SECTIONS.pop(name, None)


def memz(topk: int = 20) -> dict:
    """The /memz payload: last memory report (per-category breakdown,
    top-K buffers with callstacks) + LIVE per-device allocator stats —
    works report-less too (the live view is always available)."""
    from ..fluid.flags import flag

    devices: List[dict] = []
    try:
        from ..fluid import monitor

        devices = monitor.device_memory_stats()
    except Exception:  # noqa: BLE001 — report pages never crash
        pass
    rep = last_report()
    out = {
        "enabled": bool(flag("FLAGS_mem_profile")),
        "budget_bytes": hbm_budget_bytes(),
        "devices": devices,
        "report": rep.to_json(topk) if rep is not None else None,
    }
    for name, fn in list(_MEMZ_SECTIONS.items()):
        try:
            out[name] = fn()
        except Exception as e:  # noqa: BLE001 — report pages never crash
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out
