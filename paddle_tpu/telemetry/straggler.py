"""Straggler detection from per-rank step-rate samples.

The heartbeat channel (distributed/heartbeat.py) carries each rank's
(monotone step count, timestamp) in its stamp; the launcher feeds those
samples into a StragglerDetector, which derives each rank's recent step
time from consecutive samples and flags any rank whose step time
exceeds `factor` x the median across ranks — the EQuARX-style locate-
the-slow-participant primitive, host-side so it also catches input
stalls and background-process interference that device profiles miss.

Detection is windowed and hysteretic: a rank is reported once per
continuous straggling episode (re-armed when it returns under the
threshold), so the launcher log carries one structured `straggler`
event per incident, not one per poll tick.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

DEFAULT_FACTOR = float(os.environ.get("PADDLE_STRAGGLER_FACTOR", 3.0) or 3.0)
MIN_STEPS = int(os.environ.get("PADDLE_STRAGGLER_MIN_STEPS", 3) or 3)


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


class StragglerDetector:
    """Feed (rank, step, t) samples via observe(); events() drains the
    structured straggler events detected since the last call.

    factor      step-time multiple of the cross-rank median that flags
                a rank (PADDLE_STRAGGLER_FACTOR, default 3.0)
    min_steps   samples ignored until a rank has advanced this many
                steps (compile warmup would otherwise always flag)
    """

    def __init__(self, factor: float = DEFAULT_FACTOR,
                 min_steps: int = MIN_STEPS):
        self.factor = float(factor)
        self.min_steps = int(min_steps)
        # rank -> (last_step, last_t, step_time_s or None)
        self._state: Dict[object, tuple] = {}
        self._flagged: Dict[object, bool] = {}
        self._events: List[dict] = []

    def observe(self, rank, step: int, t: float) -> None:
        last = self._state.get(rank)
        if last is None or step < last[0]:  # first sample / restarted rank
            self._state[rank] = (step, t, None)
            self._flagged.pop(rank, None)
            return
        last_step, last_t, step_time = last
        if step == last_step:
            # no progress: stretch the implied step time as time passes,
            # so a fully wedged rank keeps growing instead of freezing
            # at its last healthy value
            if step_time is not None and t > last_t:
                implied = step_time + (t - last_t)
                self._state[rank] = (last_step, last_t, step_time)
                self._check(rank, implied, step)
            return
        dt = (t - last_t) / (step - last_step)
        self._state[rank] = (step, t, dt)
        if step >= self.min_steps:
            self._check(rank, dt, step)

    def _step_times(self) -> Dict[object, float]:
        return {r: st for r, (_s, _t, st) in self._state.items()
                if st is not None}

    def _check(self, rank, step_time: float, step: int) -> None:
        times = self._step_times()
        times[rank] = step_time
        if len(times) < 2:
            return  # no peers to compare against
        others = [v for r, v in times.items() if r != rank]
        med = _median(others)
        if med <= 0:
            return
        if step_time > self.factor * med:
            if not self._flagged.get(rank):
                self._flagged[rank] = True
                self._events.append({
                    "event": "straggler",
                    "rank": rank,
                    "step": int(step),
                    "step_time_ms": round(step_time * 1e3, 3),
                    "median_step_time_ms": round(med * 1e3, 3),
                    "slowdown": round(step_time / med, 2),
                    # the per-step badput this episode costs vs peers —
                    # what the goodput ledger charges as `stall`
                    "excess_ms": round((step_time - med) * 1e3, 3),
                    "factor": self.factor,
                })
        else:
            self._flagged[rank] = False  # episode over: re-arm

    def events(self) -> List[dict]:
        out, self._events = self._events, []
        return out


def format_event(ev: dict) -> str:
    """One structured log line (grep '\"event\": \"straggler\"')."""
    return f"[telemetry] {json.dumps(ev, sort_keys=True)}"
