"""Process-wide metrics registry: counters, gauges, histograms.

Design targets (ISSUE 4): one registry shared by the executor, the PS
data plane, hapi callbacks and bench.py, so production telemetry and
BENCH_* numbers flow through the same code path; a Prometheus-style
text exposition for scrapers; fixed histogram bucket boundaries so two
processes' histograms merge by plain addition.

Hot-path cost: a counter inc is one dict lookup + one int add under a
lock-free fast path (the instance lock is only taken by histograms and
snapshot/exposition readers). Nothing here touches the filesystem —
the JSONL sink (telemetry.sink) is the only I/O layer, and it is off
unless PADDLE_METRICS_PATH is set.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

# default latency buckets (ms): sub-ms host ops through multi-minute
# compiles. Fixed boundaries — see module docstring.
DEFAULT_MS_BUCKETS = (
    0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1000, 2500, 5000, 10000, 30000, 60000, 300000,
)

# byte-size buckets for RPC payloads (1KiB .. 1GiB)
BYTE_BUCKETS = tuple(float(2 ** p) for p in range(10, 31, 2))


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(v: str) -> str:
    """Prometheus text exposition escaping for label VALUES: backslash,
    double-quote and newline (exposition format 0.0.4 spec) — a path or
    free-text label must not tear the sample line."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labelkey) -> str:
    if not labelkey:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in labelkey)
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing count. `.value` is exact under the GIL
    (int += is a single bytecode-visible read-modify-write per thread;
    contended increments may interleave but never tear)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-set value (float)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n

    def set_max(self, v: float) -> None:
        """High-water update: keep the larger of the current and new
        value (e.g. peak checkpoint save lag)."""
        v = float(v)
        if v > self.value:
            self.value = v


class Histogram:
    """Fixed-boundary histogram: per-bucket counts (non-cumulative
    internally; the exposition emits Prometheus cumulative `le`
    buckets), plus sum/count/min/max for cheap summaries.

    Exemplar (ISSUE 9): observe(v, trace_id=...) remembers the trace of
    the sample currently sitting in the TOP occupied bucket (the running
    max), so a scrape of a latency histogram hands the operator a
    trace_id to feed straight into tools/tracetop.py. Surfaced in the
    OpenMetrics `# {trace_id="..."} v ts` exemplar syntax on the
    matching _bucket line, and in summary()/snapshot(). Callers that
    never pass a trace_id (tracing off) leave the exposition and the
    summary byte-identical to the pre-exemplar format."""

    __slots__ = ("buckets", "counts", "sum", "count", "min", "max",
                 "exemplar", "_lock")

    def __init__(self, buckets: Sequence[float] = DEFAULT_MS_BUCKETS):
        self.buckets = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(self.buckets):
            raise ValueError("histogram buckets must be sorted ascending")
        self.counts = [0] * (len(self.buckets) + 1)  # +1 = +Inf overflow
        self.sum = 0.0
        self.count = 0
        self.min = None
        self.max = None
        self.exemplar: Optional[dict] = None
        self._lock = threading.Lock()

    def observe(self, v: float, trace_id: Optional[str] = None) -> None:
        v = float(v)
        lo, hi = 0, len(self.buckets)
        while lo < hi:  # first bucket boundary >= v
            mid = (lo + hi) // 2
            if v <= self.buckets[mid]:
                hi = mid
            else:
                lo = mid + 1
        with self._lock:
            self.counts[lo] += 1
            self.sum += v
            self.count += 1
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if trace_id is not None and (
                    self.exemplar is None or v >= self.exemplar["value"]):
                self.exemplar = {"trace_id": str(trace_id),
                                 "value": v,
                                 "ts": round(time.time(), 3)}

    def summary(self) -> dict:
        """count/sum/min/max/avg. An EMPTY histogram reports zeros, not
        Nones — consumers (debugz pages, exporters, report arithmetic)
        must never have to None-guard a summary field."""
        with self._lock:
            out = {
                "count": self.count,
                "sum": round(self.sum, 6),
                "min": self.min if self.min is not None else 0.0,
                "max": self.max if self.max is not None else 0.0,
                "avg": round(self.sum / self.count, 6) if self.count else 0.0,
            }
            if self.exemplar is not None:
                out["exemplar"] = dict(self.exemplar)
            return out

    def quantile(self, q: float) -> float:
        """Bucket-boundary estimate of the q-quantile (upper boundary of
        the bucket containing it); max for the overflow bucket. An empty
        histogram reports 0.0 — well-defined instead of None-propagating
        into consumers."""
        with self._lock:
            if not self.count:
                return 0.0
            target = q * self.count
            acc = 0
            for i, c in enumerate(self.counts):
                acc += c
                if acc >= target:
                    return self.buckets[i] if i < len(self.buckets) else self.max
            return self.max


class MetricsRegistry:
    """name (+ labels) -> metric. get-or-create accessors; a name may
    only ever hold one metric type (a counter re-declared as a gauge is
    a bug, raised loudly)."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (type, help, {labelkey: metric})
        self._metrics: Dict[str, tuple] = {}

    def _get(self, name: str, kind: str, help_: str, factory, labels):
        key = _label_key(labels or {})
        with self._lock:
            ent = self._metrics.get(name)
            if ent is None:
                ent = (kind, help_, {})
                self._metrics[name] = ent
            elif ent[0] != kind:
                raise TypeError(
                    f"metric {name!r} already registered as {ent[0]}, "
                    f"not {kind}")
            series = ent[2]
            m = series.get(key)
            if m is None:
                m = series[key] = factory()
            return m

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(name, "counter", help, Counter, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(name, "gauge", help, Gauge, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
                  **labels) -> Histogram:
        return self._get(
            name, "histogram", help, lambda: Histogram(buckets), labels)

    def reset(self) -> None:
        """Drop every metric (tests / per-job reuse)."""
        with self._lock:
            self._metrics.clear()

    # -- read side -------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready dump: {name: {type, series: [{labels, ...}]}}.
        Histograms dump their summary (count/sum/min/max/avg), not raw
        buckets — the exposition format carries the full buckets."""
        with self._lock:
            items = [(n, k, h, dict(s)) for n, (k, h, s)
                     in self._metrics.items()]
        out = {}
        for name, kind, _help, series in items:
            rows = []
            for labelkey, m in sorted(series.items()):
                row = {"labels": dict(labelkey)}
                if kind == "histogram":
                    row.update(m.summary())
                else:
                    row["value"] = m.value
                rows.append(row)
            out[name] = {"type": kind, "series": rows}
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4): HELP/TYPE headers,
        one sample line per series; histograms as cumulative _bucket
        series with an +Inf bucket plus _sum/_count."""
        with self._lock:
            items = [(n, k, h, dict(s)) for n, (k, h, s)
                     in self._metrics.items()]
        lines: List[str] = []
        for name, kind, help_, series in sorted(items):
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            for labelkey, m in sorted(series.items()):
                if kind != "histogram":
                    lines.append(f"{name}{_fmt_labels(labelkey)} {m.value}")
                    continue
                with m._lock:
                    counts, total, s = list(m.counts), m.count, m.sum
                    ex = dict(m.exemplar) if m.exemplar else None
                acc = 0
                for b, c in zip(m.buckets, counts):
                    acc += c
                    lk = labelkey + (("le", f"{b:g}"),)
                    line = f"{name}_bucket{_fmt_labels(lk)} {acc}"
                    if ex is not None and ex["value"] <= b:
                        # OpenMetrics exemplar on the bucket holding the
                        # slowest traced sample; emitted once
                        line += (f' # {{trace_id="{ex["trace_id"]}"}} '
                                 f'{ex["value"]:g} {ex["ts"]}')
                        ex = None
                    lines.append(line)
                lk = labelkey + (("le", "+Inf"),)
                line = f"{name}_bucket{_fmt_labels(lk)} {total}"
                if ex is not None:  # landed in the overflow bucket
                    line += (f' # {{trace_id="{ex["trace_id"]}"}} '
                             f'{ex["value"]:g} {ex["ts"]}')
                lines.append(line)
                lines.append(f"{name}_sum{_fmt_labels(labelkey)} {s}")
                lines.append(f"{name}_count{_fmt_labels(labelkey)} {total}")
        return "\n".join(lines) + ("\n" if lines else "")


# THE process-wide registry (the executor, PS plane, hapi and bench all
# share it; tests that need isolation construct their own or reset())
_global = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _global
