"""Per-step JSONL sink (PADDLE_METRICS_PATH).

One JSON object per line, append-only, flushed per record so a killed
process loses at most the in-flight line. Schema contract (stable —
tools and tests parse it):

  every record    {"kind": str, "ts": float unix seconds, "rank": int}
  kind="step"     step-time breakdown from fluid/monitor.py:
                  {"step": int monotone per process, "data_wait_ms",
                   "compile_ms", "device_ms", "fetch_ms", "ckpt_save_ms",
                   "idle_ms": float gap between consecutive
                   Executor.run calls (the goodput ledger's idle
                   signal; iterator wait in that gap also lands in
                   data_wait_ms — classification is by residual),
                   "cache_hit": bool, "retraces": int cumulative,
                   "peak_hbm_bytes": int}; under PADDLE_TRACING the
                  record additionally carries "trace_id" — the step's
                  root span in the tracing ring (telemetry/tracing.py)
  kind="bench"    one bench.py result row (same keys as its stdout JSON)
  kind="train_epoch"  hapi MetricsLogger epoch summary
  kind="ps_step"  one APPLIED pserver update (distributed/ps_server.py;
                  the pserver arms this sink itself with a per-process
                  `ps` tag in the filename):
                  {"table": str, "mode": "sync"|"async"|"delta",
                   "step": int round/seq, "rows": int, "apply_ms": float}
  kind="numerics" training numerics (telemetry/numerics.py), split by
                  "event":
                  event="stats"      one sampled read of the in-graph
                    stat vars (FLAGS_tensor_stats, every
                    PADDLE_NUMERICS_EVERY steps): {"step": int sample
                    counter, "watch": {label: {"kind":
                    "grad"|"param"|"clip_gnorm", "nan": int,
                    "inf": int, "max_abs": float, "l2": float} —
                    clip_gnorm rows carry {"value", "clip_norm",
                    "clipped"?} instead}}
                  event="amp_scale"  one AMP dynamic-loss-scale
                    transition: {"step", "change": "growth"|"backoff",
                    "old", "new", "scale_var"}
                  event="doctor"     the NaN-provenance doctor ran:
                    {"reason", "op_index"?, "op_type"?, "output_var"?}
                    (full report: the numrec.<tag>.json dump)
                  event="divergence" a cross-replica SDC verdict
                    reached this rank: {"step", "odd_rank_out",
                    "method", "detected_step"}
  kind="goodput"  goodput/badput ledger summary (telemetry/goodput.py,
                  every PADDLE_GOODPUT_EVERY classification points when
                  PADDLE_GOODPUT=1): {"event": "summary", "tag",
                  "incarnation": int (PADDLE_ELASTIC_RESTART), "t0",
                  "t1", "steps": int, "goodput_ratio": float|null,
                  "buckets_ms": {bucket: cumulative ms for the eight
                  goodput.BUCKETS}}; the authoritative per-interval
                  rows live in goodput.<tag>.<incarnation>.jsonl under
                  PADDLE_GOODPUT_DIR (default PADDLE_TRACE_DIR)
  kind="serve_request"  one RETIRED generation request
                  (inference/engine.py, any outcome — the serving
                  flight ledger): {"trace": str|null (the request's
                  trace id when PADDLE_TRACING was on, else null),
                   "outcome": "served"|"shed"|"deadline_exceeded"|
                   "error", "prompt_len": int, "tokens": int delivered
                   (including a resumed prefix), "queue_ms": float
                   cumulative admission-queue wait (re-queues after
                   preemption accumulate), "ttft_ms": float|null
                   admission to first token, "total_ms": float
                   admission to retire, "preempts": int,
                   "resumed_from": int prefix length a resume carried
                   in, "weight_epoch": int, "detail"?: str error
                   text}; the same record feeds debugz /servez
                  ("recent_slowest") and, when tracing is on, the
                  flight-recorder dump's "requests" array that
                  tools/reqtop.py joins onto the span reconstruction
  kind="mem_report"  one static memory attribution (telemetry/memory.py,
                  emitted per compile-cache miss under FLAGS_mem_profile
                  and by explicit memtop/bench joins):
                  {"model": str|null, "static_peak_bytes": int,
                   "measured_peak_bytes": int|null, "model_bytes": int,
                   "coverage": float|null, "categories": {category: int}}

The sink is OFF (every emit a no-op costing one attribute read) unless
PADDLE_METRICS_PATH is set or enable(path) is called — the flag-off hot
path does no I/O and allocates nothing.

A `%r`/`{rank}` placeholder in the path expands to the trainer rank so
launched jobs don't interleave writers; otherwise a rank suffix is
appended automatically when PADDLE_TRAINER_ID > 0. When
PADDLE_TRAINER_ID is UNSET (processes not started by the launcher), the
placeholder — and the `.rank0` that two un-launched local processes
would otherwise collide on — falls back to the PID, so sharing one
PADDLE_METRICS_PATH template across ad-hoc processes yields one file
each. An explicit placeholder-free path stays exactly as given (the
single-process contract tools and CI read).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import IO, Optional

ENV_PATH = "PADDLE_METRICS_PATH"


def _rank() -> int:
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))
    except ValueError:
        return 0


def _expand(path: str, rank: int) -> str:
    # no launcher rank: two local processes sharing one path template
    # must not interleave into a single file — the PID is the suffix
    launched = "PADDLE_TRAINER_ID" in os.environ
    tag = str(rank) if launched else f"pid{os.getpid()}"
    if "{rank}" in path:
        return path.replace("{rank}", tag)
    if "%r" in path:
        return path.replace("%r", tag)
    if rank:
        root, ext = os.path.splitext(path)
        return f"{root}.rank{rank}{ext or '.jsonl'}"
    return path


class JsonlSink:
    def __init__(self, path: str):
        self.rank = _rank()
        self.path = _expand(path, self.rank)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f: Optional[IO] = None
        self._lock = threading.Lock()

    def emit(self, record: dict) -> None:
        rec = dict(record)
        rec.setdefault("ts", round(time.time(), 6))
        rec.setdefault("rank", self.rank)
        line = json.dumps(rec, default=_json_default)
        with self._lock:
            if self._f is None:
                self._f = open(self.path, "a", buffering=1)
            self._f.write(line + "\n")

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def _json_default(v):
    """numpy / jax scalars slip into records from fetch lists."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


_sink: Optional[JsonlSink] = None
_resolved = False
_lock = threading.Lock()


def active_sink() -> Optional[JsonlSink]:
    """The process sink, or None when telemetry output is off. Resolved
    once from PADDLE_METRICS_PATH; enable()/disable() override."""
    global _sink, _resolved
    if _resolved:
        return _sink
    with _lock:
        if not _resolved:
            path = os.environ.get(ENV_PATH)
            _sink = JsonlSink(path) if path else None
            _resolved = True
    return _sink


def enabled() -> bool:
    return active_sink() is not None


def enable(path: str) -> JsonlSink:
    global _sink, _resolved
    with _lock:
        if _sink is not None:
            _sink.close()
        _sink = JsonlSink(path)
        _resolved = True
    return _sink


def disable() -> None:
    global _sink, _resolved
    with _lock:
        if _sink is not None:
            _sink.close()
        _sink = None
        _resolved = True


def emit(record: dict) -> None:
    """Write one record if the sink is on; free no-op otherwise."""
    s = active_sink()
    if s is not None:
        s.emit(record)
