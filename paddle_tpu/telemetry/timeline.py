"""Distributed timeline: merge per-rank chrome traces into one file.

Each launched trainer writes its own chrome-trace JSON (fluid/profiler:
stop_profiler or export_chrome_trace, auto-dumped when the launcher
sets PADDLE_TRACE_DIR). This module merges them into a single trace the
way tools/timeline.py did for the reference's per-trainer profiles:
rank r's events land under pid = r * PID_STRIDE + original_pid, with a
process_name metadata row naming the rank, so Perfetto shows one
swimlane group per rank (host track + device tracks side by side).
"""
from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional

# per-rank pid namespace: profiler.py uses pid 0 for host and 1+ per
# device plane, far below this stride
PID_STRIDE = 100

TRACE_NAME_RE = re.compile(r"trace\.(?P<rank>\w+)\.json$")


def rank_trace_path(directory: str, rank) -> str:
    return os.path.join(directory, f"trace.{rank}.json")


def find_rank_traces(directory: str) -> Dict[str, str]:
    """{rank: path} for every per-rank trace in `directory`."""
    out = {}
    for p in sorted(glob.glob(os.path.join(directory, "trace.*.json"))):
        m = TRACE_NAME_RE.search(os.path.basename(p))
        if m:
            out[m.group("rank")] = p
    return out


def merge_traces(directory: str, out_path: Optional[str] = None) -> Optional[str]:
    """Merge `<directory>/trace.<rank>.json` files into
    `<directory>/timeline.json` (or `out_path`). Returns the output path,
    or None when no per-rank traces exist. Unreadable files are skipped
    with a warning line rather than failing the merge — a crashed rank
    must not cost the surviving ranks' timeline."""
    traces = find_rank_traces(directory)
    if not traces:
        return None
    out_path = out_path or os.path.join(directory, "timeline.json")
    merged: List[dict] = []
    for rank, path in traces.items():
        try:
            with open(path) as f:
                events = json.load(f).get("traceEvents", [])
        except (OSError, ValueError) as e:
            print(f"[telemetry] skipping unreadable trace {path}: {e}")
            continue
        try:
            base = int(rank) * PID_STRIDE
            label = f"rank {rank}"
        except ValueError:  # string tags (ps0) ride above the trainers
            base = (10_000 + abs(hash(rank)) % 1000) * PID_STRIDE
            label = str(rank)
        seen_pids = set()
        for ev in events:
            ev = dict(ev)
            pid = int(ev.get("pid", 0))
            ev["pid"] = base + pid
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                # prefix the rank so Perfetto's process list reads
                # "rank 1 / device: TPU:0"
                args = dict(ev.get("args", {}))
                args["name"] = f"{label} / {args.get('name', '')}".rstrip(" /")
                ev["args"] = args
                seen_pids.add(pid)
            merged.append(ev)
        if 0 not in seen_pids:  # host pid had no metadata row
            merged.append({"name": "process_name", "ph": "M", "pid": base,
                           "args": {"name": f"{label} / host"}})
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, out_path)
    return out_path
