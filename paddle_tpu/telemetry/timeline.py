"""Distributed timeline: merge per-rank chrome traces into one file.

Each launched trainer writes its own chrome-trace JSON (fluid/profiler:
stop_profiler or export_chrome_trace, auto-dumped when the launcher
sets PADDLE_TRACE_DIR). This module merges them into a single trace the
way tools/timeline.py did for the reference's per-trainer profiles:
rank r's events land under pid = r * PID_STRIDE + original_pid, with a
process_name metadata row naming the rank, so Perfetto shows one
swimlane group per rank (host track + device tracks side by side).

Non-trainer processes get DETERMINISTIC pid bases too (ISSUE 9): the
pserver span dumps ("ps0", "ps1", ...) land above the trainer ranks and
the launcher-hosted coordinator ("coord") above those, so timeline.json
spans the whole job — the same pid scheme tools/tracetop.py labels its
merged causal traces with.
"""
from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional, Tuple

# per-rank pid namespace: profiler.py uses pid 0 for host and 1+ per
# device plane, far below this stride
PID_STRIDE = 100

# non-trainer process lanes: pservers ride above any plausible trainer
# rank, the coordinator above the pservers, unknown string tags last
PS_PID_BASE = 10_000
COORD_PID_BASE = 20_000
OTHER_PID_BASE = 30_000

TRACE_NAME_RE = re.compile(r"trace\.(?P<rank>\w+)\.json$")
_PS_TAG_RE = re.compile(r"ps(\d+)$")


def process_pid_base(rank) -> Tuple[int, str]:
    """(pid base, display label) for a per-process trace tag — trainer
    ranks by number, pserver tags and the coordinator deterministically
    above them. Shared by merge_traces and tools/tracetop.py so both
    views name processes identically."""
    try:
        return int(rank) * PID_STRIDE, f"rank {rank}"
    except (TypeError, ValueError):
        pass
    m = _PS_TAG_RE.fullmatch(str(rank))
    if m:
        return (PS_PID_BASE + int(m.group(1))) * PID_STRIDE, str(rank)
    if str(rank) in ("coord", "coordinator"):
        return COORD_PID_BASE * PID_STRIDE, "coordinator"
    return ((OTHER_PID_BASE + abs(hash(str(rank))) % 1000) * PID_STRIDE,
            str(rank))


def rank_trace_path(directory: str, rank) -> str:
    return os.path.join(directory, f"trace.{rank}.json")


def find_rank_traces(directory: str) -> Dict[str, str]:
    """{rank: path} for every per-rank trace in `directory`."""
    out = {}
    for p in sorted(glob.glob(os.path.join(directory, "trace.*.json"))):
        m = TRACE_NAME_RE.search(os.path.basename(p))
        if m:
            out[m.group("rank")] = p
    return out


def merge_traces(directory: str, out_path: Optional[str] = None) -> Optional[str]:
    """Merge `<directory>/trace.<rank>.json` files into
    `<directory>/timeline.json` (or `out_path`). Returns the output path,
    or None when no per-rank traces exist. Unreadable files are skipped
    with a warning line rather than failing the merge — a crashed rank
    must not cost the surviving ranks' timeline."""
    traces = find_rank_traces(directory)
    if not traces:
        return None
    out_path = out_path or os.path.join(directory, "timeline.json")
    merged: List[dict] = []
    for rank, path in traces.items():
        try:
            with open(path) as f:
                events = json.load(f).get("traceEvents", [])
        except (OSError, ValueError) as e:
            print(f"[telemetry] skipping unreadable trace {path}: {e}")
            continue
        base, label = process_pid_base(rank)
        seen_pids = set()
        for ev in events:
            ev = dict(ev)
            pid = int(ev.get("pid", 0))
            ev["pid"] = base + pid
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                # prefix the rank so Perfetto's process list reads
                # "rank 1 / device: TPU:0"
                args = dict(ev.get("args", {}))
                args["name"] = f"{label} / {args.get('name', '')}".rstrip(" /")
                ev["args"] = args
                seen_pids.add(pid)
            merged.append(ev)
        if 0 not in seen_pids:  # host pid had no metadata row
            merged.append({"name": "process_name", "ph": "M", "pid": base,
                           "args": {"name": f"{label} / host"}})
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, out_path)
    return out_path
