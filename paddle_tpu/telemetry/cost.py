"""Per-op device-time attribution (ISSUE 6): join xplane device events
back to Program IR ops.

The Executor lowers a whole block into ONE jitted XLA computation, so a
step's device time is a single opaque span — PR 4's breakdown says where
the step went (data/compile/device/fetch) but nothing says which op
inside device_ms is hot, which is exactly the visibility gap operator
fusion creates (arXiv:2301.13062). This module closes it:

  1. FLAGS_op_profile makes the Executor wrap each op's lowering in
     jax.named_scope("op<idx>:<type>") (ops/registry.emit_ops), so every
     HLO instruction's op_name metadata carries the Program IR position
     of the op that produced it.
  2. fluid/profiler.xplane_op_events aggregates the device trace's op
     executions by HLO instruction name.
  3. parse_hlo_metadata reads the optimized HLO text
     (Executor.aot_step(...).as_text()) to map instruction -> op_name —
     including the instructions INSIDE fused computations, so an XLA
     fusion covering ops 3..7 is split pro-rata across those scopes and
     marked fused=True instead of being charged to one op.
  4. build_cost_report joins the two through the scope names, rolls the
     rows up per op / op type / user layer call (PR 5's __op_callstack__
     attribution), and derives the measured-MFU gauge.

Measured MFU definition (documented contract, asserted by CI): measured
flops come from the xplane per-op flop counters where the backend
reports them (TPU op profile) and otherwise from XLA's own cost model
(Compiled.cost_analysis()["flops"]); the time base is the ATTRIBUTED
per-step device-op time. The cross-check gauge `formula_mfu` applies
bench.py's closed-form model flops to the SAME time base, so the ratio
measured/formula compares pure flop accounting: XLA counts every
elementwise/normalization op and the exact backward, the model formula
counts 3x the forward matmul/conv MACs — agreement within a factor of 2
is the documented tolerance (typically well inside ±30% on the bench
models).

Everything heavier than stdlib (jax, protobuf) is imported inside
functions: the launcher/pserver processes import paddle_tpu.telemetry
without pulling an accelerator runtime.
"""
from __future__ import annotations

import dataclasses
import json
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from .registry import get_registry

# "op<idx>:<type>" scope component emitted by ops/registry.emit_ops; the
# FIRST occurrence in an op_name path is the top-level (block 0) op —
# sub-block emitters nest their scopes under the parent op's
_SCOPE_RE = re.compile(r"\bop(\d+):([A-Za-z0-9_.]+)")
# "fwk:<name>" — executor framework compute (rng advance, fetch sync):
# named device time that belongs to no Program op but must not read as
# unattributed mystery
_FWK_RE = re.compile(r"\bfwk:([A-Za-z0-9_.]+)")

# one optimized-HLO instruction: "%name = ..." or "ROOT %name = ..."
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%([^\s=]+)\s*=")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_CALLS_RE = re.compile(r"calls=%([^\s,)]+)")
_COMP_HEAD_RE = re.compile(r"^(?:ENTRY\s+)?%?([^\s(]+)\s*\(")


def extract_scope(op_name: str) -> Optional[Tuple[int, str]]:
    """(op index, op type) from an HLO op_name path, or None when the
    instruction was not lowered under an op scope (parameters, infeed,
    runtime-inserted copies)."""
    m = _SCOPE_RE.search(op_name or "")
    if m is None:
        return None
    return int(m.group(1)), m.group(2)


def _any_scope(op_name: str) -> Optional[tuple]:
    """("op", idx, type) | ("fwk", name) | None for an op_name path."""
    sc = extract_scope(op_name)
    if sc is not None:
        return ("op",) + sc
    m = _FWK_RE.search(op_name or "")
    if m is not None:
        return ("fwk", m.group(1))
    return None


_REF_RE = re.compile(r"%([A-Za-z0-9_.\-]+)")


def parse_hlo_metadata(hlo_text: str) -> Dict[str, Dict[str, Any]]:
    """instruction name -> {op_name, fusion_calls, scopes} from optimized
    HLO text. `scopes` is the list of scope tuples (("op", idx, type) or
    ("fwk", name)) found on the instruction — for a fusion, the scopes
    of every instruction inside its fused computation (the pro-rata
    split set); for a plain instruction, its own op_name's scope.

    Instructions the backend materialized WITHOUT metadata — layout-
    assignment copies/transposes, rewritten backward convolutions — are
    attributed by graph neighborhood (the grouping XLA's own op profile
    applies): scopes propagate transitively from operands first, then
    from users, so a layout copy feeding a convolution is charged to
    that convolution's op."""
    comps: Dict[str, List[Tuple[str, Optional[tuple]]]] = {}
    instrs: Dict[str, Dict[str, Any]] = {}
    current: Optional[str] = None
    for line in hlo_text.splitlines():
        if line and not line[0].isspace():
            head = _COMP_HEAD_RE.match(line.strip())
            current = head.group(1) if head else None
            if current is not None:
                comps.setdefault(current, [])
            continue
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        name = m.group(1)
        body = line.split("=", 1)[1] if "=" in line else ""
        opn = _OP_NAME_RE.search(line)
        op_name = opn.group(1) if opn else ""
        scope = _any_scope(op_name)
        if current is not None:
            comps[current].append((name, scope))
        calls = _CALLS_RE.search(line)
        instrs[name] = {
            "op_name": op_name,
            "fusion_calls": calls.group(1) if calls else None,
            "scopes": [scope] if scope else [],
            "operands": [r for r in _REF_RE.findall(body) if r != name],
            "computation": current,
        }
    # resolve fusions: the split set is the multiset of scopes inside the
    # called computation (instruction count is the pro-rata weight — the
    # only weight the HLO text supports uniformly; documented)
    for meta in instrs.values():
        comp = meta["fusion_calls"]
        if comp and comp in comps:
            inner = [s for _n, s in comps[comp] if s is not None]
            if inner:
                meta["scopes"] = inner
    _propagate_scopes(instrs)
    return instrs


def _propagate_scopes(instrs: Dict[str, Dict[str, Any]]) -> None:
    """Transitive neighborhood attribution for metadata-less
    instructions: operands first (a copy BELONGS to what it was copied
    from/for), then users, each to a fixed point. Scope sets acquired
    here are deduplicated — a propagated instruction splits pro-rata
    across its distinct neighboring ops."""
    # same-computation edges only: a fusion body's params don't reference
    # entry instructions by name, so cross-computation noise is already
    # structurally impossible; users is the reverse view
    users: Dict[str, List[str]] = {}
    for name, meta in instrs.items():
        for ref in meta["operands"]:
            if ref in instrs:
                users.setdefault(ref, []).append(name)
    for edges in (lambda n: instrs[n]["operands"],
                  lambda n: users.get(n, ())):
        changed = True
        while changed:
            changed = False
            for name, meta in instrs.items():
                if meta["scopes"]:
                    continue
                found: List[tuple] = []
                for ref in edges(name):
                    other = instrs.get(ref)
                    if other and other["scopes"]:
                        for s in other["scopes"]:
                            if s not in found:
                                found.append(s)
                if found:
                    meta["scopes"] = found
                    changed = True


@dataclasses.dataclass
class CostRow:
    """One attributed op: device time + Program IR identity."""

    scope: str                      # "op<idx>:<type>"
    op_index: int
    op_type: str
    device_ms: float                # total over the profiled window
    share: float                    # of attributed device-op time
    count: int                      # event executions aggregated
    fused: bool                     # any slice arrived via a fusion split
    flops: float = 0.0              # backend-reported, 0 where absent
    bytes_accessed: int = 0         # backend-reported, 0 where absent
    layer: Optional[str] = None     # "file:line in fn" user layer call
    callstack: Optional[tuple] = None

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("callstack", None)
        return d


@dataclasses.dataclass
class CostReport:
    """The joined profile: per-op rows + rollups + MFU gauges."""

    rows: List[CostRow]
    by_op_type: Dict[str, float]          # op type -> device_ms
    by_layer: Dict[str, float]            # user layer call -> device_ms
    framework: Dict[str, float]           # fwk scope (rng...) -> device_ms
    unattributed: Dict[str, float]        # instr name -> device_ms
    steps: int
    total_op_ms: float                    # all op executions
    attributed_ms: float                  # carried an op scope
    coverage: float                       # attributed / total
    device_ms_per_step: float
    measured_flops_per_step: Optional[float] = None
    formula_flops_per_step: Optional[float] = None
    peak_flops: Optional[float] = None
    measured_mfu: Optional[float] = None
    formula_mfu: Optional[float] = None
    peak_hbm_bytes: Optional[int] = None
    model: Optional[str] = None

    def top(self, k: int = 20) -> List[CostRow]:
        return sorted(self.rows, key=lambda r: -r.device_ms)[:k]

    # -- programmatic per-op queries (ISSUE 13: the autotuner ranks a
    # candidate by ITS OWN measured device time, not the whole step's) --
    def rows_for(self, op_type: Optional[str] = None,
                 op_index: Optional[int] = None) -> List[CostRow]:
        """Attributed rows filtered by op type and/or Program IR op
        index (None = don't filter on that axis)."""
        out = []
        for r in self.rows:
            if op_type is not None and r.op_type != op_type:
                continue
            if op_index is not None and r.op_index != op_index:
                continue
            out.append(r)
        return out

    def device_ms_for(self, op_type: Optional[str] = None,
                      op_index: Optional[int] = None,
                      per_step: bool = True) -> float:
        """Total attributed device time (ms) of the matching op scopes —
        per profiled step by default, over the whole window with
        per_step=False. 0.0 when nothing matched (caller decides whether
        to fall back to wall latency)."""
        total = sum(r.device_ms for r in self.rows_for(op_type, op_index))
        return total / self.steps if per_step else total

    def to_json(self, topk: Optional[int] = None) -> dict:
        rows = self.top(topk) if topk else sorted(
            self.rows, key=lambda r: -r.device_ms)
        return {
            "model": self.model,
            "steps": self.steps,
            "total_op_ms": round(self.total_op_ms, 3),
            "attributed_ms": round(self.attributed_ms, 3),
            "coverage": round(self.coverage, 4),
            "device_ms_per_step": round(self.device_ms_per_step, 3),
            "measured_flops_per_step": self.measured_flops_per_step,
            "formula_flops_per_step": self.formula_flops_per_step,
            "peak_flops": self.peak_flops,
            "measured_mfu": self.measured_mfu,
            "formula_mfu": self.formula_mfu,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "by_op_type": {k: round(v, 3) for k, v in sorted(
                self.by_op_type.items(), key=lambda kv: -kv[1])},
            "by_layer": {k: round(v, 3) for k, v in sorted(
                self.by_layer.items(), key=lambda kv: -kv[1])},
            "framework": {k: round(v, 3) for k, v in sorted(
                self.framework.items(), key=lambda kv: -kv[1])},
            "unattributed": {k: round(v, 3) for k, v in sorted(
                self.unattributed.items(), key=lambda kv: -kv[1])[:10]},
            "rows": [r.to_json() for r in rows],
        }

    def format_table(self, topk: int = 20) -> str:
        lines = [
            f"proftop: {self.steps} step(s), "
            f"{self.device_ms_per_step:.3f} ms device-op time/step, "
            f"coverage {100 * self.coverage:.1f}%"
        ]
        if self.measured_mfu is not None:
            lines.append(
                f"measured MFU {self.measured_mfu:.4f}"
                + (f" (model formula {self.formula_mfu:.4f})"
                   if self.formula_mfu is not None else ""))
        lines.append(f"{'op':<34}{'ms':>10}{'share':>8}{'fused':>7}  layer")
        for r in self.top(topk):
            lines.append(
                f"{r.scope[:33]:<34}{r.device_ms:>10.3f}"
                f"{100 * r.share:>7.1f}%{'  yes' if r.fused else '   no':>7}"
                f"  {r.layer or '-'}")
        if self.by_op_type:
            lines.append("-- by op type --")
            for t, ms in sorted(self.by_op_type.items(),
                                key=lambda kv: -kv[1])[:topk]:
                lines.append(f"{t:<34}{ms:>10.3f}")
        return "\n".join(lines)


# last report built in this process — /proftop on the debugz server
_last_report: Optional[CostReport] = None
_last_lock = threading.Lock()


def last_report() -> Optional[CostReport]:
    return _last_report


def _set_last(report: CostReport) -> None:
    global _last_report
    with _last_lock:
        _last_report = report


def _layer_of(op) -> Tuple[Optional[str], Optional[tuple]]:
    """'file:line in fn' of the user's layer call for a Program op, via
    PR 5's __op_callstack__ attribution."""
    cs = op.attrs.get("__op_callstack__") if op is not None else None
    if not cs:
        return None, None
    from ..fluid.analysis import user_frame

    uf = user_frame(cs)
    if uf is None:
        return None, cs
    return f"{uf[0]}:{uf[1]} in {uf[2]}", cs


def build_cost_report(
    op_events: Dict[str, Dict[str, Any]],
    hlo_text: str,
    program=None,
    steps: int = 1,
    measured_flops_per_step: Optional[float] = None,
    formula_flops_per_step: Optional[float] = None,
    peak_flops: Optional[float] = None,
    peak_hbm_bytes: Optional[int] = None,
    model: Optional[str] = None,
) -> CostReport:
    """Join aggregated xplane op executions (profiler.xplane_op_events)
    with the compiled HLO's op_name metadata and the Program IR. Pure
    function over its inputs — tests drive it with synthetic events.
    Publishes the measured-MFU / coverage gauges into the process
    registry and stores the report for the debugz /proftop endpoint."""
    instrs = parse_hlo_metadata(hlo_text) if hlo_text else {}
    steps = max(1, int(steps))

    per_scope: Dict[tuple, Dict[str, Any]] = {}
    framework: Dict[str, float] = {}
    unattributed: Dict[str, float] = {}
    total_ps = 0
    attributed_ps = 0
    for name, ev in op_events.items():
        dur = int(ev.get("dur_ps", 0))
        total_ps += dur
        meta = instrs.get(name)
        scopes = meta["scopes"] if meta else []
        if not scopes:
            unattributed[name] = unattributed.get(name, 0.0) + dur / 1e9
            continue
        attributed_ps += dur
        fused = len(set(scopes)) > 1 or bool(meta.get("fusion_calls"))
        # pro-rata split across the scopes inside the instruction
        # (fusions carry one entry per fused inner instruction, so a
        # scope covering more of the fusion body gets more of its time)
        w = 1.0 / len(scopes)
        for sc in scopes:
            if sc[0] == "fwk":
                framework[sc[1]] = framework.get(sc[1], 0.0) + dur * w / 1e9
                continue
            row = per_scope.setdefault(sc, {
                "dur_ps": 0.0, "count": 0, "fused": False,
                "flops": 0.0, "bytes": 0.0,
            })
            row["dur_ps"] += dur * w
            row["count"] += ev.get("count", 1)
            row["fused"] = row["fused"] or fused
            row["flops"] += float(ev.get("flops", 0.0)) * w
            row["bytes"] += float(ev.get("bytes_accessed", 0)) * w

    block_ops = list(program.global_block().ops) if program is not None else []
    rows: List[CostRow] = []
    by_type: Dict[str, float] = {}
    by_layer: Dict[str, float] = {}
    for (_kind, idx, typ), agg in per_scope.items():
        ms = agg["dur_ps"] / 1e9
        op = block_ops[idx] if 0 <= idx < len(block_ops) else None
        # the scope carries the type it was traced with; a mismatch means
        # the program was rewritten since profiling — keep the traced type
        layer, cs = _layer_of(op)
        rows.append(CostRow(
            scope=f"op{idx}:{typ}", op_index=idx, op_type=typ,
            device_ms=ms,
            share=(agg["dur_ps"] / attributed_ps) if attributed_ps else 0.0,
            count=agg["count"], fused=agg["fused"],
            flops=agg["flops"], bytes_accessed=int(agg["bytes"]),
            layer=layer, callstack=cs,
        ))
        by_type[typ] = by_type.get(typ, 0.0) + ms
        if layer:
            by_layer[layer] = by_layer.get(layer, 0.0) + ms

    total_ms = total_ps / 1e9
    attributed_ms = attributed_ps / 1e9
    device_s_per_step = (attributed_ms / 1e3) / steps
    # xplane per-op flop counters win when the backend stamped any
    # (TPU op profile); otherwise the caller passes XLA's cost model
    if measured_flops_per_step is None:
        xp_flops = sum(r.flops for r in rows)
        if xp_flops > 0:
            measured_flops_per_step = xp_flops / steps
    measured_mfu = formula_mfu = None
    if peak_flops and device_s_per_step > 0:
        if measured_flops_per_step:
            measured_mfu = round(
                measured_flops_per_step / device_s_per_step / peak_flops, 6)
        if formula_flops_per_step:
            formula_mfu = round(
                formula_flops_per_step / device_s_per_step / peak_flops, 6)

    report = CostReport(
        rows=rows, by_op_type=by_type, by_layer=by_layer,
        framework=framework, unattributed=unattributed, steps=steps,
        total_op_ms=total_ms, attributed_ms=attributed_ms,
        coverage=(attributed_ms / total_ms) if total_ms else 0.0,
        device_ms_per_step=attributed_ms / steps,
        measured_flops_per_step=measured_flops_per_step,
        formula_flops_per_step=formula_flops_per_step,
        peak_flops=peak_flops,
        measured_mfu=measured_mfu, formula_mfu=formula_mfu,
        peak_hbm_bytes=peak_hbm_bytes, model=model,
    )
    reg = get_registry()
    reg.gauge("op_profile_coverage",
              help="fraction of device-op time attributed to op scopes"
              ).set(report.coverage)
    reg.gauge("op_profile_device_ms_per_step",
              help="attributed device-op time per profiled step (ms)"
              ).set(report.device_ms_per_step)
    if measured_mfu is not None:
        reg.gauge("measured_mfu",
                  help="measured flops / attributed device time / peak "
                       "(xplane counters or XLA cost model; see "
                       "telemetry/cost.py for the definition)"
                  ).set(measured_mfu)
    _set_last(report)
    return report


def profile_executor_run(exe, program, feed, fetch_list, scope=None,
                         steps: int = 3, warmup: int = 1,
                         formula_flops_per_step: Optional[float] = None,
                         peak_flops: Optional[float] = None,
                         model: Optional[str] = None) -> CostReport:
    """End-to-end per-op profile of an Executor step: enable
    FLAGS_op_profile, warm the compile cache, trace `steps` runs under
    the jax profiler, AOT-recover the optimized HLO (one extra compile —
    diagnostics pricing), and join everything into a CostReport.
    tools/proftop.py and bench.py's BENCH_OP_PROFILE hook both ride
    this."""
    import shutil
    import tempfile

    from ..fluid import flags
    from ..fluid import monitor
    from ..fluid import profiler as prof

    prev = flags.get_flags("FLAGS_op_profile")["FLAGS_op_profile"]
    flags.set_flags({"FLAGS_op_profile": True})
    trace_dir = tempfile.mkdtemp(prefix="paddle_tpu_cost_")
    try:
        import jax

        for _ in range(max(1, warmup)):
            out = exe.run(program, feed=feed, fetch_list=fetch_list,
                          scope=scope)
        jax.profiler.start_trace(trace_dir)
        try:
            for _ in range(steps):
                out = exe.run(program, feed=feed, fetch_list=fetch_list,
                              scope=scope, return_numpy=False)
            jax.block_until_ready(out)
        finally:
            jax.profiler.stop_trace()
        compiled = exe.aot_step(program, feed=feed, fetch_list=fetch_list,
                                scope=scope)
        hlo_text = compiled.as_text()
        measured = _cost_analysis_flops(compiled)
        if peak_flops is None:
            peak_flops = peak_flops_per_chip()
        return build_cost_report(
            prof.xplane_op_events(trace_dir), hlo_text,
            program=program if not hasattr(program, "_program")
            else program._program,
            steps=steps,
            measured_flops_per_step=measured,
            formula_flops_per_step=formula_flops_per_step,
            peak_flops=peak_flops,
            peak_hbm_bytes=monitor.peak_hbm_bytes() or None,
            model=model,
        )
    finally:
        flags.set_flags({"FLAGS_op_profile": prev})
        shutil.rmtree(trace_dir, ignore_errors=True)


def _cost_analysis_flops(compiled) -> Optional[float]:
    """Per-execution flops from XLA's cost model; None when the backend
    cannot report it. jax returns a dict or a one-element list of dicts
    depending on version."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        return flops or None
    except Exception:  # noqa: BLE001 — diagnostics never fail the profile
        return None


def peak_flops_per_chip() -> float:
    """bf16 peak FLOP/s for the local chip (best-effort detect). THE
    table — bench.py delegates here so the MFU denominators of the bench
    rows and the measured gauge can never drift apart."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    table = {
        "v5 lite": 197e12,  # v5e
        "v5e": 197e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v6": 918e12,  # trillium
        "v3": 123e12,
        "v2": 45e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 197e12  # conservative default


def report_to_json_line(report: CostReport, topk: Optional[int] = None) -> str:
    return json.dumps(report.to_json(topk))
