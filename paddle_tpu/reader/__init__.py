"""Reader decorators (reference python/paddle/reader/decorator.py).

A reader is a zero-arg callable returning an iterable of samples. These
combinators compose readers; paddle_tpu.batch() groups samples into
batches. Pure host-side Python — device feeding is the Executor's job.
"""
from __future__ import annotations

import itertools
import random
from queue import Queue
from threading import Thread

__all__ = [
    "map_readers", "buffered", "compose", "chain", "shuffle",
    "firstn", "cache", "xmap_readers", "multiprocess_reader",
]


def map_readers(func, *readers):
    """Apply func elementwise over samples zipped from readers."""

    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    """Window-shuffle: fill a buf_size buffer, emit randomly."""

    def shuffled():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, check_alignment=True):
    """Zip readers into flat tuples: (a, (b, c)) -> (a, b, c)."""

    def _flatten(x):
        out = []
        for item in x:
            if isinstance(item, tuple):
                out.extend(item)
            else:
                out.append(item)
        return tuple(out)

    def reader():
        its = [r() for r in readers]
        for items in (zip(*its) if not check_alignment else _strict_zip(its)):
            yield _flatten(items)

    def _strict_zip(its):
        sentinel = object()
        for items in itertools.zip_longest(*its, fillvalue=sentinel):
            if sentinel in items:
                raise ValueError("compose: readers have different lengths")
            yield items

    return reader


def buffered(reader, size):
    """Background-thread prefetch of up to `size` samples. Abandon-safe
    (worker released when the consumer breaks early) and error-faithful
    (reader exceptions re-raise on the consumer) via the shared
    fluid.reader._buffered_gen implementation."""

    def buffered_reader():
        from ..fluid.reader import _buffered_gen

        yield from _buffered_gen(reader(), capacity=size)

    return buffered_reader


def firstn(reader, n):
    def reader_n():
        return itertools.islice(reader(), n)

    return reader_n


def cache(reader):
    all_data = None

    def cached():
        nonlocal all_data
        if all_data is None:
            all_data = list(reader())
        return iter(all_data)

    return cached


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads."""

    def xreader():
        in_q: Queue = Queue(buffer_size)
        out_q: Queue = Queue(buffer_size)
        end = object()

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        Thread(target=feed, daemon=True).start()
        workers = [Thread(target=work, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()
        done = 0
        pending = {}
        next_i = 0
        while done < process_num:
            item = out_q.get()
            if item is end:
                done += 1
                continue
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Parity alias: thread-based fan-in (TPU hosts feed via one process;
    the reference's fork-based version exists for CPU-bound decode)."""
    def reader():
        qs = [buffered(r, queue_size // max(len(readers), 1))() for r in readers]
        for items in itertools.zip_longest(*qs, fillvalue=None):
            for it in items:
                if it is not None:
                    yield it

    return reader
