"""Inference serving engine over the hardened PS RPC plane.

THE "millions of users" item (ROADMAP): an InferenceServer implements
the ps_server `_Handler` contract (an object with `handle(method,
kwargs)` + `shutdown_event` behind `_TCPServer`), so the entire
production transport comes for free — client retries with backoff,
per-RPC deadlines, hedged reads, per-verb latency histograms with
trace exemplars, deterministic fault injection (drop/refuse/delay/
slow/stall/kill), and per-request causal trace_id spans.

Verbs: `infer`, `model_info`, `health`, `stats` (+ ping/shutdown).

Robustness core — the micro-batching scheduler (`MicroBatcher`):

  admission    — a BOUNDED queue. A request is REFUSED with an explicit
                 `Overloaded` reply when (a) the queue is full, (b) the
                 server is draining, or (c) the projected queue wait
                 (depth x EWMA batch latency) already exceeds the
                 request's remaining deadline — never silent queuing to
                 death. Shed work costs the server ~nothing; accepted
                 work is expected to meet its deadline (the overload
                 drill's contract).
  batching     — queued requests coalesce into one device batch
                 (concatenated rows, padded to max_batch so the XLA
                 compile cache holds ONE entry per model), outputs are
                 sliced back per request.
  deadlines    — the client's budget rides the request; a request whose
                 deadline expired while queued gets an explicit
                 `DeadlineExceeded` reply (counted) instead of burning
                 device time.
  drain        — SIGTERM stops admission ("Overloaded: draining"),
                 finishes every in-flight request, then exits — the
                 launcher's supervised restart finds no dropped work.
  epoch fence  — fresh weights (weight_sync.py) are STAGED by the
                 subscriber thread and installed by the scheduler
                 BETWEEN micro-batches: every request is served
                 entirely by one weight epoch, echoed in its reply.

SLO accounting: serve_requests_total{outcome=served|shed|deadline_
exceeded|error}, serve_request_ms / serve_batch_ms histograms (p50/p99
via the registry), serve_queue_depth gauge, serve_weight_epoch gauge —
all on the `stats` verb, debugz /statusz, and tools/servetop.py.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

import numpy as np

from ..telemetry import get_registry
from ..telemetry import tracing as _tracing
from .freeze import FrozenModel, load_frozen
from .predictor import Predictor
from . import weight_sync as _wsync

_REG = get_registry()

# serving latency buckets (ms): sub-ms cache hits through multi-second
# cold compiles
SERVE_BUCKETS = (1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000,
                 10000, 30000)

DEFAULT_MAX_BATCH = int(os.environ.get("PADDLE_SERVE_MAX_BATCH", 8))
DEFAULT_QUEUE_DEPTH = int(os.environ.get("PADDLE_SERVE_QUEUE_DEPTH", 64))

# the process-wide active server (debugz /statusz serving row)
_ACTIVE: Optional["InferenceServer"] = None


def _note_serving_badput(ms: float, cause: str) -> None:
    """Charge shed/expired request wall-time to the goodput ledger's
    serving buckets (no-op when PADDLE_GOODPUT is off)."""
    try:
        from ..telemetry import goodput as _goodput

        _goodput.note_serving_badput(ms, cause=cause)
    except Exception:  # noqa: BLE001 — telemetry is best-effort
        pass


class Overloaded(RuntimeError):
    """Admission refused — queue full, draining, or the projected wait
    exceeds the request deadline. The CLIENT's cue to back off or go to
    another replica; the error string crosses the wire verbatim."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired before its batch ran."""


class ResumedOnNewWeights(RuntimeError):
    """A generation resume landed on a replica serving a different
    weight epoch than the one the already-delivered tokens came from.
    Splicing two models' tokens would be silent corruption; the client
    gets this typed refusal (string crosses the wire verbatim) and
    decides — retry from scratch, or surface the partial output."""


# r22 crash-tolerant generation: PADDLE_SERVE_RESUME=0 disables the
# resume/preempt/dedup machinery entirely — the engine sheds instead of
# preempting and finished streams/replies are dropped on delivery,
# byte-identical to the r21 behavior.
ENV_RESUME = "PADDLE_SERVE_RESUME"
# bound on the exactly-once dedup table and the retained finished
# streams (oldest entries evicted first)
DEDUP_MAX = int(os.environ.get("PADDLE_SERVE_DEDUP_MAX", 512))


def resume_enabled() -> bool:
    return os.environ.get(ENV_RESUME, "1") not in ("0", "false", "off")


class _Pending:
    """One admitted request riding the batch queue."""

    __slots__ = ("feed", "rows", "deadline_t", "event", "outputs",
                 "error", "weight_epoch", "t_admit")

    def __init__(self, feed, rows, deadline_t):
        self.feed = feed
        self.rows = int(rows)
        self.deadline_t = deadline_t  # monotonic seconds or None
        self.event = threading.Event()
        self.outputs: Optional[List[np.ndarray]] = None
        self.error: Optional[BaseException] = None
        self.weight_epoch = 0
        self.t_admit = time.monotonic()


class MicroBatcher:
    """Bounded admission queue + scheduler thread running the model."""

    def __init__(self, predictor: Predictor, max_batch: int = 8,
                 queue_depth: int = 64, batch_wait_ms: float = 2.0):
        self.predictor = predictor
        self.max_batch = max(1, int(max_batch))
        self.queue_limit = max(1, int(queue_depth))
        self.batch_wait_s = float(batch_wait_ms) / 1e3
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._draining = False
        self._stopped = False
        self._inflight = 0
        # EWMA of device batch latency: the admission estimator. Seeded
        # pessimistically until the first (compile-bearing) batch lands.
        self._batch_ewma_s: Optional[float] = None
        self._pending_weights = None  # (weights dict, version) staged
        self._wlock = threading.Lock()
        self.weight_epoch = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-batcher")
        self._thread.start()

    # -- admission -------------------------------------------------------
    def _projected_wait_s(self, depth_rows: int) -> float:
        """Queue wait estimate: batches ahead of us x EWMA batch time.
        Unknown EWMA (nothing measured yet) estimates 0 — the first
        requests must be admitted for the estimator to learn."""
        if self._batch_ewma_s is None:
            return 0.0
        batches_ahead = -(-depth_rows // self.max_batch) + 1
        return batches_ahead * self._batch_ewma_s

    def submit(self, feed: Dict[str, np.ndarray],
               deadline_ms: Optional[float] = None) -> _Pending:
        # validate the feed BEFORE admission: a malformed request must
        # bounce as ITS error, never enter a batch other requests share
        want = list(self.predictor.feed_names)
        missing = [n for n in want if n not in feed]
        extra = [n for n in feed if n not in want]
        if missing or extra:
            raise ValueError(
                f"infer feed mismatch: missing {missing}, unknown "
                f"{extra} (model feeds: {want})")
        rows = int(np.shape(next(iter(feed.values())))[0]) if feed else 0
        if rows <= 0 or rows > self.max_batch:
            raise ValueError(
                f"infer batch must have 1..{self.max_batch} rows "
                f"(got {rows}; raise --max_batch or split the request)")
        deadline_t = (time.monotonic() + float(deadline_ms) / 1e3
                      if deadline_ms else None)
        with self._cond:
            if self._draining or self._stopped:
                _REG.counter("serve_requests_total",
                             outcome="shed").inc()
                raise Overloaded("Overloaded: server is draining")
            depth_rows = sum(p.rows for p in self._q)
            if len(self._q) >= self.queue_limit:
                _REG.counter("serve_requests_total",
                             outcome="shed").inc()
                raise Overloaded(
                    f"Overloaded: admission queue full "
                    f"({len(self._q)}/{self.queue_limit})")
            if deadline_t is not None:
                wait = self._projected_wait_s(depth_rows + rows)
                if time.monotonic() + wait >= deadline_t:
                    _REG.counter("serve_requests_total",
                                 outcome="shed").inc()
                    _note_serving_badput(wait * 1e3, "shed")
                    raise Overloaded(
                        f"Overloaded: projected queue wait "
                        f"{wait * 1e3:.0f}ms exceeds the request "
                        f"deadline ({float(deadline_ms):.0f}ms)")
            p = _Pending(feed, rows, deadline_t)
            self._q.append(p)
            _REG.gauge("serve_queue_depth").set(len(self._q))
            self._cond.notify_all()
        return p

    # -- weight fence ----------------------------------------------------
    def stage_weights(self, weights: Dict[str, np.ndarray],
                      version: int) -> None:
        """Called from the subscriber thread; the SCHEDULER installs it
        between micro-batches (the epoch fence). Last staged wins."""
        with self._wlock:
            self._pending_weights = (weights, int(version))
        with self._cond:
            self._cond.notify_all()

    def _maybe_adopt_weights(self) -> None:
        with self._wlock:
            staged, self._pending_weights = self._pending_weights, None
        if staged is None:
            return
        weights, version = staged
        try:
            self.predictor.adopt_weights(weights)
        except Exception as e:  # noqa: BLE001 — a bad delivery (manifest
            # drift, shape mismatch) must never kill the scheduler:
            # serving continues on the CURRENT epoch's weights
            _REG.counter("serve_weight_adopt_errors_total").inc()
            import sys

            print(f"[inference_server] weight adoption rejected "
                  f"(version {version}): {e}; serving stays on epoch "
                  f"{self.weight_epoch}", file=sys.stderr, flush=True)
            return
        self.weight_epoch += 1
        _REG.gauge("serve_weight_epoch").set(self.weight_epoch)
        _REG.counter("serve_weight_fences_total").inc()

    # -- the scheduler ---------------------------------------------------
    def _take_batch(self) -> List[_Pending]:
        """Block until work exists, then coalesce up to max_batch rows.
        A short batch_wait lets near-simultaneous requests share a
        device run without adding real latency."""
        with self._cond:
            while not self._q and not self._stopped:
                self._cond.wait(0.1)
                if self._pending_weights is not None and not self._q:
                    return []  # install promptly even when idle
            if self._stopped and not self._q:
                return []
            if (sum(p.rows for p in self._q) < self.max_batch
                    and not self._draining):
                self._cond.wait(self.batch_wait_s)
            batch, rows = [], 0
            while self._q and rows + self._q[0].rows <= self.max_batch:
                p = self._q.popleft()
                batch.append(p)
                rows += p.rows
            self._inflight = len(batch)
            _REG.gauge("serve_queue_depth").set(len(self._q))
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            # fence: adoption happens here, BETWEEN micro-batches — no
            # request observes two epochs
            self._maybe_adopt_weights()
            if not batch:
                with self._cond:
                    if self._stopped and not self._q:
                        return
                continue
            try:
                self._run_batch(batch)
            except BaseException as e:  # noqa: BLE001 — the scheduler
                # must NEVER die: whatever failed, the batch gets error
                # replies and the next batch is served
                for p in batch:
                    if not p.event.is_set():
                        p.error = e
                        _REG.counter("serve_requests_total",
                                     outcome="error").inc()
                        p.event.set()
            finally:
                with self._cond:
                    self._inflight = 0
                    self._cond.notify_all()

    def _run_batch(self, batch: List[_Pending]) -> None:
        now = time.monotonic()
        live: List[_Pending] = []
        for p in batch:
            if p.deadline_t is not None and now >= p.deadline_t:
                # expired while queued: explicit reply, no device time
                p.error = DeadlineExceeded(
                    "DeadlineExceeded: request expired in the queue")
                _REG.counter("serve_requests_total",
                             outcome="deadline_exceeded").inc()
                _note_serving_badput((now - p.t_admit) * 1e3, "deadline")
                p.event.set()
            else:
                live.append(p)
        if not live:
            return
        rows = sum(p.rows for p in live)
        feed_names = self.predictor.feed_names
        feed = {}
        for n in feed_names:
            parts = [np.asarray(p.feed[n]) for p in live]
            cat = np.concatenate(parts, axis=0)
            if rows < self.max_batch:
                # pad to ONE compiled batch shape: the XLA compile cache
                # holds a single entry per model, and padding rows are
                # dead compute, not a retrace
                pad = np.zeros((self.max_batch - rows,) + cat.shape[1:],
                               cat.dtype)
                cat = np.concatenate([cat, pad], axis=0)
            feed[n] = cat
        t0 = time.perf_counter()
        try:
            outs = self.predictor.run(feed)
        except BaseException as e:  # noqa: BLE001 — reply, keep serving
            for p in live:
                p.error = e
                _REG.counter("serve_requests_total",
                             outcome="error").inc()
                p.event.set()
            return
        dt = time.perf_counter() - t0
        # EWMA the admission estimator ranks queue wait with
        ewma = self._batch_ewma_s
        self._batch_ewma_s = dt if ewma is None else 0.8 * ewma + 0.2 * dt
        _REG.histogram("serve_batch_ms", help="device micro-batch "
                       "latency", buckets=SERVE_BUCKETS).observe(dt * 1e3)
        _REG.counter("serve_batches_total").inc()
        _REG.counter("serve_batch_rows_total").inc(rows)
        off = 0
        for p in live:
            sliced = []
            for o in outs:
                o = np.asarray(o)
                if o.ndim >= 1 and o.shape[0] == self.max_batch:
                    sliced.append(o[off:off + p.rows])
                else:  # batch-independent output (scalar/global stat)
                    sliced.append(o)
            p.outputs = sliced
            p.weight_epoch = self.weight_epoch
            _REG.counter("serve_requests_total", outcome="served").inc()
            _REG.histogram(
                "serve_request_ms",
                help="admission-to-reply serving latency",
                buckets=SERVE_BUCKETS).observe(
                (time.monotonic() - p.t_admit) * 1e3)
            off += p.rows
            p.event.set()

    # -- drain / teardown ------------------------------------------------
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._q)

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting, finish in-flight + queued work. True when
        the queue reached empty inside the timeout."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = time.monotonic() + timeout
        with self._cond:
            while (self._q or self._inflight) and \
                    time.monotonic() < deadline:
                self._cond.wait(0.1)
            drained = not self._q and not self._inflight
        return drained

    def stop(self) -> None:
        self.drain(timeout=5.0)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)

    def stats(self) -> dict:
        def counter(name, **labels):
            return _REG.counter(name, **labels).value

        req_h = _REG.histogram("serve_request_ms", buckets=SERVE_BUCKETS)
        with self._cond:
            depth, inflight = len(self._q), self._inflight
        return {
            "queue_depth": depth,
            "queue_limit": self.queue_limit,
            "inflight": inflight,
            "max_batch": self.max_batch,
            "draining": self._draining,
            "weight_epoch": self.weight_epoch,
            "batch_ewma_ms": (None if self._batch_ewma_s is None
                              else round(self._batch_ewma_s * 1e3, 3)),
            "served_total": counter("serve_requests_total",
                                    outcome="served"),
            "shed_total": counter("serve_requests_total", outcome="shed"),
            "deadline_exceeded_total": counter(
                "serve_requests_total", outcome="deadline_exceeded"),
            "error_total": counter("serve_requests_total",
                                   outcome="error"),
            "batches_total": counter("serve_batches_total"),
            "request_ms": req_h.summary(),
            # the SLO numbers servetop renders (bucket-interpolated)
            "p50_ms": round(req_h.quantile(0.50), 3),
            "p99_ms": round(req_h.quantile(0.99), 3),
            "batch_ms": _REG.histogram(
                "serve_batch_ms", buckets=SERVE_BUCKETS).summary(),
        }


class InferenceServer:
    """ps_server._Handler contract: serve a FrozenModel."""

    def __init__(self, frozen: FrozenModel,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 queue_depth: int = DEFAULT_QUEUE_DEPTH,
                 batch_wait_ms: float = 2.0,
                 weight_subscribe: bool = True,
                 engine=None):
        global _ACTIVE

        self.frozen = frozen
        self.predictor = Predictor(frozen)
        self.batcher = MicroBatcher(self.predictor, max_batch=max_batch,
                                    queue_depth=queue_depth,
                                    batch_wait_ms=batch_wait_ms)
        # optional autoregressive path (engine.GenerationEngine): the
        # `generate`/`generate_poll` verbs; the padded `infer` path
        # above is untouched whether or not an engine is attached
        self.engine = engine
        self._streams: Dict[str, object] = {}
        self._streams_lock = threading.Lock()
        self._stream_seq = 0
        # exactly-once generate (r22): request_id -> {req, stream_id,
        # reply}. A marked-retry generate with a known id reattaches to
        # the in-flight GenRequest or replays the finished reply — the
        # model never runs twice for one id. Bounded LRU (DEDUP_MAX);
        # the same bound retains finished streams so a retried
        # generate_poll after an ambiguous failure replays the final
        # snapshot instead of "unknown stream".
        self._dedup: "OrderedDict[str, dict]" = OrderedDict()
        self._done_streams: "OrderedDict[str, object]" = OrderedDict()
        self._resume_on = resume_enabled()
        self.shutdown_event = threading.Event()  # _Handler contract
        self.started_at = time.time()
        self.subscriber = None
        if weight_subscribe:
            self.subscriber = _wsync.maybe_start_subscriber(
                frozen, self.batcher.stage_weights)
        _ACTIVE = self

    # -- verbs -----------------------------------------------------------
    def infer(self, feed: Dict[str, np.ndarray],
              deadline_ms: Optional[float] = None) -> dict:
        pending = self.batcher.submit(feed, deadline_ms=deadline_ms)
        # the handler thread parks here while the scheduler batches;
        # wait is bounded by the deadline (+ grace for the reply)
        timeout = None
        if pending.deadline_t is not None:
            timeout = max(0.0, pending.deadline_t - time.monotonic()) + 30.0
        if not pending.event.wait(timeout):
            _REG.counter("serve_requests_total",
                         outcome="deadline_exceeded").inc()
            raise DeadlineExceeded(
                "DeadlineExceeded: batch did not complete in time")
        if pending.error is not None:
            raise pending.error
        return {
            "outputs": pending.outputs,
            "fetch_names": self.frozen.fetch_names,
            "weight_epoch": pending.weight_epoch,
            "queue_ms": round((time.monotonic() - pending.t_admit) * 1e3,
                              3),
        }

    def generate(self, prompt, max_new_tokens: int = 16,
                 deadline_ms: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 stream: bool = False,
                 request_id: Optional[str] = None,
                 retry: bool = False,
                 resume_tokens: Optional[list] = None,
                 elapsed_ms: Optional[float] = None,
                 expect_epoch: Optional[int] = None,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None,
                 seed: Optional[int] = None,
                 top_p: Optional[float] = None) -> dict:
        """Autoregressive generation (requires an attached engine).

        Blocking form returns the full token list; ``stream=True``
        returns a ``stream_id`` the client polls with `generate_poll`
        for incremental tokens (the PS RPC transport is one-shot
        request/reply, so streaming is poll-based).

        Exactly-once (r22): ``request_id`` + the transport's ``retry``
        marker form the same dedup contract the PS data plane uses for
        (trainer_id, step) — a marked retry whose id is already known
        reattaches to the in-flight request or replays the finished
        reply; the model never runs twice.  ``resume_tokens`` +
        ``elapsed_ms`` + ``expect_epoch`` are the failover-resume state:
        tokens already delivered become the new prefill prefix, the SLO
        clock is backdated by elapsed_ms, and an epoch mismatch is
        refused with the typed ResumedOnNewWeights string."""
        if self.engine is None:
            raise ValueError("generation is not enabled on this replica "
                             "(no decoder engine attached)")
        rid = str(request_id) if request_id else None
        if rid and retry and self._resume_on:
            with self._streams_lock:
                ent = self._dedup.get(rid)
            if ent is not None:
                _REG.counter(
                    "serve_gen_dedup_hits_total",
                    help="marked-retry generates that reattached or "
                         "replayed instead of running twice").inc()
                # the retry's own server span records that it replayed
                # instead of decoding — the trace shows ONE engine
                # residency plus a cheap reattach hop
                _tracing.annotate(dedup_hit=True)
                if ent.get("stream_id") is not None:
                    return {"stream_id": ent["stream_id"]}
                if ent.get("reply") is not None:
                    return ent["reply"]
                reply = self.engine.result(ent["req"])
                ent["reply"] = reply
                return reply
        req = self.engine.submit(prompt, max_new_tokens=max_new_tokens,
                                 deadline_ms=deadline_ms, eos_id=eos_id,
                                 resume_tokens=resume_tokens,
                                 elapsed_ms=elapsed_ms,
                                 expect_epoch=expect_epoch,
                                 temperature=temperature, top_k=top_k,
                                 seed=seed, top_p=top_p)
        ent = None
        if rid and self._resume_on:
            ent = {"req": req, "stream_id": None, "reply": None}
            with self._streams_lock:
                self._dedup[rid] = ent
                while len(self._dedup) > DEDUP_MAX:
                    self._dedup.popitem(last=False)
        if stream:
            with self._streams_lock:
                self._stream_seq += 1
                sid = f"g{self._stream_seq}"
                self._streams[sid] = req
                if ent is not None:
                    ent["stream_id"] = sid
            return {"stream_id": sid}
        reply = self.engine.result(req)
        if ent is not None:
            ent["reply"] = reply
        return reply

    def generate_poll(self, stream_id: str, cursor: int = 0) -> dict:
        with self._streams_lock:
            req = (self._streams.get(stream_id)
                   or self._done_streams.get(stream_id))
        if req is None:
            raise ValueError(f"unknown stream {stream_id!r}")
        snap = req.snapshot(int(cursor))
        if snap["done"]:
            with self._streams_lock:
                live = self._streams.pop(stream_id, None)
                if live is not None and self._resume_on:
                    # retain (bounded) so a retried poll after an
                    # ambiguous failure replays the final snapshot
                    self._done_streams[stream_id] = live
                    while len(self._done_streams) > DEDUP_MAX:
                        self._done_streams.popitem(last=False)
        return snap

    def health(self) -> dict:
        return {
            "ok": not self.batcher._draining,
            "draining": self.batcher._draining,
            "weight_epoch": self.batcher.weight_epoch,
            "queue_depth": self.batcher.queue_depth(),
            "uptime_s": round(time.time() - self.started_at, 3),
        }

    def stats(self) -> dict:
        from ..distributed.ps_server import server_telemetry

        out = {
            "serving": self.batcher.stats(),
            "model": self.frozen.model_info(),
            "server": server_telemetry(),
            "weight_sync": {
                "enabled": self.subscriber is not None,
                "version": (self.subscriber.version
                            if self.subscriber else None),
            },
        }
        if self.engine is not None:
            out["generation"] = self.engine.stats()
            out["generation"]["dedup_hits_total"] = _REG.counter(
                "serve_gen_dedup_hits_total").value
        return out

    def handle(self, method: str, kwargs: dict):
        from ..distributed import faults

        inj = faults.injector()
        if inj is not None:
            # the PSServer.handle contract: deterministic server-side
            # fault rules (slow/kill/partition) apply to serving verbs
            # too — the slow-tail hedge drill and kill drills ride this
            inj.on_server_call(method)
        if kwargs.get("retry"):
            # transport marked this as a retry whose first attempt may
            # have landed (the PS _MARK_RETRY contract) — counted so
            # drills can prove the dedup table saw the replay
            _REG.counter("serve_retry_received_total",
                         help="RPCs carrying the ambiguous-retry marker",
                         verb=method).inc()
        if method == "ping":
            return "pong"
        if method == "infer":
            return self.infer(kwargs["feed"], kwargs.get("deadline_ms"))
        if method == "generate":
            return self.generate(
                kwargs["prompt"],
                max_new_tokens=int(kwargs.get("max_new_tokens", 16)),
                deadline_ms=kwargs.get("deadline_ms"),
                eos_id=kwargs.get("eos_id"),
                stream=bool(kwargs.get("stream", False)),
                request_id=kwargs.get("request_id"),
                retry=bool(kwargs.get("retry", False)),
                resume_tokens=kwargs.get("resume_tokens"),
                elapsed_ms=kwargs.get("elapsed_ms"),
                expect_epoch=kwargs.get("expect_epoch"),
                temperature=kwargs.get("temperature"),
                top_k=kwargs.get("top_k"),
                seed=kwargs.get("seed"),
                top_p=kwargs.get("top_p"))
        if method == "generate_poll":
            return self.generate_poll(kwargs["stream_id"],
                                      int(kwargs.get("cursor", 0)))
        if method == "model_info":
            return self.frozen.model_info()
        if method == "health":
            return self.health()
        if method == "stats":
            return self.stats()
        if method == "drain":
            t = float(kwargs.get("timeout", 30.0))
            drained = self.batcher.drain(timeout=t)
            if self.engine is not None:
                drained = self.engine.drain(timeout=t) and drained
            return {"drained": drained}
        if method == "shutdown":
            self.begin_drain()
            self.shutdown_event.set()
            return 0
        raise ValueError(f"unknown serving verb {method!r}")

    # -- lifecycle -------------------------------------------------------
    def begin_drain(self) -> None:
        with self.batcher._cond:
            self.batcher._draining = True
            self.batcher._cond.notify_all()
        if self.engine is not None:
            with self.engine._cond:
                self.engine._draining = True
                self.engine._cond.notify_all()

    def close(self) -> None:
        global _ACTIVE

        if self.subscriber is not None:
            self.subscriber.stop()
        self.batcher.stop()
        if self.engine is not None:
            self.engine.stop()
        if _ACTIVE is self:
            _ACTIVE = None


def current_status() -> Optional[dict]:
    """The active server's serving stats, or None — the debugz /statusz
    serving row (cheap: one module global)."""
    srv = _ACTIVE
    if srv is None:
        return None
    try:
        return srv.batcher.stats()
    except Exception:  # noqa: BLE001 — status pages never crash
        return None


def current_servez() -> Optional[dict]:
    """The active server's per-request generation view — the debugz
    /servez payload (active slots, queued requests, recent completions
    slowest-first). None when no server or no engine is attached."""
    srv = _ACTIVE
    if srv is None or srv.engine is None:
        return None
    try:
        out = srv.engine.servez()
        out["dedup_hits_total"] = _REG.counter(
            "serve_gen_dedup_hits_total").value
        return out
    except Exception:  # noqa: BLE001 — status pages never crash
        return None


# ---------------------------------------------------------------------------
# process entry (one serving replica)
# ---------------------------------------------------------------------------


def _maybe_build_engine():
    """PADDLE_SERVE_GEN=1 attaches a generation engine to the replica
    (the tiny decoder; real deployments construct their own engine and
    pass it to InferenceServer).  Sized by the PADDLE_SERVE_KV_* envs."""
    if os.environ.get("PADDLE_SERVE_GEN", "") in ("", "0", "false"):
        return None
    from . import decode_model as _dm
    from .engine import GenerationEngine

    cfg = _dm.DecoderConfig()
    seed = int(os.environ.get("PADDLE_SERVE_GEN_SEED", "0"))
    return GenerationEngine(_dm.TinyDecoderLM(cfg, seed=seed))


def serve(frozen: FrozenModel, port: int = 0, host: str = "0.0.0.0",
          ready_cb=None, max_batch: int = DEFAULT_MAX_BATCH,
          queue_depth: int = DEFAULT_QUEUE_DEPTH,
          drain_grace: float = 30.0, engine=None):
    """Run one serving replica (blocks). Mirrors ps_server.serve: the
    same _TCPServer/_Handler transport, heartbeat + coordinator lease
    integration, SIGTERM -> graceful drain -> exit 0."""
    from ..distributed.ps_server import _Handler, _TCPServer

    _tracing.maybe_install_hooks()
    # span/metrics export off the replica (ps_server.serve pattern):
    # PADDLE_TRACES_PUSH_URL drains the span ring — serving spans
    # (prefill/decode/queue_wait/evict/preempt) land in the same ring
    # as training spans — through the OTLP push exporter instead of
    # only reaching disk via the flight recorder. Env unset = zero
    # network, zero threads.
    try:
        from ..telemetry import export as _export

        _export.maybe_start()
        _export.maybe_start_traces()
    except Exception:  # noqa: BLE001 — telemetry must not stop serving
        _export = None
    srv = _TCPServer((host, port), _Handler)
    if engine is None:
        engine = _maybe_build_engine()
    inf = InferenceServer(frozen, max_batch=max_batch,
                          queue_depth=queue_depth, engine=engine)
    srv.ps = inf  # type: ignore[attr-defined] — _Handler contract

    # graceful drain: SIGTERM stops admission (new infers bounce with
    # "Overloaded: draining"), in-flight + queued requests finish, then
    # the event loop stops — zero accepted requests dropped
    def _sigterm(signum, frame):
        def _drain_and_exit():
            print("[inference_server] SIGTERM: draining "
                  f"(queue={inf.batcher.queue_depth()})",
                  file=sys.stderr, flush=True)
            inf.begin_drain()
            inf.batcher.drain(timeout=drain_grace)
            if inf.engine is not None:
                inf.engine.drain(timeout=drain_grace)
            inf.shutdown_event.set()
            srv.shutdown()

        threading.Thread(target=_drain_and_exit, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:
        pass  # not the main thread (in-process tests drive drain directly)

    hb = None
    hb_dir = os.environ.get("PADDLE_HEARTBEAT_DIR")
    hb_tag = os.environ.get("PADDLE_TRAINER_TAG") or os.environ.get(
        "PADDLE_PS_RANK_TAG")
    if hb_dir and hb_tag:
        from ..distributed.heartbeat import HeartBeatWorker

        hb = HeartBeatWorker(hb_dir, hb_tag).start()
    bound_host, bound_port = srv.server_address[0], srv.server_address[1]
    if bound_host in ("0.0.0.0", ""):
        bound_host = "127.0.0.1"
    lease_worker = None
    try:
        from ..distributed import coordinator as _coord

        lease_worker = _coord.maybe_start_lease_worker(
            kind="inference", tag=hb_tag,
            self_endpoint=f"{bound_host}:{bound_port}",
            payload_fn=lambda: {"serving": inf.batcher.stats()})
    except Exception as e:  # noqa: BLE001 — leases are advisory here
        print(f"[inference_server] lease worker failed to start: {e}",
              file=sys.stderr, flush=True)
    if ready_cb is not None:
        ready_cb(srv.server_address)
    try:
        from ..telemetry import debugz as _debugz

        _debugz.maybe_serve()
    except Exception:  # noqa: BLE001
        pass
    try:
        srv.serve_forever(poll_interval=0.1)
    finally:
        if hb is not None:
            hb.stop()
        if lease_worker is not None:
            lease_worker.stop()
        srv.close_all_connections()
        srv.server_close()
        inf.close()
        try:
            # final synchronous flush: spans from the last requests
            # leave the replica before the process does
            if _export is not None and _export.active_traces():
                _export.active_traces().flush()
        except Exception:  # noqa: BLE001 — best-effort on the way out
            pass
        _tracing.shutdown_dump()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="paddle_tpu.inference.server")
    p.add_argument("--model_dir", required=True,
                   help="fluid.io.save_inference_model output dir")
    p.add_argument("--port", type=int, default=None,
                   help="default: the port of PADDLE_CURRENT_ENDPOINT "
                        "(launch.py --serve), else an ephemeral port")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--max_batch", type=int, default=DEFAULT_MAX_BATCH)
    p.add_argument("--queue_depth", type=int, default=DEFAULT_QUEUE_DEPTH)
    p.add_argument("--drain_grace", type=float, default=float(
        os.environ.get("PADDLE_SERVE_DRAIN_GRACE", 30.0)))
    args = p.parse_args(argv)

    port = args.port
    if port is None:
        ep = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        port = int(ep.rsplit(":", 1)[1]) if ":" in ep else 0

    frozen = load_frozen(args.model_dir)

    def ready(addr):
        # the launcher/tests read this line to learn the bound port
        print(f"[inference_server] listening on {addr[0]}:{addr[1]}",
              flush=True)

    serve(frozen, port=port, host=args.host, ready_cb=ready,
          max_batch=args.max_batch, queue_depth=args.queue_depth,
          drain_grace=args.drain_grace)
    return 0


if __name__ == "__main__":
    sys.exit(main())
