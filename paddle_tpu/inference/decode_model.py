"""A tiny autoregressive decoder LM with a paged KV decode path.

The serving engine needs a model whose decode step is ONE fixed-shape
compiled program (max batch slots x one token) reading/writing the
paged KV pool.  The frozen-Program predictor can't express that today
(its cache state lives in scope vars, not a shared pool), so the
generation path runs this pure-jax decoder: embedding + learned
positions + pre-LN transformer blocks + tied-nothing head, greedy
argmax sampling.  Three entry points, all module-level jits so every
engine/test with the same shapes shares compiles:

* ``prefill``       — one request's prompt window attends over its
  (page-gathered) cached context plus itself causally; returns the
  next-token logits and the window's per-layer K/V for scattering into
  pool pages.  Window length is bucketed to powers of two so prefix
  cache hits shrink compile *and* compute.
* ``decode_step``   — the continuous-batching inner loop: [slots] query
  tokens, each attending over its page table via the paged-attention
  op.  New K/V are scattered into the pool *before* attention (dead
  slots write to trash page 0), so the step is a single pure program
  with no cache merge.
* ``recompute_step`` — the r19-style padded baseline: re-run the whole
  dense prefix for every generated token (O(n^2) per sequence).  Kept
  both as the ``PADDLE_SERVE_KV_CACHE=0`` fallback and as the oracle
  the cached path is tested against.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.pallas.paged_attention import paged_attention

_LN_EPS = 1e-5
_NEG_INF = float("-inf")


@dataclasses.dataclass(frozen=True)
class DecoderConfig:
    vocab: int = 64
    d_model: int = 32
    n_layers: int = 2
    n_heads: int = 2
    ffn: int = 64
    max_seq: int = 64

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def init_params(cfg: DecoderConfig, seed: int = 0) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)

    def w(*shape, scale=0.02):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    p: Dict[str, np.ndarray] = {
        "embed": w(cfg.vocab, cfg.d_model, scale=0.1),
        "pos": w(cfg.max_seq, cfg.d_model, scale=0.1),
        "lnf_g": np.ones(cfg.d_model, np.float32),
        "lnf_b": np.zeros(cfg.d_model, np.float32),
        "head": w(cfg.d_model, cfg.vocab, scale=0.1),
    }
    for i in range(cfg.n_layers):
        p[f"l{i}.ln1_g"] = np.ones(cfg.d_model, np.float32)
        p[f"l{i}.ln1_b"] = np.zeros(cfg.d_model, np.float32)
        p[f"l{i}.ln2_g"] = np.ones(cfg.d_model, np.float32)
        p[f"l{i}.ln2_b"] = np.zeros(cfg.d_model, np.float32)
        for nm in ("wq", "wk", "wv", "wo"):
            p[f"l{i}.{nm}"] = w(cfg.d_model, cfg.d_model)
        p[f"l{i}.w1"] = w(cfg.d_model, cfg.ffn)
        p[f"l{i}.b1"] = np.zeros(cfg.ffn, np.float32)
        p[f"l{i}.w2"] = w(cfg.ffn, cfg.d_model)
        p[f"l{i}.b2"] = np.zeros(cfg.d_model, np.float32)
    return p


def _ln(x, g, b):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + _LN_EPS) * g + b


def _n_layers(params) -> int:
    i = 0
    while f"l{i}.wq" in params:
        i += 1
    return i


def _qkv(params, i, h, n_heads):
    d = h.shape[-1]
    hd = d // n_heads
    q = (h @ params[f"l{i}.wq"]).reshape(*h.shape[:-1], n_heads, hd)
    k = (h @ params[f"l{i}.wk"]).reshape(*h.shape[:-1], n_heads, hd)
    v = (h @ params[f"l{i}.wv"]).reshape(*h.shape[:-1], n_heads, hd)
    return q, k, v


def _mlp(params, i, x):
    h = _ln(x, params[f"l{i}.ln2_g"], params[f"l{i}.ln2_b"])
    h = jax.nn.gelu(h @ params[f"l{i}.w1"] + params[f"l{i}.b1"])
    return x + h @ params[f"l{i}.w2"] + params[f"l{i}.b2"]


# ---------------------------------------------------------------------------
# prefill: one request window over gathered context
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_heads",))
def prefill(params, tokens, start, ctx_k, ctx_v, n_valid, *, n_heads):
    """One request's prompt window.

    tokens:  [R] window token ids (padded past n_valid).
    start:   scalar int32 — absolute position of tokens[0] (== number of
             context positions reused from the prefix cache).
    ctx_k/v: [L, C, H, hd] gathered cached context (C == max_seq rows;
             only the first ``start`` are live).
    n_valid: scalar int32 — live rows in the window (>= 1).

    Returns (next_logits [V], next_token, k_win [L, R, H, hd], v_win).
    """
    r = tokens.shape[0]
    c = ctx_k.shape[1]
    hd = ctx_k.shape[-1]
    scale = 1.0 / (hd ** 0.5)
    pos = start + jnp.arange(r, dtype=jnp.int32)
    x = params["embed"][tokens] + params["pos"][jnp.minimum(
        pos, params["pos"].shape[0] - 1)]
    ctx_live = jnp.arange(c, dtype=jnp.int32)[None, None, :] < start  # [1,1,C]
    causal = (jnp.arange(r)[None, :, None]
              >= jnp.arange(r)[None, None, :])                        # [1,R,R]
    ks, vs = [], []
    for i in range(_n_layers(params)):
        h = _ln(x, params[f"l{i}.ln1_g"], params[f"l{i}.ln1_b"])
        q, k, v = _qkv(params, i, h, n_heads)                # [R, H, hd]
        s_ctx = jnp.einsum("rhd,chd->hrc", q, ctx_k[i]) * scale
        s_win = jnp.einsum("rhd,shd->hrs", q, k) * scale
        s = jnp.concatenate([
            jnp.where(ctx_live, s_ctx, _NEG_INF),
            jnp.where(causal, s_win, _NEG_INF),
        ], axis=-1)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        out = (jnp.einsum("hrc,chd->rhd", p[..., :c], ctx_v[i])
               + jnp.einsum("hrs,shd->rhd", p[..., c:], v))
        x = x + out.reshape(r, -1) @ params[f"l{i}.wo"]
        x = _mlp(params, i, x)
        ks.append(k)
        vs.append(v)
    hfin = _ln(x, params["lnf_g"], params["lnf_b"])
    logits = hfin[n_valid - 1] @ params["head"]
    return (logits, jnp.argmax(logits).astype(jnp.int32),
            jnp.stack(ks), jnp.stack(vs))


@functools.partial(jax.jit, static_argnames=("page_size",))
def gather_ctx(k_flat, v_flat, page_table, *, page_size):
    """[L, N, H, hd] pool -> [L, maxp*page, H, hd] per-request context."""
    flat = (page_table[:, None] * page_size
            + jnp.arange(page_size, dtype=jnp.int32)[None, :]).reshape(-1)
    return k_flat[:, flat], v_flat[:, flat]


@jax.jit
def scatter_kv(k_flat, v_flat, k_win, v_win, flat_idx):
    """Write a prefill window's K/V into pool rows (trash rows = 0)."""
    return (k_flat.at[:, flat_idx].set(k_win),
            v_flat.at[:, flat_idx].set(v_win))


@functools.partial(jax.jit, static_argnames=("page_size",))
def copy_page(k_flat, v_flat, src_pid, dst_pid, *, page_size):
    """COW payload copy: duplicate one physical page's rows."""
    ksrc = jax.lax.dynamic_slice_in_dim(
        k_flat, src_pid * page_size, page_size, axis=1)
    vsrc = jax.lax.dynamic_slice_in_dim(
        v_flat, src_pid * page_size, page_size, axis=1)
    return (jax.lax.dynamic_update_slice_in_dim(
                k_flat, ksrc, dst_pid * page_size, axis=1),
            jax.lax.dynamic_update_slice_in_dim(
                v_flat, vsrc, dst_pid * page_size, axis=1))


# ---------------------------------------------------------------------------
# decode step: the continuous-batching inner loop (ONE compiled shape)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("page_size", "n_heads"))
def decode_step(params, k_flat, v_flat, tokens, positions, page_table,
                write_flat, *, page_size, n_heads):
    """One token for every batch slot.

    tokens/positions: [B] current token + its absolute position (dead
    slots: token 0, position 0, write_flat 0 -> they read/write trash
    page 0 and their outputs are ignored by the engine).
    page_table: [B, maxp] physical page per logical page.
    write_flat: [B] flat pool row for this step's K/V.

    New K/V are written BEFORE attention, so lengths = position + 1 and
    the token attends to itself through the pool — no cache merge.
    """
    b = tokens.shape[0]
    n = k_flat.shape[1]
    hd = k_flat.shape[-1]
    lengths = positions.astype(jnp.int32) + 1
    x = (params["embed"][tokens]
         + params["pos"][jnp.minimum(positions,
                                     params["pos"].shape[0] - 1)])
    for i in range(_n_layers(params)):
        h = _ln(x, params[f"l{i}.ln1_g"], params[f"l{i}.ln1_b"])
        q, k, v = _qkv(params, i, h, n_heads)                # [B, H, hd]
        k_flat = k_flat.at[i, write_flat].set(k)
        v_flat = v_flat.at[i, write_flat].set(v)
        k_pages = k_flat[i].reshape(n // page_size, page_size,
                                    n_heads, hd)
        v_pages = v_flat[i].reshape(n // page_size, page_size,
                                    n_heads, hd)
        out = paged_attention(q, k_pages, v_pages, page_table, lengths)
        x = x + out.reshape(b, -1) @ params[f"l{i}.wo"]
        x = _mlp(params, i, x)
    hfin = _ln(x, params["lnf_g"], params["lnf_b"])
    logits = hfin @ params["head"]
    return (logits, jnp.argmax(logits, axis=-1).astype(jnp.int32),
            k_flat, v_flat)


# ---------------------------------------------------------------------------
# recompute baseline: dense re-prefill per generated token (r19 padding)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_heads",))
def recompute_step(params, tokens, lengths, *, n_heads):
    """Dense causal forward over fixed [B, S]; logits at lengths-1.
    Dead slots pass lengths=1/zero tokens and ignore the output."""
    b, s = tokens.shape
    scale = None
    pos = jnp.arange(s, dtype=jnp.int32)
    x = (params["embed"][tokens]
         + params["pos"][jnp.minimum(pos, params["pos"].shape[0] - 1)][None])
    causal = (pos[None, :, None] >= pos[None, None, :])[None]  # [1,1,S,S]
    for i in range(_n_layers(params)):
        h = _ln(x, params[f"l{i}.ln1_g"], params[f"l{i}.ln1_b"])
        q, k, v = _qkv(params, i, h, n_heads)                # [B, S, H, hd]
        if scale is None:
            scale = 1.0 / (q.shape[-1] ** 0.5)
        sc = jnp.einsum("brhd,bshd->bhrs", q, k) * scale
        sc = jnp.where(causal, sc, _NEG_INF)
        m = jnp.max(sc, axis=-1, keepdims=True)
        p = jnp.exp(sc - m)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        out = jnp.einsum("bhrs,bshd->brhd", p, v)
        x = x + out.reshape(b, s, -1) @ params[f"l{i}.wo"]
        x = _mlp(params, i, x)
    hfin = _ln(x, params["lnf_g"], params["lnf_b"])
    logits_all = hfin @ params["head"]                       # [B, S, V]
    idx = jnp.maximum(lengths - 1, 0)
    logits = jnp.take_along_axis(
        logits_all, idx[:, None, None], axis=1)[:, 0]
    return logits, jnp.argmax(logits, axis=-1).astype(jnp.int32)


def prefill_bucket(n: int, buckets_from: int = 8) -> int:
    """Window lengths compile per padded bucket (powers of two)."""
    b = buckets_from
    while b < n:
        b *= 2
    return b


class TinyDecoderLM:
    """Config + device params + thin wrappers over the module jits."""

    def __init__(self, cfg: DecoderConfig,
                 params: Optional[Dict[str, np.ndarray]] = None,
                 seed: int = 0):
        self.cfg = cfg
        self.params = jax.tree_util.tree_map(
            jnp.asarray, params if params is not None
            else init_params(cfg, seed))

    def adopt(self, params: Dict[str, np.ndarray]) -> None:
        """Swap weights (epoch-fenced by the engine); shapes must match."""
        cur = self.params
        for k, v in params.items():
            if k not in cur:
                raise KeyError(f"unknown param {k!r}")
            if tuple(cur[k].shape) != tuple(np.shape(v)):
                raise ValueError(
                    f"shape mismatch for {k!r}: "
                    f"{tuple(np.shape(v))} vs {tuple(cur[k].shape)}")
        self.params = {**cur,
                       **{k: jnp.asarray(v) for k, v in params.items()}}
