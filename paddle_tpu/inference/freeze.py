"""Program freezing: training Program -> self-contained inference model.

The serving-side analog of the reference's `AnalysisPredictor` graph
preparation (analysis_predictor.cc PrepareProgram + IR pass manager):

  1. `clone(for_test=True)` — every op holding an `is_test` attr flips
     to test mode (dropout off, batch_norm reads running stats);
  2. backward slice from the fetch targets (fluid/io.py's inference
     prune) — backward ops, optimizer update ops and feed-queue glue
     all fall out because nothing downstream of the fetches needs them;
  3. the conv+BN fold (fluid/fusion_pass.py): with `is_test=True` the
     fused emitter folds the BN scale/shift into the conv weights — one
     conv + bias add, no normalization pass ("Operator Fusion in XLA":
     freezing-time rewrites are the cheap win);
  4. dead-variable sweep: vars only the stripped ops touched (gradients,
     optimizer moments, loss) leave block.vars so the frozen program
     lints clean;
  5. PR-5 pass sandwich: under FLAGS_program_verify the whole rewrite is
     verified before/after, and a structural error the freeze introduced
     raises attributed to it. `freeze_program` additionally runs one
     unconditional verify of the RESULT — a frozen model ships to
     serving replicas, so it is always worth one static check.

The frozen weights are captured by VALUE into the FrozenModel's own
scope (arrays are immutable; a training step replaces, never mutates),
so serving is isolated from further training by construction — live
weight adoption is explicit (weight_sync.py), never aliased.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .. import fluid
from ..fluid import framework
from ..fluid.analysis import ERROR, ProgramVerifyError, \
    assert_scope_valid, pass_sandwich, verify_program
from ..fluid.executor import Scope
from ..fluid.fusion_pass import apply_conv_bn_fusion
from ..fluid.io import _prune_for_inference

# feed-pipeline glue with no inference semantics: stripped even when a
# fetch accidentally depends on one (none of these are registered
# compute ops on the serving path)
_FEED_QUEUE_OPS = ("read", "create_py_reader", "double_buffer",
                   "queue_generator", "feed", "fetch")


@dataclass
class FrozenModel:
    """A self-contained inference model: pruned `is_test` program +
    captured weights. Everything a Predictor / InferenceServer needs."""

    program: framework.Program
    feed_names: List[str]
    fetch_names: List[str]
    param_names: List[str]
    scope: Scope
    fused_conv_bn: int = 0
    meta: Dict[str, object] = field(default_factory=dict)

    def model_info(self) -> dict:
        """JSON-ready description (the `model_info` serving verb)."""
        blk = self.program.global_block()

        def var_meta(n):
            v = blk._find_var_recursive(n)
            return {"shape": list(v.shape) if v is not None and
                    v.shape is not None else None,
                    "dtype": str(v.dtype) if v is not None and
                    v.dtype is not None else None}

        return {
            "feeds": {n: var_meta(n) for n in self.feed_names},
            "fetches": {n: var_meta(n) for n in self.fetch_names},
            "num_ops": len(blk.ops),
            "num_params": len(self.param_names),
            "fused_conv_bn": self.fused_conv_bn,
            **self.meta,
        }


def _infer_feed_names(program) -> List[str]:
    return [v.name for v in program.global_block().vars.values()
            if getattr(v, "is_data", False)]


def _detect_state_vars(program, feed_names: Sequence[str],
                       fetch_names: Sequence[str]) -> List[str]:
    """State-carrying cache vars of a decode program: persistable
    non-Parameter vars that the INFERENCE slice reads at op index i
    and some op writes back at index j >= i (the read-old / write-new
    cross-step pattern — KV caches, rolling decode state).  The
    executor round-trips such vars back into the scope after every
    run, so the frozen program must keep their writer ops live even
    though no fetch depends on them.

    Two classes of read+written persistables must NOT be detected:

    * BN batch statistics — read+written in TRAINING mode only;
      detection runs on a for_test clone, where their writers are gone.
    * Optimizer accumulators (Adam moments, beta-pow state) — read and
      written, but only by backward/optimizer ops.  Restricting the
      read side to vars the fetch-rooted slice actually needs keeps
      them (and, transitively, the whole training graph they'd drag
      back in) out.  The slice is iterated to a fixpoint because a kept
      writer chain can itself read further state vars."""
    test = program.clone(for_test=True)
    blk = test.global_block()
    first_read: Dict[str, int] = {}
    last_write: Dict[str, int] = {}
    for i, op in enumerate(blk.ops):
        for n in op.input_names():
            first_read.setdefault(n, i)
        for n in op.output_names():
            last_write[n] = i
    feeds = set(feed_names)

    state: set = set()
    while True:
        # the vars the fetch+state-rooted backward slice reads
        needed = set(str(n) for n in fetch_names) | state
        for i in range(len(blk.ops) - 1, -1, -1):
            op = blk.ops[i]
            if any(n in needed for n in op.output_names()):
                needed.update(op.input_names())
        new = set()
        for n in needed - state:
            if n in feeds:
                continue
            wi, ri = last_write.get(n), first_read.get(n)
            if wi is None or ri is None or ri > wi:
                continue
            v = blk._find_var_recursive(n)
            if v is None or not v.persistable \
                    or isinstance(v, framework.Parameter):
                continue
            new.add(n)
        if not new:
            return sorted(state)
        state |= new


def freeze_program(program, scope=None, feed_names: Optional[Sequence[str]]
                   = None, fetch_list: Sequence = ()) -> FrozenModel:
    """Clone `program` into a pruned `is_test` inference Program and
    capture its weights from `scope` (default: the global scope).

    fetch_list: Variables or names the model serves (required).
    feed_names: defaults to the program's data vars.
    """
    if not fetch_list:
        raise ValueError("freeze_program needs a non-empty fetch_list")
    scope = scope or fluid.executor.global_scope()
    fetch_names = [v.name if isinstance(v, framework.Variable) else str(v)
                   for v in fetch_list]
    if feed_names is None:
        feed_names = _infer_feed_names(program)
    feed_names = [str(n) for n in feed_names]
    # state-carrying cache vars (decode programs): extra slice roots so
    # their write-back ops survive the fetch-rooted backward slice
    state_vars = _detect_state_vars(program, feed_names, fetch_names)
    live_out = set(feed_names) | set(fetch_names) | set(state_vars)

    with pass_sandwich(program, "freeze_program", live_out=live_out):
        # clone(for_test=True) + backward slice: backward/optimizer ops
        # and every var only they touched drop out of the op list here
        frozen = _prune_for_inference(program, feed_names, fetch_names,
                                      state_vars=state_vars)
    blk = frozen.global_block()
    blk.ops = [op for op in blk.ops if op.type not in _FEED_QUEUE_OPS]

    # conv+BN fold: is_test=True, so the fused emitter folds the BN into
    # the conv weights (sandwiched itself under FLAGS_program_verify)
    fused = apply_conv_bn_fusion(frozen)

    # dead-variable sweep: the pruned op list no longer reads/writes the
    # training-only vars (grads, moments, LR, loss) — leaving them in
    # block.vars keeps stale Variable.op links and proglint noise
    used = set(live_out)
    for op in blk.ops:
        used.update(op.input_names())
        used.update(op.output_names())
    for name in [n for n in blk.vars if n not in used]:
        del blk.vars[name]
    # rebuild last-writer links: surviving vars whose writer was pruned
    # (params the optimizer updated, BN running stats) must not point at
    # removed ops (proglint stale-last-writer)
    for v in blk.vars.values():
        v.op = None
    for op in blk.ops:
        for n in op.output_names():
            v = blk._find_var_recursive(n)
            if v is not None:
                v.op = op
    frozen._bump_version()

    # a frozen model ships to serving replicas: always worth one verify
    findings = verify_program(frozen, live_out=live_out)
    errors = [f for f in findings if f.severity == ERROR]
    if errors:
        raise ProgramVerifyError(errors, where="freeze_program result")

    # capture weights BY VALUE into the model's own scope: every
    # persistable the frozen ops still read (params AND buffers — BN
    # running stats, traced constants)
    param_names = sorted(
        v.name for v in frozen.list_vars()
        if v.persistable and v.name in used and v.name not in feed_names)
    fscope = Scope()
    missing = []
    for n in param_names:
        val = scope.find_var(n)
        if val is None:
            missing.append(n)
        else:
            fscope.set_var(n, val)
    if missing:
        raise RuntimeError(
            f"freeze_program: {len(missing)} persistable(s) are "
            f"uninitialized in the scope (run the startup program "
            f"first): {missing[:5]}")
    # scope-aware lint of the CAPTURE (unconditional, like the result
    # verify): the frozen program must read only its captured weights +
    # detected state vars, and each captured array must match the var's
    # shape/dtype — a serving replica is the worst place to learn a
    # training-side rewrite changed a weight's geometry
    assert_scope_valid(frozen, fscope, feed_names=feed_names,
                       where="freeze_program captured scope")
    return FrozenModel(program=frozen, feed_names=list(feed_names),
                       fetch_names=fetch_names, param_names=param_names,
                       scope=fscope, fused_conv_bn=fused,
                       meta={"state_vars": state_vars})


def load_frozen(model_dir: str, model_filename=None, params_filename=None,
                ) -> FrozenModel:
    """Freeze a saved inference model (fluid.io.save_inference_model
    output) — the disk path serving replicas load from."""
    exe = fluid.Executor()
    scope = Scope()
    with fluid.scope_guard(scope):
        prog, feeds, fetches = fluid.io.load_inference_model(
            model_dir, exe, model_filename=model_filename,
            params_filename=params_filename)
    fm = freeze_program(prog, scope=scope, feed_names=feeds,
                        fetch_list=fetches)
    fm.meta["model_dir"] = model_dir
    return fm
