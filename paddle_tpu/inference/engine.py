"""Continuous-batching generation engine.

Where the r19 MicroBatcher coalesces whole requests into padded
micro-batches (every request enters and leaves together), this engine
schedules at *iteration* granularity: a single decode loop runs ONE
compiled step shape — ``max_slots`` batch slots x one token — and
requests join free slots at step boundaries, retire mid-loop the moment
they finish, and never force a retrace (slot occupancy changes the
*data*, not the shape; dead slots read/write the KV pool's trash page).

Modes, selected by ``PADDLE_SERVE_KV_CACHE`` (default on):

* **paged** — prompts prefill once into pool pages (with page-granular
  prefix-cache reuse), then every generated token is one fixed-shape
  ``decode_step`` attending over cached pages: O(1) positions of new
  work per token.
* **recompute** — the r19-style padded baseline: the whole prefix is
  re-run densely for every token (O(n) positions per token, O(n^2) per
  sequence).  Kept for the flag-off escape hatch and as the oracle the
  cached path is verified against.

Deterministic work accounting (`prefill_positions` / `decode_positions`
/ `recompute_positions`) lets tests assert the O(n)-per-sequence bound
without relying on wall-clock.  Admission, shedding, deadline and
epoch-fenced weight-swap semantics mirror server.MicroBatcher: the only
legal weight swap point is between decode steps, `Overloaded` /
`DeadlineExceeded` reply strings cross the RPC boundary verbatim, and
shed/expired wall-time is charged to the goodput ledger's serving
badput buckets.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import decode_model as dm
from .kv_cache import PagedKVPool
from .server import DeadlineExceeded, Overloaded

ENV_KV_CACHE = "PADDLE_SERVE_KV_CACHE"
ENV_MAX_SLOTS = "PADDLE_SERVE_MAX_SLOTS"

_SERVE_BUCKETS = (1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
                  5000, 10000)


def kv_cache_enabled() -> bool:
    return os.environ.get(ENV_KV_CACHE, "1") not in ("0", "false", "off")


class GenRequest:
    """One admitted generation request."""

    __slots__ = ("prompt", "max_new_tokens", "eos_id", "deadline_t",
                 "event", "tokens", "error", "weight_epoch", "t_admit",
                 "pages", "reuse", "pos", "cur_token", "slot",
                 "rc_tokens", "rc_len", "t_first_token")

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 eos_id: Optional[int], deadline_t: Optional[float]):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.deadline_t = deadline_t
        self.event = threading.Event()
        self.tokens: List[int] = []       # generated tokens (appended)
        self.error: Optional[BaseException] = None
        self.weight_epoch = 0
        self.t_admit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.pages: List[int] = []        # paged mode: physical pages
        self.reuse = 0                    # prompt tokens from prefix cache
        self.pos = 0                      # abs position of cur_token
        self.cur_token = 0
        self.slot: Optional[int] = None
        self.rc_tokens: Optional[np.ndarray] = None  # recompute mode
        self.rc_len = 0

    def snapshot(self, cursor: int = 0) -> dict:
        """Streaming poll: tokens generated past ``cursor`` + liveness.
        List append is atomic under the GIL; no lock needed."""
        toks = self.tokens[cursor:]
        return {
            "tokens": list(toks),
            "cursor": cursor + len(toks),
            "done": self.event.is_set(),
            "error": (f"{self.error}" if self.error is not None else None),
            "weight_epoch": self.weight_epoch,
        }


class GenerationEngine:
    """Iteration-level scheduler over a TinyDecoderLM + PagedKVPool."""

    def __init__(self, model: dm.TinyDecoderLM, *,
                 max_slots: Optional[int] = None,
                 page_size: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 queue_depth: int = 32,
                 kv_cache: Optional[bool] = None,
                 prefix_cache: bool = True,
                 eos_id: Optional[int] = None,
                 step_wait_s: float = 0.02):
        self.model = model
        cfg = model.cfg
        self.max_seq = cfg.max_seq
        self.max_slots = int(max_slots or os.environ.get(
            ENV_MAX_SLOTS, 4))
        self.queue_limit = max(1, int(queue_depth))
        self.kv_cache = (kv_cache_enabled() if kv_cache is None
                         else bool(kv_cache))
        self.prefix_cache = bool(prefix_cache) and self.kv_cache
        self.eos_id = eos_id
        self.step_wait_s = float(step_wait_s)
        self.pool: Optional[PagedKVPool] = None
        if self.kv_cache:
            self.pool = PagedKVPool.from_budget(
                n_layers=cfg.n_layers, kv_heads=cfg.n_heads,
                head_dim=cfg.head_dim, page_size=page_size,
                n_pages=n_pages)
            self.page_size = self.pool.page_size
            self.maxp = -(-self.max_seq // self.page_size)
        self._q: deque = deque()
        self._slots: List[Optional[GenRequest]] = [None] * self.max_slots
        self._cond = threading.Condition()
        self._draining = False
        self._stopped = False
        self._pending_weights = None
        self._wlock = threading.Lock()
        self.weight_epoch = 0
        # deterministic work accounting (the O(n) proof in tests)
        self.counters = {
            "prefill_positions": 0,    # positions computed in prefills
            "cached_positions": 0,     # positions reused from prefix cache
            "decode_positions": 0,     # positions computed by decode steps
            "recompute_positions": 0,  # positions re-run by the baseline
            "tokens_out": 0,
            "decode_steps": 0,
            "served": 0, "shed": 0, "deadline_exceeded": 0, "evicted": 0,
        }
        self._t_start = time.monotonic()
        self._step_ewma_s: Optional[float] = None
        from ..telemetry import get_registry

        self._reg = get_registry()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-genloop")
        self._thread.start()

    # -- admission -------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               deadline_ms: Optional[float] = None,
               eos_id: Optional[int] = None) -> GenRequest:
        prompt = [int(t) for t in prompt]
        if not prompt or len(prompt) >= self.max_seq:
            raise ValueError(
                f"prompt must have 1..{self.max_seq - 1} tokens "
                f"(got {len(prompt)})")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        deadline_t = (time.monotonic() + float(deadline_ms) / 1e3
                      if deadline_ms else None)
        req = GenRequest(prompt, int(max_new_tokens),
                         self.eos_id if eos_id is None else int(eos_id),
                         deadline_t)
        with self._cond:
            if self._draining or self._stopped:
                self._shed(req, "Overloaded: server is draining")
            if len(self._q) >= self.queue_limit:
                self._shed(req, f"Overloaded: admission queue full "
                                f"({len(self._q)}/{self.queue_limit})")
            if self.pool is not None:
                need = self._pages_needed(req)
                if need > self.pool.capacity:
                    self._shed(req, f"Overloaded: request needs {need} "
                                    f"KV pages, pool capacity is "
                                    f"{self.pool.capacity}")
                # conservative fit gate (prefix sharing can only help):
                # bounce work the pool cannot start promptly instead of
                # queueing it behind capacity we don't have
                if need > self.pool.available() and not self._will_free(
                        need):
                    self._shed(req, f"Overloaded: kv pool full ({need} "
                                    f"pages needed, "
                                    f"{self.pool.available()} available)")
            self._q.append(req)
            self._gauge("serve_gen_queue_depth").set(len(self._q))
            self._cond.notify_all()
        return req

    def _will_free(self, need: int) -> bool:
        """Pages active requests will return when they retire."""
        freed = sum(len(r.pages) for r in self._slots if r is not None)
        return self.pool.available() + freed >= need

    def _shed(self, req: GenRequest, msg: str):
        self._count("shed")
        self._badput(req, "shed")
        raise Overloaded(msg)

    def _pages_needed(self, req: GenRequest) -> int:
        total = min(len(req.prompt) + req.max_new_tokens, self.max_seq)
        return -(-total // self.page_size)

    # -- weight fence ----------------------------------------------------

    def stage_weights(self, weights: Dict[str, np.ndarray],
                      version: int) -> None:
        """Same contract as MicroBatcher.stage_weights: the decode LOOP
        installs staged weights between steps — the epoch fence."""
        with self._wlock:
            self._pending_weights = (weights, int(version))
        with self._cond:
            self._cond.notify_all()

    def _maybe_adopt_weights(self) -> None:
        with self._wlock:
            staged, self._pending_weights = self._pending_weights, None
        if staged is None:
            return
        weights, version = staged
        try:
            self.model.adopt(weights)
        except Exception as e:  # noqa: BLE001 — a bad delivery must not
            # kill the loop; serving stays on the current epoch
            self._reg.counter("serve_weight_adopt_errors_total").inc()
            import sys

            print(f"[generation_engine] weight adoption rejected "
                  f"(version {version}): {e}; staying on epoch "
                  f"{self.weight_epoch}", file=sys.stderr, flush=True)
            return
        self.weight_epoch += 1
        self._reg.gauge("serve_weight_epoch").set(self.weight_epoch)
        self._reg.counter("serve_weight_fences_total").inc()

    # -- the decode loop -------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stopped and not self._q and not any(self._slots):
                    return
                if not self._q and not any(self._slots) \
                        and self._pending_weights is None:
                    self._cond.wait(0.05)
            try:
                self._maybe_adopt_weights()  # fence: between steps only
                self._expire_and_admit()
                if any(s is not None for s in self._slots):
                    self._step()
                elif self._q:
                    # queued work that can't start yet (pool/slots):
                    # don't spin
                    time.sleep(0.001)
            except BaseException as e:  # noqa: BLE001 — the loop must
                # never die: fail the implicated requests, keep serving
                for i, r in enumerate(self._slots):
                    if r is not None:
                        self._finish(r, error=e, outcome="error")
                        self._slots[i] = None

    def _expire_and_admit(self) -> None:
        now = time.monotonic()
        # mid-decode deadline eviction: expired requests leave their
        # slot immediately and their pages return to the pool
        for i, r in enumerate(self._slots):
            if r is not None and r.deadline_t is not None \
                    and now >= r.deadline_t:
                self._finish(r, error=DeadlineExceeded(
                    "DeadlineExceeded: request expired mid-decode"),
                    outcome="deadline_exceeded")
                self._slots[i] = None
                self.counters["evicted"] += 1
        with self._cond:
            queued = list(self._q)
        for req in queued:
            if req.deadline_t is not None and now >= req.deadline_t:
                with self._cond:
                    try:
                        self._q.remove(req)
                    except ValueError:
                        continue
                self._finish(req, error=DeadlineExceeded(
                    "DeadlineExceeded: request expired in the queue"),
                    outcome="deadline_exceeded")
                continue
            slot = next((i for i, s in enumerate(self._slots)
                         if s is None), None)
            if slot is None:
                break
            if not self._try_admit(req, slot):
                break  # pool can't fit it yet; keep FIFO order
        self._gauge("serve_gen_queue_depth").set(len(self._q))

    def _try_admit(self, req: GenRequest, slot: int) -> bool:
        if self.pool is None:
            self._admit_recompute(req, slot)
        else:
            matched, covered = ([], 0)
            if self.prefix_cache:
                matched, covered = self.pool.match_prefix(req.prompt)
            # whole-page reuse only, and at least one prompt token must
            # be computed so prefill has logits to sample from
            reuse_pages = min(len(matched),
                              (len(req.prompt) - 1) // self.page_size)
            if reuse_pages < len(matched):
                self.pool.free(matched[reuse_pages:])
                matched = matched[:reuse_pages]
            reuse = reuse_pages * self.page_size
            try:
                fresh = self.pool.alloc(self._pages_needed(req)
                                        - reuse_pages)
            except MemoryError:
                self.pool.free(matched)
                return False
            req.pages = matched + fresh
            req.reuse = reuse
            self._prefill_paged(req, slot)
        with self._cond:
            try:
                self._q.remove(req)
            except ValueError:
                pass
        self._slots[slot] = req
        req.slot = slot
        if req.event.is_set():  # finished during prefill (eos/max_new)
            self._slots[slot] = None
        return True

    # -- paged mode ------------------------------------------------------

    def _table_row(self, req: GenRequest) -> np.ndarray:
        row = np.zeros(self.maxp, np.int32)
        row[:len(req.pages)] = req.pages
        return row

    def _prefill_paged(self, req: GenRequest, slot: int) -> None:
        import jax.numpy as jnp

        pool, psz = self.pool, self.page_size
        n_valid = len(req.prompt) - req.reuse
        r = min(dm.prefill_bucket(n_valid), self.max_seq)
        window = np.zeros(r, np.int32)
        window[:n_valid] = req.prompt[req.reuse:]
        ctx_k, ctx_v = dm.gather_ctx(pool.k, pool.v,
                                     jnp.asarray(self._table_row(req)),
                                     page_size=psz)
        t0 = time.perf_counter()
        logits, tok, k_win, v_win = dm.prefill(
            self.model.params, jnp.asarray(window),
            jnp.int32(req.reuse), ctx_k, ctx_v, jnp.int32(n_valid),
            n_heads=self.model.cfg.n_heads)
        flat = np.zeros(r, np.int32)
        for i in range(n_valid):
            p_abs = req.reuse + i
            flat[i] = req.pages[p_abs // psz] * psz + p_abs % psz
        pool.set_arrays(*dm.scatter_kv(pool.k, pool.v, k_win, v_win,
                                       jnp.asarray(flat)))
        self._observe_ms("serve_prefill_ms", t0)
        if self.prefix_cache:
            pool.register_prefix(req.prompt,
                                 req.pages[:len(req.prompt) // psz])
        self.counters["prefill_positions"] += n_valid
        self.counters["cached_positions"] += req.reuse
        self._tok_counter("prefill").inc(n_valid)
        req.pos = len(req.prompt)
        self._emit(req, int(tok))

    def _step_paged(self, active: List[GenRequest]) -> None:
        import jax.numpy as jnp

        pool, psz, b = self.pool, self.page_size, self.max_slots
        tokens = np.zeros(b, np.int32)
        positions = np.zeros(b, np.int32)
        write_flat = np.zeros(b, np.int32)
        table = np.zeros((b, self.maxp), np.int32)
        for r in active:
            pid = r.pages[r.pos // psz]
            # COW safety: never write a shared/cached page in place
            new_pid, needs_copy = pool.ensure_private(pid)
            if needs_copy:
                pool.set_arrays(*dm.copy_page(
                    pool.k, pool.v, jnp.int32(pid), jnp.int32(new_pid),
                    page_size=psz))
                r.pages[r.pos // psz] = new_pid
                pid = new_pid
            tokens[r.slot] = r.cur_token
            positions[r.slot] = r.pos
            write_flat[r.slot] = pid * psz + r.pos % psz
            table[r.slot, :len(r.pages)] = r.pages
        t0 = time.perf_counter()
        logits, nxt, k, v = dm.decode_step(
            self.model.params, pool.k, pool.v, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(table),
            jnp.asarray(write_flat), page_size=psz,
            n_heads=self.model.cfg.n_heads)
        pool.set_arrays(k, v)
        nxt = np.asarray(nxt)
        self._observe_ms("serve_decode_step_ms", t0)
        self.counters["decode_steps"] += 1
        self.counters["decode_positions"] += len(active)
        self._tok_counter("decode").inc(len(active))
        for r in active:
            r.pos += 1
            self._emit(r, int(nxt[r.slot]))

    # -- recompute baseline (PADDLE_SERVE_KV_CACHE=0) --------------------

    def _admit_recompute(self, req: GenRequest, slot: int) -> None:
        req.rc_tokens = np.zeros(self.max_seq, np.int32)
        req.rc_tokens[:len(req.prompt)] = req.prompt
        req.rc_len = len(req.prompt)

    def _step_recompute(self, active: List[GenRequest]) -> None:
        import jax.numpy as jnp

        b = self.max_slots
        tokens = np.zeros((b, self.max_seq), np.int32)
        lengths = np.ones(b, np.int32)
        for r in active:
            tokens[r.slot] = r.rc_tokens
            lengths[r.slot] = r.rc_len
        t0 = time.perf_counter()
        logits, nxt = dm.recompute_step(
            self.model.params, jnp.asarray(tokens),
            jnp.asarray(lengths), n_heads=self.model.cfg.n_heads)
        nxt = np.asarray(nxt)
        self._observe_ms("serve_decode_step_ms", t0)
        self.counters["decode_steps"] += 1
        # the whole live prefix was re-run for ONE new token per slot —
        # this counter is the measured O(n^2) the paged path removes
        self.counters["recompute_positions"] += int(
            sum(r.rc_len for r in active))
        self._tok_counter("decode").inc(len(active))
        for r in active:
            tok = int(nxt[r.slot])
            if r.rc_len < self.max_seq:
                r.rc_tokens[r.rc_len] = tok
            r.rc_len += 1
            self._emit(r, tok)

    # -- shared loop pieces ---------------------------------------------

    def _step(self) -> None:
        active = [r for r in self._slots if r is not None]
        if not active:
            return
        if self.pool is not None:
            self._step_paged(active)
        else:
            self._step_recompute(active)
        for i, r in enumerate(self._slots):
            if r is not None and r.event.is_set():
                self._slots[i] = None
        if self.pool is not None:
            self.pool.publish_gauges()

    def _emit(self, req: GenRequest, tok: int) -> None:
        """Append one generated token; retire on eos/max_new/capacity."""
        if req.t_first_token is None:
            req.t_first_token = time.monotonic()
        req.tokens.append(tok)
        self.counters["tokens_out"] += 1
        done = (len(req.tokens) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id))
        total = len(req.prompt) + len(req.tokens)
        if not done and total >= self.max_seq:
            done = True  # context capacity reached
        if done:
            self._finish(req, outcome="served")
        else:
            req.cur_token = tok

    def _finish(self, req: GenRequest,
                error: Optional[BaseException] = None,
                outcome: str = "served") -> None:
        if req.event.is_set():
            return
        if self.pool is not None and req.pages:
            self.pool.free(req.pages)
            req.pages = []
        req.error = error
        req.weight_epoch = self.weight_epoch
        self._count(outcome)
        if outcome == "deadline_exceeded":
            self._badput(req, "deadline")
        self._observe_ms("serve_gen_request_ms",
                         None, ms=(time.monotonic() - req.t_admit) * 1e3)
        req.event.set()
        with self._cond:
            self._cond.notify_all()

    # -- client side -----------------------------------------------------

    def result(self, req: GenRequest,
               timeout: Optional[float] = None) -> dict:
        grace = 30.0
        if timeout is None and req.deadline_t is not None:
            timeout = max(0.0, req.deadline_t - time.monotonic()) + grace
        if not req.event.wait(timeout):
            raise DeadlineExceeded(
                "DeadlineExceeded: generation did not complete in time")
        if req.error is not None:
            raise req.error
        return {
            "tokens": list(req.tokens),
            "weight_epoch": req.weight_epoch,
            "ttft_ms": (None if req.t_first_token is None else round(
                (req.t_first_token - req.t_admit) * 1e3, 3)),
        }

    # -- lifecycle / observability ---------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = time.monotonic() + timeout
        with self._cond:
            while (self._q or any(s is not None for s in self._slots)) \
                    and time.monotonic() < deadline:
                self._cond.wait(0.1)
            return not self._q and not any(
                s is not None for s in self._slots)

    def stop(self) -> None:
        self.drain(timeout=5.0)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)

    def stats(self) -> dict:
        c = dict(self.counters)
        dt = max(1e-9, time.monotonic() - self._t_start)
        out = {
            "mode": "paged" if self.pool is not None else "recompute",
            "max_slots": self.max_slots,
            "active_slots": sum(1 for s in self._slots if s is not None),
            "queue_depth": len(self._q),
            "draining": self._draining,
            "weight_epoch": self.weight_epoch,
            "tokens_total": c["tokens_out"],
            "tokens_per_s": round(c["tokens_out"] / dt, 3),
            "decode_steps": c["decode_steps"],
            "prefill_positions_total": c["prefill_positions"],
            "cached_positions_total": c["cached_positions"],
            "decode_positions_total": c["decode_positions"],
            "recompute_positions_total": c["recompute_positions"],
            "served_total": c["served"],
            "shed_total": c["shed"],
            "deadline_exceeded_total": c["deadline_exceeded"],
            "evicted_total": c["evicted"],
            "step_ewma_ms": (None if self._step_ewma_s is None
                             else round(self._step_ewma_s * 1e3, 3)),
        }
        if self.pool is not None:
            out["kv_pool"] = self.pool.stats()
        return out

    # -- small helpers ---------------------------------------------------

    def _count(self, outcome: str) -> None:
        if outcome in self.counters:
            self.counters[outcome] += 1
        self._reg.counter("serve_gen_requests_total",
                          outcome=outcome).inc()

    def _tok_counter(self, phase: str):
        return self._reg.counter(
            "serve_tokens_total",
            help="generated/prefilled token positions by phase",
            phase=phase)

    def _gauge(self, name: str):
        return self._reg.gauge(name)

    def _observe_ms(self, name: str, t0: Optional[float],
                    ms: Optional[float] = None) -> None:
        if ms is None:
            ms = (time.perf_counter() - t0) * 1e3
        if name == "serve_decode_step_ms":
            s = ms / 1e3
            self._step_ewma_s = (s if self._step_ewma_s is None
                                 else 0.8 * self._step_ewma_s + 0.2 * s)
        self._reg.histogram(name, buckets=_SERVE_BUCKETS).observe(ms)

    def _badput(self, req: GenRequest, cause: str) -> None:
        try:
            from ..telemetry import goodput as _goodput

            _goodput.note_serving_badput(
                (time.monotonic() - req.t_admit) * 1e3, cause=cause)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass
