"""Continuous-batching generation engine.

Where the r19 MicroBatcher coalesces whole requests into padded
micro-batches (every request enters and leaves together), this engine
schedules at *iteration* granularity: a single decode loop runs ONE
compiled step shape — ``max_slots`` batch slots x one token — and
requests join free slots at step boundaries, retire mid-loop the moment
they finish, and never force a retrace (slot occupancy changes the
*data*, not the shape; dead slots read/write the KV pool's trash page).

Modes, selected by ``PADDLE_SERVE_KV_CACHE`` (default on):

* **paged** — prompts prefill once into pool pages (with page-granular
  prefix-cache reuse), then every generated token is one fixed-shape
  ``decode_step`` attending over cached pages: O(1) positions of new
  work per token.
* **recompute** — the r19-style padded baseline: the whole prefix is
  re-run densely for every token (O(n) positions per token, O(n^2) per
  sequence).  Kept for the flag-off escape hatch and as the oracle the
  cached path is verified against.

Deterministic work accounting (`prefill_positions` / `decode_positions`
/ `recompute_positions`) lets tests assert the O(n)-per-sequence bound
without relying on wall-clock.  Admission, shedding, deadline and
epoch-fenced weight-swap semantics mirror server.MicroBatcher: the only
legal weight swap point is between decode steps, `Overloaded` /
`DeadlineExceeded` reply strings cross the RPC boundary verbatim, and
shed/expired wall-time is charged to the goodput ledger's serving
badput buckets.

Crash tolerance (r22, gated on ``PADDLE_SERVE_RESUME``, default on):

* **resume admission** — `submit(resume_tokens=...)` re-admits a
  generation whose prefix (prompt + tokens already delivered) was
  computed elsewhere: the prefix prefills as one window (page-granular
  prefix-cache reuse makes the replayed prompt cheap), the SLO clock is
  backdated by ``elapsed_ms`` so failover never resets deadline
  accounting, and ``expect_epoch`` refuses a cross-epoch splice with
  the typed `ResumedOnNewWeights`.  Resumes queue ahead of fresh
  admissions — degrade by shedding new work before abandoning old work.
* **preemption ladder** — when a fresh request cannot be placed, the
  active request with the MOST remaining work is preempted (pages
  freed, tokens kept, same GenRequest requeued through the resume
  path) instead of the queue head deadline-starving.  A victim is only
  taken when it has strictly more remaining work than the incoming
  request, and resumes themselves never preempt — both rules together
  make the ladder livelock-free.  Preempt/resume wall-time latches
  into the goodput ledger's `serve_preempt`/`serve_resume` buckets.
* **sampling** — temperature/top-k/top-p ride the single `_emit` choke
  point (host-side, from the logits every step already returns); the
  per-request seed and the token INDEX feed a counter-mode PRNG, so a
  resumed sampled generation replays bit-identically. Top-p (nucleus)
  composes after top-k and, like top-k, is active only when a
  temperature is set — greedy requests stay on the device argmax.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..distributed import faults as _faults
from ..telemetry import sink as _sink
from ..telemetry import tracing as _tracing
from . import decode_model as dm
from .kv_cache import PagedKVPool
from .server import (DeadlineExceeded, Overloaded, ResumedOnNewWeights,
                     resume_enabled)

ENV_KV_CACHE = "PADDLE_SERVE_KV_CACHE"
ENV_MAX_SLOTS = "PADDLE_SERVE_MAX_SLOTS"

_SERVE_BUCKETS = (1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
                  5000, 10000)


def kv_cache_enabled() -> bool:
    return os.environ.get(ENV_KV_CACHE, "1") not in ("0", "false", "off")


def _sample_token(logits: np.ndarray, temperature: float,
                  top_k: Optional[int], seed: int, index: int,
                  top_p: Optional[float] = None) -> int:
    """Deterministic temperature/top-k/top-p sampling at token ``index``.

    Counter-mode: the PRNG is keyed on (seed, index), never on call
    order or engine state — the token at index i depends only on the
    prefix (via logits) and the request seed, which is exactly what
    makes a resumed/preempted sampled generation replay the same
    tokens the uninterrupted run produced.

    Top-p (nucleus) filtering composes after top-k: the smallest set of
    highest-probability tokens whose cumulative mass reaches ``top_p``
    survives, the tail is zeroed, and the nucleus is renormalized. The
    sort is stable on descending probability so ties resolve by token
    id — the filter is a pure function of (logits, knobs), keeping the
    resume-replay contract bit-exact."""
    scores = np.asarray(logits, np.float64) / max(float(temperature),
                                                  1e-6)
    if top_k and 0 < int(top_k) < scores.size:
        kth = np.partition(scores, -int(top_k))[-int(top_k)]
        scores = np.where(scores >= kth, scores, -np.inf)
    scores -= scores.max()
    probs = np.exp(scores)
    probs /= probs.sum()
    if top_p is not None and 0.0 < float(top_p) < 1.0:
        order = np.argsort(-probs, kind="stable")
        csum = np.cumsum(probs[order])
        # smallest prefix whose mass >= top_p (always >= 1 token)
        cut = int(np.searchsorted(csum, float(top_p))) + 1
        keep = order[:cut]
        mask = np.zeros_like(probs)
        mask[keep] = probs[keep]
        probs = mask / mask.sum()
    rng = np.random.default_rng(
        [int(seed) & 0xFFFFFFFF, int(index) & 0xFFFFFFFF])
    return int(rng.choice(scores.size, p=probs))


class GenRequest:
    """One admitted generation request."""

    __slots__ = ("prompt", "max_new_tokens", "eos_id", "deadline_t",
                 "event", "tokens", "error", "weight_epoch", "t_admit",
                 "pages", "reuse", "pos", "cur_token", "slot",
                 "rc_tokens", "rc_len", "t_first_token",
                 "temperature", "top_k", "top_p", "seed", "resumed_from",
                 "expect_epoch", "is_resume", "t_preempt", "preempts",
                 "span", "queue_span", "t_enq", "t_last_token",
                 "queue_ms")

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 eos_id: Optional[int], deadline_t: Optional[float],
                 resume_tokens: Optional[List[int]] = None,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None,
                 seed: Optional[int] = None,
                 top_p: Optional[float] = None):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.deadline_t = deadline_t
        self.event = threading.Event()
        # generated tokens (appended). A resume pre-seeds the tokens
        # another replica already delivered — they are part of the
        # prefill prefix, never re-counted as new output.
        self.tokens: List[int] = list(resume_tokens or [])
        self.resumed_from = len(self.tokens)
        self.error: Optional[BaseException] = None
        self.weight_epoch = 0
        self.t_admit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.pages: List[int] = []        # paged mode: physical pages
        self.reuse = 0                    # prefix tokens from prefix cache
        self.pos = 0                      # abs position of cur_token
        self.cur_token = 0
        self.slot: Optional[int] = None
        self.rc_tokens: Optional[np.ndarray] = None  # recompute mode
        self.rc_len = 0
        # sampling (None temperature => greedy argmax on device)
        self.temperature = (float(temperature)
                            if temperature else None)
        self.top_k = int(top_k) if top_k else None
        self.top_p = float(top_p) if top_p else None
        self.seed = int(seed) if seed is not None else 0
        self.expect_epoch: Optional[int] = None
        self.is_resume = resume_tokens is not None
        self.t_preempt: Optional[float] = None
        self.preempts = 0
        # ISSUE 19 request-lifecycle tracing: the umbrella span for the
        # whole engine residency (parented under the propagated RPC
        # context so one trace_id spans client -> replica(s)), the open
        # queue_wait child, and the SLO clocks
        self.span = None
        self.queue_span = None
        self.t_enq = time.monotonic()
        self.t_last_token: Optional[float] = None
        self.queue_ms = 0.0

    def snapshot(self, cursor: int = 0) -> dict:
        """Streaming poll: tokens generated past ``cursor`` + liveness.
        List append is atomic under the GIL; no lock needed."""
        toks = self.tokens[cursor:]
        return {
            "tokens": list(toks),
            "cursor": cursor + len(toks),
            "done": self.event.is_set(),
            "error": (f"{self.error}" if self.error is not None else None),
            "weight_epoch": self.weight_epoch,
        }


class GenerationEngine:
    """Iteration-level scheduler over a TinyDecoderLM + PagedKVPool."""

    def __init__(self, model: dm.TinyDecoderLM, *,
                 max_slots: Optional[int] = None,
                 page_size: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 queue_depth: int = 32,
                 kv_cache: Optional[bool] = None,
                 prefix_cache: bool = True,
                 eos_id: Optional[int] = None,
                 step_wait_s: float = 0.02):
        self.model = model
        cfg = model.cfg
        self.max_seq = cfg.max_seq
        self.max_slots = int(max_slots or os.environ.get(
            ENV_MAX_SLOTS, 4))
        self.queue_limit = max(1, int(queue_depth))
        self.kv_cache = (kv_cache_enabled() if kv_cache is None
                         else bool(kv_cache))
        self.prefix_cache = bool(prefix_cache) and self.kv_cache
        self.eos_id = eos_id
        self.step_wait_s = float(step_wait_s)
        self.pool: Optional[PagedKVPool] = None
        if self.kv_cache:
            self.pool = PagedKVPool.from_budget(
                n_layers=cfg.n_layers, kv_heads=cfg.n_heads,
                head_dim=cfg.head_dim, page_size=page_size,
                n_pages=n_pages)
            self.page_size = self.pool.page_size
            self.maxp = -(-self.max_seq // self.page_size)
        self._q: deque = deque()
        # resumes (failover re-admissions + preemption victims) queue
        # separately and admit FIRST: shed new work before abandoning
        # old work
        self._rq: deque = deque()
        self.resume_on = resume_enabled()
        self._slots: List[Optional[GenRequest]] = [None] * self.max_slots
        self._cond = threading.Condition()
        self._draining = False
        self._stopped = False
        self._pending_weights = None
        self._wlock = threading.Lock()
        self.weight_epoch = 0
        # deterministic work accounting (the O(n) proof in tests)
        self.counters = {
            "prefill_positions": 0,    # positions computed in prefills
            "cached_positions": 0,     # positions reused from prefix cache
            "decode_positions": 0,     # positions computed by decode steps
            "recompute_positions": 0,  # positions re-run by the baseline
            "tokens_out": 0,
            "decode_steps": 0,
            "served": 0, "shed": 0, "deadline_exceeded": 0, "evicted": 0,
            # preemption ladder: positions freed at preemption must be
            # matched 1:1 by positions restored at resume prefill — the
            # exact-token-accounting proof the drills assert
            "preempted": 0, "resumed": 0,
            "preempt_positions": 0, "resume_positions": 0,
        }
        self._t_start = time.monotonic()
        self._step_ewma_s: Optional[float] = None
        # recent completions (newest last) for debugz /servez — kept
        # tracing-on or off; records carry trace ids only when traced
        self._recent: deque = deque(maxlen=64)
        from ..telemetry import get_registry

        self._reg = get_registry()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-genloop")
        self._thread.start()

    # -- admission -------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               deadline_ms: Optional[float] = None,
               eos_id: Optional[int] = None,
               resume_tokens: Optional[Sequence[int]] = None,
               elapsed_ms: Optional[float] = None,
               expect_epoch: Optional[int] = None,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               seed: Optional[int] = None,
               top_p: Optional[float] = None,
               trace_ctx=None) -> GenRequest:
        prompt = [int(t) for t in prompt]
        if not prompt or len(prompt) >= self.max_seq:
            raise ValueError(
                f"prompt must have 1..{self.max_seq - 1} tokens "
                f"(got {len(prompt)})")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if resume_tokens is not None and not self.resume_on:
            raise ValueError("generation resume is disabled "
                             "(PADDLE_SERVE_RESUME=0)")
        if expect_epoch is not None and int(expect_epoch) \
                != self.weight_epoch:
            raise ResumedOnNewWeights(
                f"ResumedOnNewWeights: resume expected weight epoch "
                f"{int(expect_epoch)}, this replica serves epoch "
                f"{self.weight_epoch}")
        resume_tokens = ([int(t) for t in resume_tokens]
                         if resume_tokens is not None else None)
        deadline_t = (time.monotonic() + float(deadline_ms) / 1e3
                      if deadline_ms else None)
        req = GenRequest(prompt, int(max_new_tokens),
                         self.eos_id if eos_id is None else int(eos_id),
                         deadline_t, resume_tokens=resume_tokens,
                         temperature=temperature, top_k=top_k, seed=seed,
                         top_p=top_p)
        if elapsed_ms:
            # carry the ORIGINAL arrival time across a failover: SLO
            # accounting (request latency, badput charges) never resets
            req.t_admit -= float(elapsed_ms) / 1e3
        req.expect_epoch = (int(expect_epoch)
                            if expect_epoch is not None else None)
        if _tracing.enabled():
            # umbrella span for the engine residency. The RPC handler
            # thread dispatches inside the propagated `server:generate`
            # scope, so "auto" parenting picks up the client's trace_id
            # with zero extra wire plumbing; a failover resume carries
            # the same trace, so ONE trace spans both replicas.
            req.span = _tracing.begin(
                "gen_request", kind="server",
                parent=(trace_ctx if trace_ctx is not None else "auto"),
                attrs={"prompt_len": len(prompt),
                       "max_new_tokens": int(max_new_tokens),
                       "resume": bool(req.is_resume),
                       "resumed_from": req.resumed_from})
        if req.is_resume and (
                len(req.tokens) >= req.max_new_tokens
                or len(prompt) + len(req.tokens) >= self.max_seq
                or (req.eos_id is not None and req.tokens
                    and req.tokens[-1] == req.eos_id)):
            # everything was already delivered — only the done marker
            # was lost; finish without touching the model
            self._finish(req, outcome="served")
            return req
        q = self._rq if req.is_resume else self._q
        with self._cond:
            if self._draining or self._stopped:
                self._shed(req, "Overloaded: server is draining")
            if len(q) >= self.queue_limit:
                self._shed(req, f"Overloaded: admission queue full "
                                f"({len(q)}/{self.queue_limit})")
            if self.pool is not None:
                need = self._pages_needed(req)
                if need > self.pool.capacity:
                    self._shed(req, f"Overloaded: request needs {need} "
                                    f"KV pages, pool capacity is "
                                    f"{self.pool.capacity}")
                # conservative fit gate (prefix sharing can only help):
                # bounce work the pool cannot start promptly instead of
                # queueing it behind capacity we don't have
                if need > self.pool.available() and not self._will_free(
                        need):
                    self._shed(req, f"Overloaded: kv pool full ({need} "
                                    f"pages needed, "
                                    f"{self.pool.available()} available)")
            req.t_enq = time.monotonic()
            req.queue_span = self._req_span(
                req, "queue_wait", attrs={"resume": req.is_resume})
            q.append(req)
            self._gauge("serve_gen_queue_depth").set(len(self._q))
            self._cond.notify_all()
        return req

    def _will_free(self, need: int) -> bool:
        """Pages active requests will return when they retire."""
        freed = sum(len(r.pages) for r in self._slots if r is not None)
        return self.pool.available() + freed >= need

    def _shed(self, req: GenRequest, msg: str):
        self._count("shed")
        self._badput(req, "shed")
        self._retire_trace(req, "shed", detail=msg)
        raise Overloaded(msg)

    def _pages_needed(self, req: GenRequest) -> int:
        total = min(len(req.prompt) + req.max_new_tokens, self.max_seq)
        return -(-total // self.page_size)

    # -- weight fence ----------------------------------------------------

    def stage_weights(self, weights: Dict[str, np.ndarray],
                      version: int) -> None:
        """Same contract as MicroBatcher.stage_weights: the decode LOOP
        installs staged weights between steps — the epoch fence."""
        with self._wlock:
            self._pending_weights = (weights, int(version))
        with self._cond:
            self._cond.notify_all()

    def _maybe_adopt_weights(self) -> None:
        with self._wlock:
            staged, self._pending_weights = self._pending_weights, None
        if staged is None:
            return
        weights, version = staged
        try:
            self.model.adopt(weights)
        except Exception as e:  # noqa: BLE001 — a bad delivery must not
            # kill the loop; serving stays on the current epoch
            self._reg.counter("serve_weight_adopt_errors_total").inc()
            import sys

            print(f"[generation_engine] weight adoption rejected "
                  f"(version {version}): {e}; staying on epoch "
                  f"{self.weight_epoch}", file=sys.stderr, flush=True)
            return
        self.weight_epoch += 1
        self._reg.gauge("serve_weight_epoch").set(self.weight_epoch)
        self._reg.counter("serve_weight_fences_total").inc()
        for r in self._slots:
            if r is not None:
                self._event_span(r, "weight_fence",
                                 attrs={"epoch": self.weight_epoch})
        # every live request's tail now decodes under the new epoch —
        # stream snapshots carry it so a client resuming elsewhere can
        # state which epoch its expectation belongs to
        with self._cond:
            live = ([r for r in self._slots if r is not None]
                    + list(self._q) + list(self._rq))
        for r in live:
            r.weight_epoch = self.weight_epoch

    # -- the decode loop -------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stopped and not self._q and not self._rq \
                        and not any(self._slots):
                    return
                if not self._q and not self._rq \
                        and not any(self._slots) \
                        and self._pending_weights is None:
                    self._cond.wait(0.05)
            try:
                self._maybe_adopt_weights()  # fence: between steps only
                self._expire_and_admit()
                if any(s is not None for s in self._slots):
                    self._step()
                elif self._q or self._rq:
                    # queued work that can't start yet (pool/slots):
                    # don't spin
                    time.sleep(0.001)
            except BaseException as e:  # noqa: BLE001 — the loop must
                # never die: fail the implicated requests, keep serving
                for i, r in enumerate(self._slots):
                    if r is not None:
                        self._finish(r, error=e, outcome="error")
                        self._slots[i] = None

    def _expire_and_admit(self) -> None:
        now = time.monotonic()
        # mid-decode deadline eviction: expired requests leave their
        # slot immediately and their pages return to the pool
        for i, r in enumerate(self._slots):
            if r is not None and r.deadline_t is not None \
                    and now >= r.deadline_t:
                self._event_span(r, "evict",
                                 attrs={"reason": "deadline",
                                        "tokens": len(r.tokens),
                                        "pos": r.pos})
                self._finish(r, error=DeadlineExceeded(
                    "DeadlineExceeded: request expired mid-decode"),
                    outcome="deadline_exceeded")
                self._slots[i] = None
                self.counters["evicted"] += 1
        # resumes first (old work beats fresh admissions for pages),
        # and they never preempt — freed pages flow to them by priority
        self._admit_from(self._rq, now, allow_preempt=False)
        self._admit_from(self._q, now,
                         allow_preempt=self.resume_on)
        self._gauge("serve_gen_queue_depth").set(len(self._q))
        self._gauge("serve_gen_resume_queue_depth").set(len(self._rq))

    def _admit_from(self, q: deque, now: float,
                    allow_preempt: bool) -> None:
        for req in list(q):
            if req.deadline_t is not None and now >= req.deadline_t:
                with self._cond:
                    try:
                        q.remove(req)
                    except ValueError:
                        continue
                self._finish(req, error=DeadlineExceeded(
                    "DeadlineExceeded: request expired in the queue"),
                    outcome="deadline_exceeded")
                continue
            slot = next((i for i, s in enumerate(self._slots)
                         if s is None), None)
            if slot is None:
                break
            if not self._try_admit(req, slot):
                # pool can't fit it: climb the preemption ladder once
                # (fresh queue only), else keep FIFO order and wait
                if not (allow_preempt and self._preempt_for(req)
                        and self._try_admit(req, slot)):
                    break

    # -- preemption ladder (PADDLE_SERVE_RESUME gate) --------------------

    def _preempt_for(self, incoming: GenRequest) -> bool:
        """Free pages for ``incoming`` by preempting the active request
        with the MOST remaining work — but only when it has strictly
        more left than the incoming request (shorter job first), so the
        preempted request can never bounce straight back and evict its
        evictor: remaining work strictly decreases down the ladder."""
        if self.pool is None or not self.resume_on:
            return False
        active = [r for r in self._slots if r is not None]
        if not active:
            return False

        def remaining(r: GenRequest) -> int:
            return r.max_new_tokens - len(r.tokens)

        victim = max(active, key=remaining)
        if remaining(victim) <= remaining(incoming):
            return False
        self._preempt(victim)
        return True

    def _preempt(self, victim: GenRequest) -> None:
        """Evict ``victim`` mid-decode WITHOUT failing it: pages return
        to the pool (prompt pages usually park in the prefix cache, so
        the re-prefill is bounded, not a restart), tokens-so-far stay
        on the request, and the same GenRequest object requeues through
        the resume path — waiters and stream pollers never notice."""
        slot = victim.slot
        self.counters["preempted"] += 1
        self.counters["preempt_positions"] += (
            len(victim.prompt) + len(victim.tokens))
        self._reg.counter(
            "serve_gen_preempted_total",
            help="active generations preempted for KV pressure").inc()
        self._event_span(victim, "preempt",
                         attrs={"pages_freed": len(victim.pages),
                                "tokens": len(victim.tokens),
                                "pos": victim.pos})
        if victim.pages:
            self.pool.free(victim.pages)
            victim.pages = []
        victim.reuse = 0
        victim.slot = None
        victim.is_resume = True
        victim.t_preempt = time.monotonic()
        victim.preempts += 1
        self._slots[slot] = None
        with self._cond:
            victim.t_enq = time.monotonic()
            victim.queue_span = self._req_span(
                victim, "queue_wait", attrs={"resume": True,
                                             "preempted": True})
            self._rq.append(victim)
        _tracing.flight_dump("serve_preempt")

    def _try_admit(self, req: GenRequest, slot: int) -> bool:
        if req.expect_epoch is not None \
                and req.expect_epoch != self.weight_epoch:
            # a weight fence installed between submit and admission:
            # refuse the cross-epoch splice before any prefill runs
            self._dequeue(req)
            self._finish(req, error=ResumedOnNewWeights(
                f"ResumedOnNewWeights: resume expected weight epoch "
                f"{req.expect_epoch}, this replica serves epoch "
                f"{self.weight_epoch}"), outcome="error")
            return True
        req.weight_epoch = self.weight_epoch
        wait_ms = (time.monotonic() - req.t_enq) * 1e3
        req.queue_ms += wait_ms
        if req.queue_span is not None:
            req.queue_span.attrs["wait_ms"] = round(wait_ms, 3)
        _tracing.finish(req.queue_span)
        req.queue_span = None
        self._reg.histogram(
            "serve_queue_wait_ms", buckets=_SERVE_BUCKETS,
            help="generation admission wait (enqueue -> slot+pages)",
        ).observe(wait_ms,
                  trace_id=(req.span.trace_id if req.span is not None
                            else None))
        if req.is_resume:
            self._event_span(req, "resume",
                             attrs={"prefix_len": (len(req.prompt)
                                                   + len(req.tokens)),
                                    "preempts": req.preempts})
        # resume prefix: the prompt plus whatever tokens were already
        # delivered (empty for fresh requests — prefix == prompt)
        prefix = req.prompt + req.tokens
        if self.pool is None:
            if req.is_resume:
                self._note_resume(req, len(prefix))
            self._admit_recompute(req, slot)
        else:
            matched, covered = ([], 0)
            if self.prefix_cache:
                matched, covered = self.pool.match_prefix(prefix)
            # whole-page reuse only, and at least one prefix token must
            # be computed so prefill has logits to sample from
            reuse_pages = min(len(matched),
                              (len(prefix) - 1) // self.page_size)
            if reuse_pages < len(matched):
                self.pool.free(matched[reuse_pages:])
                matched = matched[:reuse_pages]
            reuse = reuse_pages * self.page_size
            try:
                fresh = self.pool.alloc(self._pages_needed(req)
                                        - reuse_pages)
            except MemoryError:
                self.pool.free(matched)
                return False
            req.pages = matched + fresh
            req.reuse = reuse
            if req.is_resume:
                self._note_resume(req, len(prefix))
            self._prefill_paged(req, slot)
        self._dequeue(req)
        req.is_resume = False
        self._slots[slot] = req
        req.slot = slot
        if req.event.is_set():  # finished during prefill (eos/max_new)
            self._slots[slot] = None
        return True

    def _dequeue(self, req: GenRequest) -> None:
        with self._cond:
            for q in (self._q, self._rq):
                try:
                    q.remove(req)
                except ValueError:
                    pass

    def _note_resume(self, req: GenRequest, prefix_len: int) -> None:
        self.counters["resumed"] += 1
        self.counters["resume_positions"] += prefix_len
        self._reg.counter(
            "serve_gen_resumed_total",
            help="generations re-admitted from a supplied prefix "
                 "(failover resumes + preemption victims)").inc()
        if req.t_preempt is not None:
            # off-device wall time between preemption and re-admission
            self._badput_ms((time.monotonic() - req.t_preempt) * 1e3,
                            "preempt")
            req.t_preempt = None

    # -- paged mode ------------------------------------------------------

    def _table_row(self, req: GenRequest) -> np.ndarray:
        row = np.zeros(self.maxp, np.int32)
        row[:len(req.pages)] = req.pages
        return row

    def _prefill_paged(self, req: GenRequest, slot: int) -> None:
        import jax.numpy as jnp

        pool, psz = self.pool, self.page_size
        # the prefill prefix is prompt + already-delivered tokens — for
        # fresh requests that's just the prompt; for resumes the
        # delivered tail rides the same window (and the prompt's pages
        # usually come back from the prefix cache)
        prefix = req.prompt + req.tokens
        n_valid = len(prefix) - req.reuse
        psp = self._req_span(req, "prefill",
                             attrs={"positions": n_valid,
                                    "cached": req.reuse,
                                    "prefix_hit": req.reuse > 0,
                                    "pages": len(req.pages)})
        # the decode loop is busy prefilling THIS request — every other
        # active slot stalls for the same wall time. A peer_prefill span
        # per co-batched request makes that bubble attributable ("my p99
        # came from peer prefill"), and closes the coverage gap the
        # >=90%-attribution drill measures.
        peers = [(r, self._req_span(
            r, "peer_prefill",
            attrs={"peer_trace": (req.span.trace_id
                                  if req.span is not None else None),
                   "positions": n_valid}))
            for r in self._slots if r is not None and r is not req]
        r = min(dm.prefill_bucket(n_valid), self.max_seq)
        window = np.zeros(r, np.int32)
        window[:n_valid] = prefix[req.reuse:]
        ctx_k, ctx_v = dm.gather_ctx(pool.k, pool.v,
                                     jnp.asarray(self._table_row(req)),
                                     page_size=psz)
        t0 = time.perf_counter()
        logits, tok, k_win, v_win = dm.prefill(
            self.model.params, jnp.asarray(window),
            jnp.int32(req.reuse), ctx_k, ctx_v, jnp.int32(n_valid),
            n_heads=self.model.cfg.n_heads)
        flat = np.zeros(r, np.int32)
        for i in range(n_valid):
            p_abs = req.reuse + i
            flat[i] = req.pages[p_abs // psz] * psz + p_abs % psz
        pool.set_arrays(*dm.scatter_kv(pool.k, pool.v, k_win, v_win,
                                       jnp.asarray(flat)))
        ms = (time.perf_counter() - t0) * 1e3
        if psp is not None:
            psp.attrs["prefill_ms"] = round(ms, 3)
        _tracing.finish(psp)
        for _, sp in peers:
            _tracing.finish(sp)
        self._observe_ms("serve_prefill_ms", None, ms=ms)
        if req.is_resume:
            # the bounded extra prefill a preemption/failover costs
            self._badput_ms(ms, "resume")
        if self.prefix_cache:
            pool.register_prefix(prefix, req.pages[:len(prefix) // psz])
        self.counters["prefill_positions"] += n_valid
        self.counters["cached_positions"] += req.reuse
        self._tok_counter("prefill").inc(n_valid)
        req.pos = len(prefix)
        self._emit(req, int(tok), logits_row=logits)

    def _step_paged(self, active: List[GenRequest]) -> None:
        import jax.numpy as jnp

        pool, psz, b = self.pool, self.page_size, self.max_slots
        tokens = np.zeros(b, np.int32)
        positions = np.zeros(b, np.int32)
        write_flat = np.zeros(b, np.int32)
        table = np.zeros((b, self.maxp), np.int32)
        for r in active:
            pid = r.pages[r.pos // psz]
            # COW safety: never write a shared/cached page in place
            new_pid, needs_copy = pool.ensure_private(pid)
            if needs_copy:
                pool.set_arrays(*dm.copy_page(
                    pool.k, pool.v, jnp.int32(pid), jnp.int32(new_pid),
                    page_size=psz))
                r.pages[r.pos // psz] = new_pid
                pid = new_pid
            tokens[r.slot] = r.cur_token
            positions[r.slot] = r.pos
            write_flat[r.slot] = pid * psz + r.pos % psz
            table[r.slot, :len(r.pages)] = r.pages
        t0 = time.perf_counter()
        logits, nxt, k, v = dm.decode_step(
            self.model.params, pool.k, pool.v, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(table),
            jnp.asarray(write_flat), page_size=psz,
            n_heads=self.model.cfg.n_heads)
        pool.set_arrays(k, v)
        nxt = np.asarray(nxt)
        logits_np = (np.asarray(logits)
                     if any(r.temperature for r in active) else None)
        self._observe_ms("serve_decode_step_ms", t0)
        self.counters["decode_steps"] += 1
        self.counters["decode_positions"] += len(active)
        self._tok_counter("decode").inc(len(active))
        for r in active:
            r.pos += 1
            self._emit(r, int(nxt[r.slot]),
                       logits_row=(None if logits_np is None
                                   else logits_np[r.slot]))

    # -- recompute baseline (PADDLE_SERVE_KV_CACHE=0) --------------------

    def _admit_recompute(self, req: GenRequest, slot: int) -> None:
        # resume prefix rides the dense buffer too: delivered tokens
        # re-enter as context, the next decode step emits token
        # len(req.tokens) — same replay contract as the paged path
        seq = req.prompt + req.tokens
        req.rc_tokens = np.zeros(self.max_seq, np.int32)
        req.rc_tokens[:len(seq)] = seq
        req.rc_len = len(seq)

    def _step_recompute(self, active: List[GenRequest]) -> None:
        import jax.numpy as jnp

        b = self.max_slots
        tokens = np.zeros((b, self.max_seq), np.int32)
        lengths = np.ones(b, np.int32)
        for r in active:
            tokens[r.slot] = r.rc_tokens
            lengths[r.slot] = r.rc_len
        t0 = time.perf_counter()
        logits, nxt = dm.recompute_step(
            self.model.params, jnp.asarray(tokens),
            jnp.asarray(lengths), n_heads=self.model.cfg.n_heads)
        nxt = np.asarray(nxt)
        logits_np = (np.asarray(logits)
                     if any(r.temperature for r in active) else None)
        self._observe_ms("serve_decode_step_ms", t0)
        self.counters["decode_steps"] += 1
        # the whole live prefix was re-run for ONE new token per slot —
        # this counter is the measured O(n^2) the paged path removes
        self.counters["recompute_positions"] += int(
            sum(r.rc_len for r in active))
        self._tok_counter("decode").inc(len(active))
        for r in active:
            tok = self._choose_token(
                r, int(nxt[r.slot]),
                None if logits_np is None else logits_np[r.slot])
            if r.rc_len < self.max_seq:
                r.rc_tokens[r.rc_len] = tok
            r.rc_len += 1
            self._emit(r, tok)

    # -- shared loop pieces ---------------------------------------------

    def _step(self) -> None:
        active = [r for r in self._slots if r is not None]
        if not active:
            return
        # one batched step = one span PER active slot, all sharing the
        # same `step` index. Wall time is charged pro-rata (`charged_ms`
        # = step wall / batch) so co-batching interference is
        # attributable: a victim of a peer's stall carries the stalled
        # step's index and its full `step_ms`. Spans open BEFORE the
        # chaos sites so injected stalls land inside them.
        step_idx = self.counters["decode_steps"]
        spans = [(r, self._req_span(
            r, "decode_step",
            attrs={"step": step_idx, "batch": len(active),
                   "slot": r.slot, "pos": r.pos}))
            for r in active]
        t_wall = time.perf_counter()
        # deterministic chaos sites: `stall:gen_decode_step:N:MS` delays
        # and `crash:gen_decode_step:N` kills this replica mid-decode —
        # the chaos drill's proof that in-flight generations survive a
        # replica death at the worst possible moment
        _faults.stall_point("gen_decode_step")
        _faults.crash_point("gen_decode_step")
        try:
            if self.pool is not None:
                self._step_paged(active)
            else:
                self._step_recompute(active)
        finally:
            ms = (time.perf_counter() - t_wall) * 1e3
            charged = ms / len(active)
            for r, sp in spans:
                if sp is None:
                    continue
                sp.attrs["step_ms"] = round(ms, 3)
                sp.attrs["charged_ms"] = round(charged, 3)
                _tracing.finish(sp)
        for i, r in enumerate(self._slots):
            if r is not None and r.event.is_set():
                self._slots[i] = None
        if self.pool is not None:
            self.pool.publish_gauges()

    def _choose_token(self, req: GenRequest, argmax_tok: int,
                      logits_row) -> int:
        """THE sampling choke point: greedy requests keep the device
        argmax untouched (bit-identical to r21); sampled requests draw
        from the same logits with the (seed, index) counter PRNG."""
        if not req.temperature or logits_row is None:
            return argmax_tok
        return _sample_token(logits_row, req.temperature, req.top_k,
                             req.seed, len(req.tokens),
                             top_p=req.top_p)

    def _emit(self, req: GenRequest, tok: int, logits_row=None) -> None:
        """Append one generated token; retire on eos/max_new/capacity."""
        tok = self._choose_token(req, tok, logits_row)
        now = time.monotonic()
        tid = req.span.trace_id if req.span is not None else None
        if req.t_first_token is None:
            req.t_first_token = now
            self._reg.histogram(
                "serve_ttft_ms", buckets=_SERVE_BUCKETS,
                help="time to first token (admission-backdated across "
                     "failover resumes)",
            ).observe((now - req.t_admit) * 1e3, trace_id=tid)
        elif req.t_last_token is not None:
            self._reg.histogram(
                "serve_tpot_ms", buckets=_SERVE_BUCKETS,
                help="inter-token latency (time per output token)",
            ).observe((now - req.t_last_token) * 1e3, trace_id=tid)
        req.t_last_token = now
        req.tokens.append(tok)
        self.counters["tokens_out"] += 1
        done = (len(req.tokens) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id))
        total = len(req.prompt) + len(req.tokens)
        if not done and total >= self.max_seq:
            done = True  # context capacity reached
        if done:
            self._finish(req, outcome="served")
        else:
            req.cur_token = tok

    def _finish(self, req: GenRequest,
                error: Optional[BaseException] = None,
                outcome: str = "served") -> None:
        if req.event.is_set():
            return
        if self.pool is not None and req.pages:
            self.pool.free(req.pages)
            req.pages = []
        req.error = error
        req.weight_epoch = self.weight_epoch
        self._count(outcome)
        if outcome == "deadline_exceeded":
            self._badput(req, "deadline")
        self._observe_ms("serve_gen_request_ms",
                         None, ms=(time.monotonic() - req.t_admit) * 1e3)
        self._retire_trace(
            req, outcome,
            detail=(f"{error}" if error is not None else None))
        req.event.set()
        with self._cond:
            self._cond.notify_all()

    # -- client side -----------------------------------------------------

    def result(self, req: GenRequest,
               timeout: Optional[float] = None) -> dict:
        grace = 30.0
        if timeout is None and req.deadline_t is not None:
            timeout = max(0.0, req.deadline_t - time.monotonic()) + grace
        if not req.event.wait(timeout):
            raise DeadlineExceeded(
                "DeadlineExceeded: generation did not complete in time")
        if req.error is not None:
            raise req.error
        return {
            "tokens": list(req.tokens),
            "weight_epoch": req.weight_epoch,
            "ttft_ms": (None if req.t_first_token is None else round(
                (req.t_first_token - req.t_admit) * 1e3, 3)),
            "resumed_from": req.resumed_from,
        }

    # -- lifecycle / observability ---------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = time.monotonic() + timeout
        with self._cond:
            while (self._q or self._rq
                   or any(s is not None for s in self._slots)) \
                    and time.monotonic() < deadline:
                self._cond.wait(0.1)
            return not self._q and not self._rq and not any(
                s is not None for s in self._slots)

    def stop(self) -> None:
        self.drain(timeout=5.0)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)

    def stats(self) -> dict:
        c = dict(self.counters)
        dt = max(1e-9, time.monotonic() - self._t_start)
        out = {
            "mode": "paged" if self.pool is not None else "recompute",
            "max_slots": self.max_slots,
            "active_slots": sum(1 for s in self._slots if s is not None),
            "queue_depth": len(self._q),
            "draining": self._draining,
            "weight_epoch": self.weight_epoch,
            "tokens_total": c["tokens_out"],
            "tokens_per_s": round(c["tokens_out"] / dt, 3),
            "decode_steps": c["decode_steps"],
            "prefill_positions_total": c["prefill_positions"],
            "cached_positions_total": c["cached_positions"],
            "decode_positions_total": c["decode_positions"],
            "recompute_positions_total": c["recompute_positions"],
            "served_total": c["served"],
            "shed_total": c["shed"],
            "deadline_exceeded_total": c["deadline_exceeded"],
            "evicted_total": c["evicted"],
            "preempted_total": c["preempted"],
            "resumed_total": c["resumed"],
            "preempt_positions_total": c["preempt_positions"],
            "resume_positions_total": c["resume_positions"],
            "resume_queue_depth": len(self._rq),
            "resume_enabled": self.resume_on,
            "step_ewma_ms": (None if self._step_ewma_s is None
                             else round(self._step_ewma_s * 1e3, 3)),
        }
        # SLO quantiles (ISSUE 19): bucket-boundary estimates from the
        # first-class histograms. servetop renders dashes when a replica
        # predates these keys.
        for hname, pfx in (("serve_ttft_ms", "ttft"),
                           ("serve_tpot_ms", "tpot"),
                           ("serve_queue_wait_ms", "queue_wait")):
            hist = self._reg.histogram(hname, buckets=_SERVE_BUCKETS)
            out[f"{pfx}_p50_ms"] = round(hist.quantile(0.5), 3)
            out[f"{pfx}_p99_ms"] = round(hist.quantile(0.99), 3)
        if self.pool is not None:
            out["kv_pool"] = self.pool.stats()
        return out

    def servez(self) -> dict:
        """debugz /servez payload: active slots, queued requests, recent
        completions slowest-first. Works tracing-on or off (trace ids
        are null when untraced)."""
        now = time.monotonic()

        def _row(r: GenRequest, phase: str, slot=None) -> dict:
            return {
                "slot": slot,
                "trace": (r.span.trace_id if r.span is not None
                          else None),
                "phase": phase,
                "age_s": round(now - r.t_admit, 3),
                "prompt_len": len(r.prompt),
                "tokens": len(r.tokens),
                "max_new_tokens": r.max_new_tokens,
                "pages": len(r.pages),
                "pos": r.pos,
                "preempts": r.preempts,
                "resumed_from": r.resumed_from,
                "deadline_in_s": (None if r.deadline_t is None
                                  else round(r.deadline_t - now, 3)),
            }

        active = [_row(r, "decode", slot=i)
                  for i, r in enumerate(self._slots) if r is not None]
        with self._cond:
            queued = [_row(r, "queued") for r in self._q]
            resumes = [_row(r, "queued_resume") for r in self._rq]
        recent = sorted(self._recent,
                        key=lambda rec: -(rec.get("total_ms") or 0.0))
        return {
            "mode": "paged" if self.pool is not None else "recompute",
            "max_slots": self.max_slots,
            "draining": self._draining,
            "weight_epoch": self.weight_epoch,
            "active": active,
            "queued": queued,
            "resume_queue": resumes,
            "recent_slowest": recent[:32],
        }

    # -- small helpers ---------------------------------------------------

    def _req_span(self, req: GenRequest, name: str,
                  attrs: Optional[dict] = None):
        """Child span under the request's umbrella span (None when the
        request is untraced — every consumer is None-safe)."""
        if req.span is None:
            return None
        return _tracing.begin(name, parent=req.span, attrs=attrs)

    def _event_span(self, req: GenRequest, name: str,
                    attrs: Optional[dict] = None) -> None:
        """Zero-duration lifecycle marker (preempt/resume/evict/
        weight_fence) on the request's trace."""
        _tracing.finish(self._req_span(req, name, attrs=attrs))

    # outcome -> flight-recorder dump reason (the r9 post-mortem path)
    _DUMP_REASONS = {"shed": "serve_shed",
                     "deadline_exceeded": "serve_deadline"}

    def _retire_trace(self, req: GenRequest, outcome: str,
                      detail: Optional[str] = None) -> None:
        """Close the request's open spans, append the /servez completion
        record, note the per-request flight record, and trigger a flight
        dump on bad outcomes."""
        now = time.monotonic()
        if req.queue_span is not None:
            # retired straight out of the queue (queue deadline / epoch
            # refusal): the whole residency was queue wait
            req.queue_ms += (now - req.t_enq) * 1e3
            _tracing.finish(req.queue_span,
                            status=(None if outcome == "served"
                                    else outcome))
            req.queue_span = None
        rec = {
            "trace": req.span.trace_id if req.span is not None else None,
            "outcome": outcome,
            "prompt_len": len(req.prompt),
            "tokens": len(req.tokens),
            "queue_ms": round(req.queue_ms, 3),
            "ttft_ms": (None if req.t_first_token is None else round(
                (req.t_first_token - req.t_admit) * 1e3, 3)),
            "total_ms": round((now - req.t_admit) * 1e3, 3),
            "preempts": req.preempts,
            "resumed_from": req.resumed_from,
            "weight_epoch": req.weight_epoch,
            "ts": round(time.time(), 3),
        }
        if detail:
            rec["detail"] = detail
        self._recent.append(rec)
        _sink.emit({"kind": "serve_request", **rec})
        if req.span is not None:
            req.span.attrs.update(outcome=outcome,
                                  tokens=len(req.tokens),
                                  queue_ms=rec["queue_ms"],
                                  preempts=req.preempts)
            if detail:
                req.span.attrs["detail"] = detail
            _tracing.finish(req.span,
                            status=(None if outcome == "served"
                                    else outcome))
            req.span = None
            _tracing.note_request(rec)
        reason = self._DUMP_REASONS.get(outcome)
        if reason is None and outcome == "error" and detail \
                and "ResumedOnNewWeights" in detail:
            reason = "serve_epoch_refusal"
        if reason is not None:
            _tracing.flight_dump(reason)

    def _count(self, outcome: str) -> None:
        if outcome in self.counters:
            self.counters[outcome] += 1
        self._reg.counter("serve_gen_requests_total",
                          outcome=outcome).inc()

    def _tok_counter(self, phase: str):
        return self._reg.counter(
            "serve_tokens_total",
            help="generated/prefilled token positions by phase",
            phase=phase)

    def _gauge(self, name: str):
        return self._reg.gauge(name)

    def _observe_ms(self, name: str, t0: Optional[float],
                    ms: Optional[float] = None) -> None:
        if ms is None:
            ms = (time.perf_counter() - t0) * 1e3
        if name == "serve_decode_step_ms":
            s = ms / 1e3
            self._step_ewma_s = (s if self._step_ewma_s is None
                                 else 0.8 * self._step_ewma_s + 0.2 * s)
        self._reg.histogram(name, buckets=_SERVE_BUCKETS).observe(ms)

    def _badput(self, req: GenRequest, cause: str) -> None:
        self._badput_ms((time.monotonic() - req.t_admit) * 1e3, cause)

    def _badput_ms(self, ms: float, cause: str) -> None:
        try:
            from ..telemetry import goodput as _goodput

            _goodput.note_serving_badput(ms, cause=cause)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass
