"""Serving predictor over a FrozenModel.

Compilation goes through the Executor's compile-program cache with THE
training cache key (fluid/executor.py _cache_key: program serial +
version + feed signature + fetch names + flags): every Predictor built
from the same FrozenModel shares one module-level Executor, so the
second instantiation — and every replica thread — hits the cached XLA
executable instead of re-compiling (the reference AnalysisPredictor
clone contract, without the scope aliasing).

Weight adoption (`adopt_weights`) swaps parameter VALUES in the
predictor's scope between runs — the compiled function reloads its
non-donated inputs from the scope every call, so the next run serves
the new weights with zero recompilation. The epoch fence around it
lives in server.py's micro-batch scheduler; a bare Predictor is
single-threaded by contract.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from .. import fluid
from ..fluid.executor import Scope
from .freeze import FrozenModel

# one process-wide executor => one compile cache across predictors and
# replica worker threads (keyed like training's, so distinct programs /
# shapes / flag states never collide)
_shared_executor: Optional[fluid.Executor] = None
_shared_lock = threading.Lock()


def shared_executor() -> fluid.Executor:
    global _shared_executor
    with _shared_lock:
        if _shared_executor is None:
            _shared_executor = fluid.Executor()
        return _shared_executor


class Predictor:
    """Run a FrozenModel: feed dict in, fetch arrays out."""

    def __init__(self, frozen: FrozenModel,
                 executor: Optional[fluid.Executor] = None,
                 share_weights: bool = True):
        self.frozen = frozen
        self._exe = executor or shared_executor()
        if share_weights:
            # replicas of one model share the weight arrays (immutable);
            # adopt_weights REPLACES entries, so sharing is never aliasing
            self._scope = frozen.scope
        else:
            self._scope = Scope()
            for n in frozen.param_names:
                self._scope.set_var(n, frozen.scope.find_var(n))
        self.weight_epoch = 0

    @property
    def feed_names(self) -> List[str]:
        return list(self.frozen.feed_names)

    @property
    def fetch_names(self) -> List[str]:
        return list(self.frozen.fetch_names)

    def run(self, feed: Dict[str, np.ndarray],
            return_numpy: bool = True) -> List[np.ndarray]:
        missing = [n for n in self.frozen.feed_names if n not in feed]
        if missing:
            raise ValueError(f"predictor feed missing inputs: {missing}")
        extra = [n for n in feed if n not in self.frozen.feed_names]
        if extra:
            raise ValueError(f"predictor feed has unknown inputs: {extra}")
        with fluid.scope_guard(self._scope):
            return self._exe.run(
                self.frozen.program, feed=dict(feed),
                fetch_list=self.frozen.fetch_names,
                return_numpy=return_numpy)

    def adopt_weights(self, weights: Dict[str, np.ndarray],
                      epoch: Optional[int] = None) -> int:
        """Install fresh parameter values (a weight_sync delivery).
        Unknown names are rejected loudly — a manifest drift between
        trainer and replica must never half-apply. Returns the new
        weight epoch. NOT thread-safe against a concurrent run(); the
        serving scheduler calls it only between micro-batches."""
        unknown = [n for n in weights if n not in self.frozen.param_names]
        if unknown:
            raise KeyError(
                f"adopt_weights: {len(unknown)} names not in the frozen "
                f"model: {unknown[:5]}")
        for n, v in weights.items():
            cur = self._scope.find_var(n)
            if cur is not None and np.shape(cur) != np.shape(v):
                raise ValueError(
                    f"adopt_weights: shape mismatch for {n!r}: "
                    f"{np.shape(cur)} vs {np.shape(v)}")
            self._scope.set_var(n, np.ascontiguousarray(v))
        self.weight_epoch = (self.weight_epoch + 1 if epoch is None
                             else int(epoch))
        return self.weight_epoch
