"""Inference stack: Config + Predictor + zero-copy tensor handles.

Parity surface: reference paddle/fluid/inference/api/
(AnalysisPredictor: analysis_predictor.h:82, AnalysisConfig:
analysis_config.cc, ZeroCopyTensor) and paddle_infer's
create_predictor / get_input_handle surface.

TPU-native design: "analysis passes" (the reference's IR pass manager,
TensorRT subgraph capture, MKLDNN placement) are subsumed by XLA — the
loaded program compiles as one cached XLA computation on first run.
Zero-copy semantics: input handles hold device arrays; share_external_
data accepts an existing jax.Array without a host round trip; outputs
stay on device until copy_to_cpu.

The C API (reference inference/capi/) is the native shim in
native/capi.cc: a C library embedding this module via the CPython C API.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import fluid


class Config:
    """AnalysisConfig parity."""

    def __init__(self, model_dir: Optional[str] = None,
                 prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._model_dir = model_dir
        self._prog_file = prog_file
        self._params_file = params_file
        self._memory_optim = True
        self._glog_info = False

    def set_model(self, model_dir, params_file=None):
        self._model_dir = model_dir
        self._params_file = params_file

    def model_dir(self):
        return self._model_dir

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag  # XLA buffer liveness; accepted

    def disable_glog_info(self):
        self._glog_info = False

    def switch_ir_optim(self, flag=True):
        pass  # XLA owns graph optimization

    def switch_use_feed_fetch_ops(self, flag):
        pass  # feed/fetch glue is host-side here

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        pass  # device selection is JAX's; accepted for parity

    def disable_gpu(self):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT subgraphs are a CUDA-stack concept; XLA compiles the "
            "whole program natively on TPU — no engine delegation exists"
        )


class Tensor:
    """Zero-copy tensor handle (reference ZeroCopyTensor)."""

    def __init__(self, predictor: "Predictor", name: str, is_input: bool):
        self._p = predictor
        self.name = name
        self._is_input = is_input

    # -- input side ------------------------------------------------------
    def copy_from_cpu(self, arr):
        if not self._is_input:
            raise RuntimeError(f"{self.name!r} is an output handle")
        import jax

        self._p._feed[self.name] = jax.device_put(np.ascontiguousarray(arr))

    def share_external_data(self, arr):
        """Adopt an existing (device) array without copying."""
        if not self._is_input:
            raise RuntimeError(f"{self.name!r} is an output handle")
        self._p._feed[self.name] = arr

    def reshape(self, shape):
        pass  # shapes come from the array in copy_from_cpu

    # -- output side -----------------------------------------------------
    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            val = self._p._feed.get(self.name)
        else:
            val = self._p._outputs.get(self.name)
        if val is None:
            raise RuntimeError(f"tensor {self.name!r} has no value yet")
        return np.asarray(val)

    def shape(self):
        return list(np.shape(self.copy_to_cpu()))


class Predictor:
    """AnalysisPredictor parity: load once, run many; first run compiles
    the whole pruned program via the Executor's XLA path."""

    def __init__(self, config: Config, _clone_from: Optional["Predictor"] = None):
        self._config = config
        self._exe = fluid.Executor()
        if _clone_from is not None:
            # share the scope (weights) without re-reading from disk —
            # the reference clone's multi-instance scope sharing
            self._scope = _clone_from._scope
            self._program = _clone_from._program
            self._feed_names = list(_clone_from._feed_names)
            self._fetch_vars = _clone_from._fetch_vars
            self._fetch_names = list(_clone_from._fetch_names)
        else:
            import os

            dirname = config.model_dir()
            model_filename = None
            if config._prog_file:
                if dirname is None:
                    dirname = os.path.dirname(config._prog_file) or "."
                model_filename = os.path.basename(config._prog_file)
            if dirname is None:
                raise ValueError(
                    "Config needs model_dir or prog_file to locate the model"
                )
            self._scope = fluid.executor.Scope()
            with fluid.scope_guard(self._scope):
                prog, feeds, fetches = fluid.io.load_inference_model(
                    dirname, self._exe, model_filename=model_filename,
                    params_filename=config._params_file,
                )
            self._program = prog
            self._feed_names = list(feeds)
            self._fetch_vars = fetches
            self._fetch_names = [
                v.name if hasattr(v, "name") else str(v) for v in fetches
            ]
        self._feed: Dict[str, object] = {}
        self._outputs: Dict[str, object] = {}

    # -- reference surface ----------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def get_input_handle(self, name) -> Tensor:
        if name not in self._feed_names:
            raise KeyError(f"unknown input {name!r}")
        return Tensor(self, name, is_input=True)

    def get_output_handle(self, name) -> Tensor:
        if name not in self._fetch_names:
            raise KeyError(f"unknown output {name!r}")
        return Tensor(self, name, is_input=False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """paddle_infer style: either set inputs via handles then run(),
        or pass a positional list (old PaddlePredictor::Run)."""
        if inputs is not None:
            if len(inputs) != len(self._feed_names):
                raise ValueError(
                    f"run() got {len(inputs)} inputs, model has "
                    f"{len(self._feed_names)}: {self._feed_names}"
                )
            for n, a in zip(self._feed_names, inputs):
                self._feed[n] = np.ascontiguousarray(a)
        missing = [n for n in self._feed_names if n not in self._feed]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        with fluid.scope_guard(self._scope):
            outs = self._exe.run(
                self._program, feed=dict(self._feed),
                fetch_list=self._fetch_names, return_numpy=False,
            )
        self._outputs = dict(zip(self._fetch_names, outs))
        return [np.asarray(o) for o in outs] if inputs is not None else True

    def clone(self) -> "Predictor":
        """Share weights (scope), separate feed/fetch state — the
        reference's multi-instance scope sharing (no disk reload)."""
        return Predictor(self._config, _clone_from=self)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


# legacy fluid.core-style aliases
AnalysisConfig = Config
AnalysisPredictor = Predictor


def create_paddle_predictor(config: Config) -> Predictor:
    return Predictor(config)


# ---------------------------------------------------------------------------
# serving engine (ISSUE 14): program freezing + production serving over
# the hardened PS RPC plane. Submodules import lazily inside functions
# where they need jax; these names are the public surface.
# ---------------------------------------------------------------------------
from .freeze import FrozenModel, freeze_program, load_frozen  # noqa: F401,E402
from .predictor import Predictor as ServingPredictor  # noqa: F401,E402
from .predictor import shared_executor  # noqa: F401,E402
from . import weight_sync  # noqa: F401,E402


def __getattr__(name):
    # server/client pull in the distributed transport: lazy so `import
    # paddle_tpu.inference` stays cheap for the file-based Predictor
    if name in ("InferenceServer", "MicroBatcher", "Overloaded",
                "DeadlineExceeded", "serve"):
        from . import server as _server

        return getattr(_server, name)
    if name in ("InferenceClient", "InferResult", "OverloadedError",
                "DeadlineExceededError"):
        from . import client as _client

        return getattr(_client, name)
    if name in ("GenerationEngine", "GenRequest", "kv_cache_enabled"):
        from . import engine as _engine

        return getattr(_engine, name)
    if name == "PagedKVPool":
        from .kv_cache import PagedKVPool

        return PagedKVPool
    if name in ("TinyDecoderLM", "DecoderConfig"):
        from . import decode_model as _dm

        return getattr(_dm, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
