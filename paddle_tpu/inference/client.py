"""Inference client: replica-set failover + hedged requests over the
hardened PS transport.

`InferenceClient` talks to N serving replicas through `_Conn` (retries
with backoff, per-RPC deadlines, fault injection, trace spans — the
exact client the training data plane hardened). On top it adds:

  failover  — a replica whose deadline-capped retry budget is exhausted
              is marked down and the BEST live replica is promoted (the
              RemoteTable `_failover` shape: probe every candidate's
              `health`, rank by (not draining, weight_epoch, chain
              order)); `infer` is idempotent, so the request replays on
              the new replica — zero accepted requests lost. A rejoin
              probe re-enables the dead endpoint once it answers again.
  hedging   — after the infer latency histogram's quantile
              (PADDLE_SERVE_HEDGE_QUANTILE, default p95) a hedge is
              raced against another replica; first response wins — the
              slow-tail drill's contract.
  deadlines — `infer(deadline_ms=...)` rides the wire so the server's
              admission control sheds what it cannot finish in time;
              the client maps the explicit refusals onto typed errors
              (OverloadedError / DeadlineExceededError) instead of
              retrying a reply the server already made deliberately.
  resume    — `generate`/`generate_stream` survive a mid-request
              replica death (r22): every generation carries a client-
              stamped request id, so a retry against the SAME replica
              reattaches to the in-flight stream (server-side dedup,
              exactly-once) and a retry against a PROMOTED replica
              re-issues as a resume — original prompt plus the tokens
              already delivered become the new prefill prefix, and the
              elapsed wall time rides along so failover never resets
              SLO accounting. Greedy decode is deterministic, so within
              one weight epoch the resumed tail is bit-identical to the
              uninterrupted run; a cross-epoch resume is REFUSED by the
              server and surfaces as ResumedOnNewWeightsError with the
              partial tokens attached.
"""
from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..telemetry import get_registry
from ..telemetry import tracing as _tracing

_REG = get_registry()

HEDGE_QUANTILE = float(os.environ.get("PADDLE_SERVE_HEDGE_QUANTILE",
                                      0.95) or 0)
HEDGE_MIN_SAMPLES = int(os.environ.get("PADDLE_SERVE_HEDGE_MIN_SAMPLES",
                                       16))
CLIENT_DEADLINE = float(os.environ.get("PADDLE_SERVE_CLIENT_DEADLINE_SECS",
                                       10.0))
REJOIN_SECS = float(os.environ.get("PADDLE_SERVE_REJOIN_SECS", 60.0))


class OverloadedError(RuntimeError):
    """The server REFUSED admission (queue full / draining / projected
    wait past the deadline). Deliberate load shedding — back off or try
    a less loaded replica; blind retry against the same one is exactly
    the retry storm admission control exists to prevent."""


class DeadlineExceededError(RuntimeError):
    """The request's deadline expired before the server could serve it."""


class ResumedOnNewWeightsError(RuntimeError):
    """A generation resume landed on a replica serving a different
    weight epoch than the one that produced the already-delivered
    tokens. Splicing the tail on silently would hand the caller a
    sequence no single model ever produced, so the server refuses and
    the client surfaces the refusal typed. `.tokens` carries the
    partial output delivered before the cut — the caller decides
    whether to keep it or regenerate from scratch on the new weights."""

    def __init__(self, msg: str, tokens: Optional[List[int]] = None):
        super().__init__(msg)
        self.tokens: List[int] = list(tokens or [])


class InferResult:
    __slots__ = ("outputs", "fetch_names", "weight_epoch", "replica",
                 "queue_ms")

    def __init__(self, reply: dict, replica: str):
        self.outputs = [np.asarray(o) for o in reply["outputs"]]
        self.fetch_names = list(reply.get("fetch_names") or [])
        self.weight_epoch = int(reply.get("weight_epoch", 0))
        self.queue_ms = float(reply.get("queue_ms", 0.0))
        self.replica = replica

    def __getitem__(self, i):
        return self.outputs[i]


def _map_app_error(e: RuntimeError) -> BaseException:
    msg = str(e)
    if "ResumedOnNewWeights" in msg:
        return ResumedOnNewWeightsError(msg)
    if "Overloaded" in msg:
        return OverloadedError(msg)
    if "DeadlineExceeded" in msg:
        return DeadlineExceededError(msg)
    return e


class InferenceClient:
    """Failover + hedging client over a serving replica set."""

    def __init__(self, endpoints: Sequence[str],
                 deadline_secs: Optional[float] = None,
                 hedge_quantile: Optional[float] = None,
                 hedge_min_samples: Optional[int] = None):
        from ..distributed.ps_server import _Conn

        if not endpoints:
            raise ValueError("InferenceClient needs at least one endpoint")
        self.endpoints = [str(e) for e in endpoints]
        self._deadline = (CLIENT_DEADLINE if deadline_secs is None
                          else float(deadline_secs))
        # io_timeout past the deadline: a request parked in the server's
        # batch queue is progress, not a dead peer
        self._conns = [_Conn(e, deadline=self._deadline,
                             io_timeout=self._deadline + 30.0)
                       for e in self.endpoints]
        self._primary = 0
        self._down: Dict[int, float] = {}  # idx -> downed-at monotonic
        self._lock = threading.RLock()
        self._closed = threading.Event()  # stops rejoin probe threads
        self._hedge_q = (HEDGE_QUANTILE if hedge_quantile is None
                         else float(hedge_quantile))
        self._hedge_min = (HEDGE_MIN_SAMPLES if hedge_min_samples is None
                           else int(hedge_min_samples))
        self._hedge_pool = None
        if len(self.endpoints) > 1 and self._hedge_q > 0:
            from concurrent.futures import ThreadPoolExecutor

            self._hedge_pool = ThreadPoolExecutor(
                max_workers=max(4, 2 * len(self.endpoints)))

    # -- routing ---------------------------------------------------------
    def _probe(self, j: int) -> Optional[dict]:
        from ..distributed.ps_server import _Conn

        probe = _Conn(self.endpoints[j], deadline=2.0, io_timeout=10.0)
        try:
            return probe.call("health")
        except Exception:  # noqa: BLE001 — a dead candidate scores None
            return None
        finally:
            probe.close()

    def _failover(self, dead_j: int) -> None:
        """Promote the best live replica: serving (not draining) beats
        draining, then highest weight_epoch (freshest model), then
        list order. Mirrors RemoteTable._failover's promote-best-live."""
        with self._lock:
            if self._primary != dead_j:
                return  # another thread already moved on
            self._down[dead_j] = time.monotonic()
            best = None
            for j in range(len(self.endpoints)):
                if j == dead_j:
                    continue
                h = self._probe(j)
                if h is None:
                    continue
                rank = (0 if h.get("draining") else 1,
                        int(h.get("weight_epoch", 0)), -j)
                if best is None or rank > best[0]:
                    best = (rank, j)
            if best is None:
                raise ConnectionError(
                    f"all {len(self.endpoints)} serving replicas are "
                    f"unreachable (last dead: "
                    f"{self.endpoints[dead_j]})")
            self._primary = best[1]
            _REG.counter("serve_client_failovers_total").inc()
            import sys

            print(f"[serve_client] replica {self.endpoints[dead_j]} "
                  f"unreachable; failing over to "
                  f"{self.endpoints[best[1]]}", file=sys.stderr,
                  flush=True)
        self._schedule_rejoin(dead_j)

    def _schedule_rejoin(self, dead_j: int) -> None:
        def loop():
            deadline = time.monotonic() + REJOIN_SECS
            while time.monotonic() < deadline \
                    and not self._closed.is_set():
                if self._closed.wait(0.5):
                    return  # client closed: stop probing immediately
                if self._probe(dead_j) is not None:
                    with self._lock:
                        self._down.pop(dead_j, None)
                    _REG.counter("serve_client_rejoins_total").inc()
                    return

        threading.Thread(target=loop, daemon=True,
                         name=f"serve-rejoin-{dead_j}").start()

    def _call(self, method: str, hops: int = 0, **kwargs):
        with self._lock:
            j = self._primary
        try:
            return self._conns[j].call(method, **kwargs)
        except (OverloadedError, DeadlineExceededError):
            raise
        except ConnectionError:
            if hops >= len(self.endpoints):
                raise
            self._failover(j)
            return self._call(method, hops=hops + 1, **kwargs)
        except RuntimeError as e:
            raise _map_app_error(e) from None

    # -- API -------------------------------------------------------------
    def infer(self, feed: Dict[str, np.ndarray],
              deadline_ms: Optional[float] = None) -> InferResult:
        if deadline_ms is not None:
            kwargs = {"feed": feed, "deadline_ms": float(deadline_ms)}
        else:
            kwargs = {"feed": feed}
        t0 = time.perf_counter()
        try:
            if self._hedge_pool is not None:
                reply, replica = self._hedged_infer(kwargs)
            else:
                reply = self._call("infer", **kwargs)
                with self._lock:  # read AFTER: a failover moved routing
                    replica = self.endpoints[self._primary]
            return InferResult(reply, replica)
        finally:
            _REG.histogram(
                "serve_client_infer_ms",
                help="caller-observed infer latency (failover + "
                     "hedging included)").observe(
                (time.perf_counter() - t0) * 1e3)

    def _hedged_infer(self, kwargs: dict):
        """Race the primary against a second replica once the observed
        latency quantile elapses (RemoteTable._hedged_call shape). The
        infer verb is idempotent — a duplicate execution costs device
        time, never correctness. Overloaded/DeadlineExceeded are
        DELIBERATE replies: the race only ends early on success or when
        both legs errored."""
        from concurrent import futures as _fut

        hist = _REG.histogram("ps_client_rpc_ms", verb="infer")
        with self._lock:
            j = self._primary
        if hist.count < self._hedge_min or len(self.endpoints) < 2:
            reply = self._call("infer", **kwargs)
            return reply, self.endpoints[j]
        delay_s = max(hist.quantile(self._hedge_q) / 1e3, 1e-3)
        fut = self._hedge_pool.submit(_tracing.bound(
            lambda: self._call("infer", **dict(kwargs))))
        try:
            return fut.result(timeout=delay_s), self.endpoints[j]
        except _fut.TimeoutError:
            pass
        except RuntimeError:
            raise
        _REG.counter("serve_client_hedges_issued_total").inc()
        with self._lock:
            hedge_j = next(
                (k for k in range(len(self.endpoints))
                 if k != self._primary and k not in self._down),
                (self._primary + 1) % len(self.endpoints))

        def _hedge_exec():
            with _tracing.span("hedge:infer",
                               attrs={"peer": self.endpoints[hedge_j]}):
                return self._conns[hedge_j].call("infer", **dict(kwargs))

        hedge = self._hedge_pool.submit(_tracing.bound(_hedge_exec))
        pending = {fut: self.endpoints[j], hedge: self.endpoints[hedge_j]}
        last_err = None
        while pending:
            done, _ = _fut.wait(set(pending),
                                return_when=_fut.FIRST_COMPLETED)
            for f in done:
                src = pending.pop(f)
                err = f.exception()
                if err is None:
                    if f is hedge:
                        _REG.counter(
                            "serve_client_hedges_won_total").inc()
                    return f.result(), src
                last_err = err
        if isinstance(last_err, RuntimeError):
            raise _map_app_error(last_err)
        raise last_err

    class GenerateResult:
        __slots__ = ("tokens", "weight_epoch", "ttft_ms", "replica",
                     "resumed_from")

        def __init__(self, reply: dict, replica: str):
            self.tokens = list(reply["tokens"])
            self.weight_epoch = int(reply.get("weight_epoch", 0))
            self.ttft_ms = reply.get("ttft_ms")
            self.replica = replica
            # >0: the run was spliced — this many leading tokens came
            # from a previous attempt (failover / preemption resume)
            self.resumed_from = int(reply.get("resumed_from", 0) or 0)

    @staticmethod
    def _gen_kwargs(prompt, max_new_tokens, deadline_ms, eos_id,
                    temperature, top_k, top_p, seed) -> dict:
        kwargs = {"prompt": [int(t) for t in prompt],
                  "max_new_tokens": int(max_new_tokens),
                  "request_id": uuid.uuid4().hex}
        if deadline_ms is not None:
            kwargs["deadline_ms"] = float(deadline_ms)
        if eos_id is not None:
            kwargs["eos_id"] = int(eos_id)
        if temperature is not None:
            kwargs["temperature"] = float(temperature)
            if top_k is not None:
                kwargs["top_k"] = int(top_k)
            if top_p is not None:
                kwargs["top_p"] = float(top_p)
            # Sampling without a caller seed: draw one HERE so a
            # failover resume replays the exact token sequence — the
            # seed must be fixed before the first attempt, not per
            # replica.
            kwargs["seed"] = (int.from_bytes(os.urandom(4), "little")
                              if seed is None else int(seed))
        elif seed is not None:
            kwargs["seed"] = int(seed)
        return kwargs

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 deadline_ms: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None,
                 seed: Optional[int] = None,
                 top_p: Optional[float] = None) -> "GenerateResult":
        """Blocking autoregressive generation on the primary replica.
        Generation is NOT hedged: a duplicate run would burn KV pages
        and decode slots on two replicas for one reply. Instead every
        call is stamped with a request id: a transport-level retry
        against the same replica reattaches to the in-flight request
        (server dedup — the model never runs twice), and a dead replica
        is failed over with the retry marker and elapsed time carried
        so the promoted replica charges the full request age against
        the deadline."""
        kwargs = self._gen_kwargs(prompt, max_new_tokens, deadline_ms,
                                  eos_id, temperature, top_k, top_p,
                                  seed)
        t0 = time.perf_counter()
        abs_deadline = (None if deadline_ms is None
                        else t0 + float(deadline_ms) / 1e3)
        hops = 0
        # root span for the WHOLE generation: the context rides every
        # attempt's RPC payload, so after a failover both replicas'
        # server+engine spans share this one trace_id
        root = _tracing.begin(
            "generate", kind="client",
            attrs={"prompt_len": len(kwargs["prompt"]),
                   "max_new_tokens": int(max_new_tokens),
                   "request_id": kwargs["request_id"]})
        ctx = (None if root is None
               else (root.trace_id, root.span_id))
        status = "error"
        try:
            while True:
                with self._lock:
                    j = self._primary
                try:
                    with _tracing.attach(ctx):
                        reply = self._conns[j].call("generate", **kwargs)
                except ConnectionError:
                    if hops >= len(self.endpoints):
                        raise
                    hops += 1
                    self._failover(j)
                    # re-issue as a marked retry: the promoted replica
                    # sees the original arrival age, not a fresh clock
                    kwargs["retry"] = True
                    kwargs["elapsed_ms"] = (time.perf_counter() - t0) * 1e3
                    if abs_deadline is not None:
                        kwargs["deadline_ms"] = max(
                            (abs_deadline - time.perf_counter()) * 1e3, 1.0)
                    continue
                except RuntimeError as e:
                    raise _map_app_error(e) from None
                with self._lock:
                    replica = self.endpoints[self._primary]
                status = None
                if root is not None:
                    root.attrs.update(replica=replica, failovers=hops)
                return self.GenerateResult(reply, replica)
        finally:
            _tracing.finish(root, status=status)
            _REG.histogram(
                "serve_client_generate_ms",
                help="caller-observed generation latency").observe(
                (time.perf_counter() - t0) * 1e3)

    def generate_stream(self, prompt: Sequence[int],
                        max_new_tokens: int = 16,
                        deadline_ms: Optional[float] = None,
                        eos_id: Optional[int] = None,
                        poll_s: float = 0.01,
                        temperature: Optional[float] = None,
                        top_k: Optional[int] = None,
                        seed: Optional[int] = None,
                        top_p: Optional[float] = None,
                        timings: Optional[dict] = None):
        """Incremental generation: yields lists of new tokens as the
        replica's decode loop produces them.  The PS transport is
        one-shot request/reply, so streaming is poll-based: `generate`
        with stream=True returns a stream id, `generate_poll` drains it.
        KV state is replica-local, so a mid-stream replica death cannot
        be retried blindly — instead the stream RESUMES (r22): the dead
        replica is failed over and the generation re-issued with the
        tokens already delivered as the new prefill prefix, the elapsed
        time carried for SLO accounting, and the weight epoch that
        produced the delivered tokens pinned via `expect_epoch`. Within
        one epoch the resumed tail is bit-identical (greedy decode is
        deterministic; sampling is counter-mode keyed on (seed, index));
        across an epoch boundary the server refuses and the caller gets
        ResumedOnNewWeightsError with the partial tokens attached.

        ``timings``: an optional dict the client fills IN PLACE with
        caller-observed SLO numbers — ``ttft_ms`` (call start to first
        token arrival), ``tpot_avg_ms`` (mean inter-token gap),
        ``token_ts_ms`` (per-token arrival offsets from call start; the
        tokens of one poll chunk share an arrival), ``tokens``. The
        server-observed ttft is measured at admission, so the delta is
        exactly network + poll-cadence skew — measurable, not guessed."""
        base = self._gen_kwargs(prompt, max_new_tokens, deadline_ms,
                                eos_id, temperature, top_k, top_p, seed)
        base["stream"] = True
        t0 = time.perf_counter()
        abs_deadline = (None if deadline_ms is None
                        else t0 + float(deadline_ms) / 1e3)
        delivered: List[int] = []
        last_epoch: Optional[int] = None
        hops = 0
        if timings is not None:
            timings.clear()
            timings.update(ttft_ms=None, tpot_avg_ms=None,
                           token_ts_ms=[], tokens=0)

        def _note_arrival(n_new: int) -> None:
            if timings is None or n_new <= 0:
                return
            at_ms = (time.perf_counter() - t0) * 1e3
            if timings["ttft_ms"] is None:
                timings["ttft_ms"] = round(at_ms, 3)
            timings["token_ts_ms"].extend([round(at_ms, 3)] * n_new)
            timings["tokens"] += n_new
            if timings["tokens"] > 1:
                timings["tpot_avg_ms"] = round(
                    (at_ms - timings["token_ts_ms"][0])
                    / (timings["tokens"] - 1), 3)

        root = _tracing.begin(
            "generate_stream", kind="client",
            attrs={"prompt_len": len(base["prompt"]),
                   "max_new_tokens": int(max_new_tokens),
                   "request_id": base["request_id"]})
        ctx = (None if root is None
               else (root.trace_id, root.span_id))
        status = "error"
        try:
            while True:  # one iteration per (re)attach
                with self._lock:
                    j = self._primary
                kwargs = dict(base)
                if hops:
                    kwargs["retry"] = True
                    kwargs["elapsed_ms"] = (time.perf_counter() - t0) * 1e3
                    if abs_deadline is not None:
                        kwargs["deadline_ms"] = max(
                            (abs_deadline - time.perf_counter()) * 1e3, 1.0)
                    if delivered:
                        kwargs["resume_tokens"] = list(delivered)
                        if last_epoch is not None:
                            kwargs["expect_epoch"] = int(last_epoch)
                try:
                    with _tracing.attach(ctx):
                        sid = self._conns[j].call("generate",
                                                  **kwargs)["stream_id"]
                    # dedup reattach and resume both pre-seed the stream
                    # with everything already delivered: skip past it
                    cursor = len(delivered)
                    while True:
                        with _tracing.attach(ctx):
                            snap = self._conns[j].call("generate_poll",
                                                       stream_id=sid,
                                                       cursor=cursor)
                        if snap["tokens"]:
                            chunk = list(snap["tokens"])
                            _note_arrival(len(chunk))
                            delivered.extend(chunk)
                            yield chunk
                        cursor = int(snap["cursor"])
                        last_epoch = int(snap.get("weight_epoch") or 0)
                        if snap["done"]:
                            if snap.get("error"):
                                err = _map_app_error(
                                    RuntimeError(snap["error"]))
                                if isinstance(err,
                                              ResumedOnNewWeightsError):
                                    err.tokens = list(delivered)
                                raise err
                            status = None
                            if root is not None:
                                root.attrs.update(
                                    failovers=hops,
                                    tokens=len(delivered))
                            return
                        time.sleep(poll_s)
                except ConnectionError:
                    if hops >= len(self.endpoints):
                        raise
                    hops += 1
                    self._failover(j)
                    if delivered:
                        _REG.counter(
                            "serve_client_stream_resumes_total").inc()
                    continue
                except (OverloadedError, DeadlineExceededError,
                        ResumedOnNewWeightsError):
                    raise
                except RuntimeError as e:
                    err = _map_app_error(e)
                    if isinstance(err, ResumedOnNewWeightsError):
                        err.tokens = list(delivered)
                    raise err from None
        finally:
            _tracing.finish(root, status=status)

    def model_info(self) -> dict:
        return self._call("model_info")

    def health(self, replica: Optional[int] = None) -> dict:
        if replica is not None:
            return self._conns[replica].call("health")
        return self._call("health")

    def stats(self, all_replicas: bool = False):
        if not all_replicas:
            return self._call("stats")
        out = []
        for j, c in enumerate(self._conns):
            try:
                out.append({"endpoint": self.endpoints[j],
                            **c.call("stats")})
            except Exception as e:  # noqa: BLE001 — dead replica row
                out.append({"endpoint": self.endpoints[j],
                            "error": f"{type(e).__name__}: {e}"})
        return out

    def client_stats(self) -> dict:
        """This process's serve_client_* + ps_client_* registry slice."""
        snap = _REG.snapshot()
        return {k: v for k, v in snap.items()
                if k.startswith(("serve_client_", "ps_client_"))}

    def close(self) -> None:
        self._closed.set()  # rejoin probes must not outlive the client
        if self._hedge_pool is not None:
            self._hedge_pool.shutdown(wait=False)
        for c in self._conns:
            c.close()
