"""Inference client: replica-set failover + hedged requests over the
hardened PS transport.

`InferenceClient` talks to N serving replicas through `_Conn` (retries
with backoff, per-RPC deadlines, fault injection, trace spans — the
exact client the training data plane hardened). On top it adds:

  failover  — a replica whose deadline-capped retry budget is exhausted
              is marked down and the BEST live replica is promoted (the
              RemoteTable `_failover` shape: probe every candidate's
              `health`, rank by (not draining, weight_epoch, chain
              order)); `infer` is idempotent, so the request replays on
              the new replica — zero accepted requests lost. A rejoin
              probe re-enables the dead endpoint once it answers again.
  hedging   — after the infer latency histogram's quantile
              (PADDLE_SERVE_HEDGE_QUANTILE, default p95) a hedge is
              raced against another replica; first response wins — the
              slow-tail drill's contract.
  deadlines — `infer(deadline_ms=...)` rides the wire so the server's
              admission control sheds what it cannot finish in time;
              the client maps the explicit refusals onto typed errors
              (OverloadedError / DeadlineExceededError) instead of
              retrying a reply the server already made deliberately.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..telemetry import get_registry
from ..telemetry import tracing as _tracing

_REG = get_registry()

HEDGE_QUANTILE = float(os.environ.get("PADDLE_SERVE_HEDGE_QUANTILE",
                                      0.95) or 0)
HEDGE_MIN_SAMPLES = int(os.environ.get("PADDLE_SERVE_HEDGE_MIN_SAMPLES",
                                       16))
CLIENT_DEADLINE = float(os.environ.get("PADDLE_SERVE_CLIENT_DEADLINE_SECS",
                                       10.0))
REJOIN_SECS = float(os.environ.get("PADDLE_SERVE_REJOIN_SECS", 60.0))


class OverloadedError(RuntimeError):
    """The server REFUSED admission (queue full / draining / projected
    wait past the deadline). Deliberate load shedding — back off or try
    a less loaded replica; blind retry against the same one is exactly
    the retry storm admission control exists to prevent."""


class DeadlineExceededError(RuntimeError):
    """The request's deadline expired before the server could serve it."""


class InferResult:
    __slots__ = ("outputs", "fetch_names", "weight_epoch", "replica",
                 "queue_ms")

    def __init__(self, reply: dict, replica: str):
        self.outputs = [np.asarray(o) for o in reply["outputs"]]
        self.fetch_names = list(reply.get("fetch_names") or [])
        self.weight_epoch = int(reply.get("weight_epoch", 0))
        self.queue_ms = float(reply.get("queue_ms", 0.0))
        self.replica = replica

    def __getitem__(self, i):
        return self.outputs[i]


def _map_app_error(e: RuntimeError) -> BaseException:
    msg = str(e)
    if "Overloaded" in msg:
        return OverloadedError(msg)
    if "DeadlineExceeded" in msg:
        return DeadlineExceededError(msg)
    return e


class InferenceClient:
    """Failover + hedging client over a serving replica set."""

    def __init__(self, endpoints: Sequence[str],
                 deadline_secs: Optional[float] = None,
                 hedge_quantile: Optional[float] = None,
                 hedge_min_samples: Optional[int] = None):
        from ..distributed.ps_server import _Conn

        if not endpoints:
            raise ValueError("InferenceClient needs at least one endpoint")
        self.endpoints = [str(e) for e in endpoints]
        self._deadline = (CLIENT_DEADLINE if deadline_secs is None
                          else float(deadline_secs))
        # io_timeout past the deadline: a request parked in the server's
        # batch queue is progress, not a dead peer
        self._conns = [_Conn(e, deadline=self._deadline,
                             io_timeout=self._deadline + 30.0)
                       for e in self.endpoints]
        self._primary = 0
        self._down: Dict[int, float] = {}  # idx -> downed-at monotonic
        self._lock = threading.RLock()
        self._closed = threading.Event()  # stops rejoin probe threads
        self._hedge_q = (HEDGE_QUANTILE if hedge_quantile is None
                         else float(hedge_quantile))
        self._hedge_min = (HEDGE_MIN_SAMPLES if hedge_min_samples is None
                           else int(hedge_min_samples))
        self._hedge_pool = None
        if len(self.endpoints) > 1 and self._hedge_q > 0:
            from concurrent.futures import ThreadPoolExecutor

            self._hedge_pool = ThreadPoolExecutor(
                max_workers=max(4, 2 * len(self.endpoints)))

    # -- routing ---------------------------------------------------------
    def _probe(self, j: int) -> Optional[dict]:
        from ..distributed.ps_server import _Conn

        probe = _Conn(self.endpoints[j], deadline=2.0, io_timeout=10.0)
        try:
            return probe.call("health")
        except Exception:  # noqa: BLE001 — a dead candidate scores None
            return None
        finally:
            probe.close()

    def _failover(self, dead_j: int) -> None:
        """Promote the best live replica: serving (not draining) beats
        draining, then highest weight_epoch (freshest model), then
        list order. Mirrors RemoteTable._failover's promote-best-live."""
        with self._lock:
            if self._primary != dead_j:
                return  # another thread already moved on
            self._down[dead_j] = time.monotonic()
            best = None
            for j in range(len(self.endpoints)):
                if j == dead_j:
                    continue
                h = self._probe(j)
                if h is None:
                    continue
                rank = (0 if h.get("draining") else 1,
                        int(h.get("weight_epoch", 0)), -j)
                if best is None or rank > best[0]:
                    best = (rank, j)
            if best is None:
                raise ConnectionError(
                    f"all {len(self.endpoints)} serving replicas are "
                    f"unreachable (last dead: "
                    f"{self.endpoints[dead_j]})")
            self._primary = best[1]
            _REG.counter("serve_client_failovers_total").inc()
            import sys

            print(f"[serve_client] replica {self.endpoints[dead_j]} "
                  f"unreachable; failing over to "
                  f"{self.endpoints[best[1]]}", file=sys.stderr,
                  flush=True)
        self._schedule_rejoin(dead_j)

    def _schedule_rejoin(self, dead_j: int) -> None:
        def loop():
            deadline = time.monotonic() + REJOIN_SECS
            while time.monotonic() < deadline \
                    and not self._closed.is_set():
                if self._closed.wait(0.5):
                    return  # client closed: stop probing immediately
                if self._probe(dead_j) is not None:
                    with self._lock:
                        self._down.pop(dead_j, None)
                    _REG.counter("serve_client_rejoins_total").inc()
                    return

        threading.Thread(target=loop, daemon=True,
                         name=f"serve-rejoin-{dead_j}").start()

    def _call(self, method: str, hops: int = 0, **kwargs):
        with self._lock:
            j = self._primary
        try:
            return self._conns[j].call(method, **kwargs)
        except (OverloadedError, DeadlineExceededError):
            raise
        except ConnectionError:
            if hops >= len(self.endpoints):
                raise
            self._failover(j)
            return self._call(method, hops=hops + 1, **kwargs)
        except RuntimeError as e:
            raise _map_app_error(e) from None

    # -- API -------------------------------------------------------------
    def infer(self, feed: Dict[str, np.ndarray],
              deadline_ms: Optional[float] = None) -> InferResult:
        if deadline_ms is not None:
            kwargs = {"feed": feed, "deadline_ms": float(deadline_ms)}
        else:
            kwargs = {"feed": feed}
        t0 = time.perf_counter()
        try:
            if self._hedge_pool is not None:
                reply, replica = self._hedged_infer(kwargs)
            else:
                reply = self._call("infer", **kwargs)
                with self._lock:  # read AFTER: a failover moved routing
                    replica = self.endpoints[self._primary]
            return InferResult(reply, replica)
        finally:
            _REG.histogram(
                "serve_client_infer_ms",
                help="caller-observed infer latency (failover + "
                     "hedging included)").observe(
                (time.perf_counter() - t0) * 1e3)

    def _hedged_infer(self, kwargs: dict):
        """Race the primary against a second replica once the observed
        latency quantile elapses (RemoteTable._hedged_call shape). The
        infer verb is idempotent — a duplicate execution costs device
        time, never correctness. Overloaded/DeadlineExceeded are
        DELIBERATE replies: the race only ends early on success or when
        both legs errored."""
        from concurrent import futures as _fut

        hist = _REG.histogram("ps_client_rpc_ms", verb="infer")
        with self._lock:
            j = self._primary
        if hist.count < self._hedge_min or len(self.endpoints) < 2:
            reply = self._call("infer", **kwargs)
            return reply, self.endpoints[j]
        delay_s = max(hist.quantile(self._hedge_q) / 1e3, 1e-3)
        fut = self._hedge_pool.submit(_tracing.bound(
            lambda: self._call("infer", **dict(kwargs))))
        try:
            return fut.result(timeout=delay_s), self.endpoints[j]
        except _fut.TimeoutError:
            pass
        except RuntimeError:
            raise
        _REG.counter("serve_client_hedges_issued_total").inc()
        with self._lock:
            hedge_j = next(
                (k for k in range(len(self.endpoints))
                 if k != self._primary and k not in self._down),
                (self._primary + 1) % len(self.endpoints))

        def _hedge_exec():
            with _tracing.span("hedge:infer",
                               attrs={"peer": self.endpoints[hedge_j]}):
                return self._conns[hedge_j].call("infer", **dict(kwargs))

        hedge = self._hedge_pool.submit(_tracing.bound(_hedge_exec))
        pending = {fut: self.endpoints[j], hedge: self.endpoints[hedge_j]}
        last_err = None
        while pending:
            done, _ = _fut.wait(set(pending),
                                return_when=_fut.FIRST_COMPLETED)
            for f in done:
                src = pending.pop(f)
                err = f.exception()
                if err is None:
                    if f is hedge:
                        _REG.counter(
                            "serve_client_hedges_won_total").inc()
                    return f.result(), src
                last_err = err
        if isinstance(last_err, RuntimeError):
            raise _map_app_error(last_err)
        raise last_err

    class GenerateResult:
        __slots__ = ("tokens", "weight_epoch", "ttft_ms", "replica")

        def __init__(self, reply: dict, replica: str):
            self.tokens = list(reply["tokens"])
            self.weight_epoch = int(reply.get("weight_epoch", 0))
            self.ttft_ms = reply.get("ttft_ms")
            self.replica = replica

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 deadline_ms: Optional[float] = None,
                 eos_id: Optional[int] = None) -> "GenerateResult":
        """Blocking autoregressive generation on the primary replica.
        Generation is NOT hedged: a duplicate run would burn KV pages
        and decode slots on two replicas for one reply."""
        kwargs = {"prompt": [int(t) for t in prompt],
                  "max_new_tokens": int(max_new_tokens)}
        if deadline_ms is not None:
            kwargs["deadline_ms"] = float(deadline_ms)
        if eos_id is not None:
            kwargs["eos_id"] = int(eos_id)
        t0 = time.perf_counter()
        try:
            reply = self._call("generate", **kwargs)
            with self._lock:
                replica = self.endpoints[self._primary]
            return self.GenerateResult(reply, replica)
        finally:
            _REG.histogram(
                "serve_client_generate_ms",
                help="caller-observed generation latency").observe(
                (time.perf_counter() - t0) * 1e3)

    def generate_stream(self, prompt: Sequence[int],
                        max_new_tokens: int = 16,
                        deadline_ms: Optional[float] = None,
                        eos_id: Optional[int] = None,
                        poll_s: float = 0.01):
        """Incremental generation: yields lists of new tokens as the
        replica's decode loop produces them.  The PS transport is
        one-shot request/reply, so streaming is poll-based: `generate`
        with stream=True returns a stream id, `generate_poll` drains it.
        The stream is pinned to one replica (KV state is replica-local);
        a mid-stream replica death surfaces as the connection error."""
        kwargs = {"prompt": [int(t) for t in prompt],
                  "max_new_tokens": int(max_new_tokens), "stream": True}
        if deadline_ms is not None:
            kwargs["deadline_ms"] = float(deadline_ms)
        if eos_id is not None:
            kwargs["eos_id"] = int(eos_id)
        with self._lock:
            j = self._primary
        sid = self._conns[j].call("generate", **kwargs)["stream_id"]
        cursor = 0
        while True:
            try:
                snap = self._conns[j].call("generate_poll",
                                           stream_id=sid, cursor=cursor)
            except RuntimeError as e:
                raise _map_app_error(e) from None
            if snap["tokens"]:
                yield list(snap["tokens"])
            cursor = int(snap["cursor"])
            if snap["done"]:
                if snap.get("error"):
                    raise _map_app_error(RuntimeError(snap["error"]))
                return
            time.sleep(poll_s)

    def model_info(self) -> dict:
        return self._call("model_info")

    def health(self, replica: Optional[int] = None) -> dict:
        if replica is not None:
            return self._conns[replica].call("health")
        return self._call("health")

    def stats(self, all_replicas: bool = False):
        if not all_replicas:
            return self._call("stats")
        out = []
        for j, c in enumerate(self._conns):
            try:
                out.append({"endpoint": self.endpoints[j],
                            **c.call("stats")})
            except Exception as e:  # noqa: BLE001 — dead replica row
                out.append({"endpoint": self.endpoints[j],
                            "error": f"{type(e).__name__}: {e}"})
        return out

    def client_stats(self) -> dict:
        """This process's serve_client_* + ps_client_* registry slice."""
        snap = _REG.snapshot()
        return {k: v for k, v in snap.items()
                if k.startswith(("serve_client_", "ps_client_"))}

    def close(self) -> None:
        self._closed.set()  # rejoin probes must not outlive the client
        if self._hedge_pool is not None:
            self._hedge_pool.shutdown(wait=False)
        for c in self._conns:
            c.close()
