"""Paged KV cache — a preallocated HBM block pool for the serving engine.

The pool carves two device arrays (keys and values, all layers) into
fixed-size *pages* of ``page_size`` token positions each and hands out
pages to decode requests:

* layout is flat per layer: ``[n_layers, n_pages * page_size, kv_heads,
  head_dim]`` so a logical position maps to device row
  ``page_id * page_size + offset`` — the decode step gathers/scatters by
  flat row index and the paged-attention kernel chases page ids.
* physical page 0 is reserved as a **trash page**: dead batch slots and
  padded positions write there, so scatter indices never need masking.
* pages are refcounted.  ``free`` drops a reference; a zero-ref page
  returns to the free list unless it is hash-registered as a cached
  prompt prefix, in which case it parks in an LRU side pool and is
  reclaimed lazily when allocation pressure needs it.
* **prefix cache**: full pages of a prompt are registered under a
  page-granular rolling hash (``_page_hash`` chains the parent page's
  hash with the page's token tuple).  ``match_prefix`` walks a new
  prompt page-by-page, verifying both the hash chain and the stored
  token tuple + parent id — a hash collision therefore degrades to a
  miss, never to wrong KV reuse (tests monkeypatch ``_page_hash`` to a
  constant to prove it).
* **copy-on-write**: matched pages may be shared by many requests.  A
  writer that must touch a shared or cached page calls
  ``ensure_private`` first, which hands back a fresh page id and tells
  the caller to copy the payload — the engine issues the device copy.

Sizing comes from the memtop live-range machinery: ``from_budget`` fits
the pool into the ``PADDLE_HBM_BUDGET_BYTES`` envelope (the same budget
``memtop --budget`` gates on), and the pool registers a ``kv_pool``
section on /memz so residency shows up next to the allocator stats.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

ENV_KV_PAGES = "PADDLE_SERVE_KV_PAGES"
ENV_KV_PAGE_SIZE = "PADDLE_SERVE_KV_PAGE_SIZE"
ENV_KV_BUDGET_FRAC = "PADDLE_SERVE_KV_BUDGET_FRAC"

_DEFAULT_PAGES = 64
_DEFAULT_PAGE_SIZE = 16


def _page_hash(parent_hash: Optional[int], tokens: Tuple[int, ...]) -> int:
    """Rolling page hash: chain the parent page's hash with this page's
    token tuple.  Module-level so tests can monkeypatch it to force
    collisions; collision *correctness* comes from the token-tuple
    verification in match_prefix, not from hash quality."""
    h = 1469598103934665603 if parent_hash is None else parent_hash
    for t in tokens:
        h = ((h ^ (int(t) & 0xFFFFFFFF)) * 1099511628211) & (2 ** 64 - 1)
    return h


class PagedKVPool:
    """Page accounting + the device-resident KV arrays.

    The engine threads ``self.k`` / ``self.v`` through its jitted decode
    step functionally (with buffer donation) and stores the updated
    arrays back via ``set_arrays`` — the pool itself never launches
    device work, so it stays importable and testable without jax.
    """

    def __init__(self, *, n_pages: int, page_size: int, n_layers: int,
                 kv_heads: int, head_dim: int, dtype="float32",
                 allocate: bool = True):
        if n_pages < 2:
            raise ValueError("n_pages must be >= 2 (page 0 is reserved)")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.n_layers = int(n_layers)
        self.kv_heads = int(kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = np.dtype(dtype)
        self._lock = threading.RLock()
        # page 0 = trash; ids 1..n_pages-1 allocatable
        self._free: List[int] = list(range(1, self.n_pages))
        self._ref: Dict[int, int] = {}
        # prefix cache: chained hash -> pid; pid -> (parent_pid, tokens)
        self._hash_to_pid: Dict[int, int] = {}
        self._page_meta: Dict[int, Tuple[Optional[int], Tuple[int, ...]]] = {}
        self._cached: "OrderedDict[int, None]" = OrderedDict()  # LRU, ref==0
        self.prefix_hits = 0          # pages reused from the cache
        self.prefix_misses = 0        # pages walked without a hit
        self.collisions = 0           # hash hit, token verify failed
        self.cow_copies = 0
        self.k = None
        self.v = None
        if allocate:
            self._allocate_arrays()
        self._register_telemetry()

    # -- device arrays ------------------------------------------------

    def _allocate_arrays(self) -> None:
        import jax.numpy as jnp

        shape = (self.n_layers, self.n_pages * self.page_size,
                 self.kv_heads, self.head_dim)
        self.k = jnp.zeros(shape, dtype=jnp.dtype(self.dtype.name))
        self.v = jnp.zeros(shape, dtype=jnp.dtype(self.dtype.name))

    def set_arrays(self, k, v) -> None:
        self.k, self.v = k, v

    @property
    def bytes_total(self) -> int:
        return (2 * self.n_layers * self.n_pages * self.page_size
                * self.kv_heads * self.head_dim * self.dtype.itemsize)

    @classmethod
    def from_budget(cls, *, n_layers: int, kv_heads: int, head_dim: int,
                    dtype="float32", page_size: Optional[int] = None,
                    n_pages: Optional[int] = None, **kw) -> "PagedKVPool":
        """Size the pool from the serving envs, falling back to a
        fraction of the memtop HBM budget when no explicit page count is
        given.  ``memtop --budget`` remains the fit gate: the pool's
        standing allocation shows up in the live allocator stats it
        renders, and /memz carries the pool section."""
        if page_size is None and not os.environ.get(ENV_KV_PAGE_SIZE):
            # No explicit choice anywhere: let the paged-attention
            # autotuner pick (r22). The kernel streams one KV page per
            # grid step, so its tuned page size IS the pool's page size
            # — a mismatch would force a re-layout at attention time.
            # Silent no-op when tuning is off or the cache has no entry.
            try:
                from .. import tuning as _tuning

                if _tuning.enabled():
                    cfg = _tuning.maybe_lookup("paged_attention", {
                        "kv_heads": int(kv_heads),
                        "head_dim": int(head_dim),
                        "dtype": str(np.dtype(dtype).name),
                    })
                    if cfg and cfg.get("page_size"):
                        page_size = int(cfg["page_size"])
            except Exception:  # noqa: BLE001 — tuning is best-effort
                pass
        page_size = int(page_size or os.environ.get(
            ENV_KV_PAGE_SIZE, _DEFAULT_PAGE_SIZE))
        if n_pages is None and os.environ.get(ENV_KV_PAGES):
            n_pages = int(os.environ[ENV_KV_PAGES])
        if n_pages is None:
            from ..telemetry.memory import hbm_budget_bytes

            budget = hbm_budget_bytes()
            if budget:
                frac = float(os.environ.get(ENV_KV_BUDGET_FRAC, "0.3"))
                page_bytes = (2 * n_layers * page_size * kv_heads
                              * head_dim * np.dtype(dtype).itemsize)
                n_pages = max(2, int(budget * frac) // max(1, page_bytes))
        n_pages = int(n_pages or _DEFAULT_PAGES)
        return cls(n_pages=n_pages, page_size=page_size,
                   n_layers=n_layers, kv_heads=kv_heads,
                   head_dim=head_dim, dtype=dtype, **kw)

    # -- allocation ---------------------------------------------------

    def available(self) -> int:
        with self._lock:
            return len(self._free) + len(self._cached)

    @property
    def capacity(self) -> int:
        return self.n_pages - 1

    def _reclaim_one(self) -> bool:
        """Evict the least-recently-parked cached prefix page back to
        the free list (dropping its hash registration)."""
        if not self._cached:
            return False
        pid, _ = self._cached.popitem(last=False)
        self._unregister(pid)
        self._ref.pop(pid, None)
        self._free.append(pid)
        return True

    def _unregister(self, pid: int) -> None:
        meta = self._page_meta.pop(pid, None)
        if meta is not None:
            parent, tokens = meta
            parent_h = (self._chain_hash_of(parent)
                        if parent is not None else None)
            h = _page_hash(parent_h, tokens)
            if self._hash_to_pid.get(h) == pid:
                del self._hash_to_pid[h]

    def _chain_hash_of(self, pid: int) -> Optional[int]:
        meta = self._page_meta.get(pid)
        if meta is None:
            return None
        parent, tokens = meta
        parent_h = self._chain_hash_of(parent) if parent is not None else None
        return _page_hash(parent_h, tokens)

    def alloc(self, n: int) -> List[int]:
        """Take n pages (refcount 1 each); raises MemoryError when the
        pool cannot satisfy the request even after reclaiming cached
        prefix pages."""
        with self._lock:
            while len(self._free) < n and self._reclaim_one():
                pass
            if len(self._free) < n:
                raise MemoryError(
                    f"kv pool exhausted: want {n} pages, "
                    f"{len(self._free)} free of {self.capacity}")
            pids = [self._free.pop() for _ in range(n)]
            for p in pids:
                self._ref[p] = 1
            return pids

    def incref(self, pids: Sequence[int]) -> None:
        with self._lock:
            for p in pids:
                if p in self._cached:
                    del self._cached[p]
                self._ref[p] = self._ref.get(p, 0) + 1

    def free(self, pids: Sequence[int]) -> None:
        with self._lock:
            for p in pids:
                r = self._ref.get(p, 0) - 1
                if r > 0:
                    self._ref[p] = r
                    continue
                self._ref.pop(p, None)
                if p in self._page_meta:   # cached prefix: park in LRU
                    self._cached[p] = None
                    self._cached.move_to_end(p)
                else:
                    self._free.append(p)

    def refcount(self, pid: int) -> int:
        with self._lock:
            return self._ref.get(pid, 0)

    # -- prefix cache -------------------------------------------------

    def match_prefix(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached page chain matching ``tokens``.  Returns the
        matched physical page ids (each increffed for the caller) and
        the token count they cover.  Only whole pages are shared."""
        psz = self.page_size
        matched: List[int] = []
        with self._lock:
            parent: Optional[int] = None
            parent_h: Optional[int] = None
            for i in range(len(tokens) // psz):
                page_toks = tuple(int(t) for t in tokens[i * psz:(i + 1) * psz])
                h = _page_hash(parent_h, page_toks)
                pid = self._hash_to_pid.get(h)
                if pid is None:
                    self.prefix_misses += 1
                    break
                meta = self._page_meta.get(pid)
                if meta != (parent, page_toks):
                    self.collisions += 1
                    break
                matched.append(pid)
                self.prefix_hits += 1
                parent, parent_h = pid, h
            self.incref(matched)
        return matched, len(matched) * psz

    def register_prefix(self, tokens: Sequence[int],
                        pids: Sequence[int]) -> None:
        """Record every full page of ``tokens`` (held in ``pids``, one
        id per page in order) in the prefix cache.  First writer wins on
        a hash slot; re-registration of an identical chain is a no-op."""
        psz = self.page_size
        with self._lock:
            parent: Optional[int] = None
            parent_h: Optional[int] = None
            for i in range(min(len(pids), len(tokens) // psz)):
                page_toks = tuple(int(t) for t in tokens[i * psz:(i + 1) * psz])
                h = _page_hash(parent_h, page_toks)
                pid = int(pids[i])
                holder = self._hash_to_pid.get(h)
                if holder is None and pid not in self._page_meta:
                    self._hash_to_pid[h] = pid
                    self._page_meta[pid] = (parent, page_toks)
                    holder = pid
                elif holder is None:
                    break  # pid already registered under another chain
                if self._page_meta.get(holder) != (parent, page_toks):
                    break  # occupied slot holds a different chain
                parent, parent_h = holder, h

    def ensure_private(self, pid: int) -> Tuple[int, bool]:
        """Copy-on-write gate: returns (page id to write, needs_copy).
        A page referenced once and not hash-registered is private —
        write in place.  Otherwise allocate a fresh page, drop one ref
        on the shared page, and tell the caller to copy the payload."""
        with self._lock:
            if self._ref.get(pid, 0) <= 1 and pid not in self._page_meta:
                return pid, False
            new = self.alloc(1)[0]
            self.free([pid])
            self.cow_copies += 1
            return new, True

    # -- observability ------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            free = len(self._free)
            cached = len(self._cached)
            active = self.capacity - free - cached
            walked = self.prefix_hits + self.prefix_misses + self.collisions
            return {
                "n_pages": self.n_pages,
                "page_size": self.page_size,
                "pages_free": free,
                "pages_cached": cached,
                "pages_active": active,
                "residency": (active + cached) / max(1, self.capacity),
                "bytes_total": self.bytes_total,
                "prefix_hit_pages": self.prefix_hits,
                "prefix_miss_pages": self.prefix_misses,
                "prefix_collisions": self.collisions,
                "prefix_hit_rate": self.prefix_hits / max(1, walked),
                "cow_copies": self.cow_copies,
            }

    def _register_telemetry(self) -> None:
        try:
            from ..telemetry import get_registry
            from ..telemetry.memory import register_memz_section

            reg = get_registry()
            self._g_free = reg.gauge("kv_pool_pages", state="free")
            self._g_active = reg.gauge("kv_pool_pages", state="active")
            self._g_cached = reg.gauge("kv_pool_pages", state="cached")
            self._g_bytes = reg.gauge("kv_pool_bytes")
            self._g_bytes.set(float(self.bytes_total))
            register_memz_section("kv_pool", self.stats)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            self._g_free = self._g_active = self._g_cached = None

    def publish_gauges(self) -> None:
        if getattr(self, "_g_free", None) is None:
            return
        st = self.stats()
        self._g_free.set(float(st["pages_free"]))
        self._g_active.set(float(st["pages_active"]))
        self._g_cached.set(float(st["pages_cached"]))
