"""Live weight sync: serving replicas subscribe to a PS-hosted weight
table and adopt fresh parameters under an epoch fence.

The creative extension ROADMAP names: the train and serve stacks become
ONE continuous system. A trainer (or a publisher sidecar) packs the
model's parameters into rows of an ordinary PS table — the same
replicated, snapshotted, failover-capable tables the training data
plane already hardened — and each inference replica subscribes:

  publisher   — `pack()` flattens every parameter into a deterministic
                [total_rows, dim] float32 layout (PackPlan: sorted
                names, row offsets derived only from shapes, so trainer
                and replicas agree without a manifest exchange) and
                pushes it with `load_state_dict` — a REPLACE, so
                adoption is value-exact, and a replicated op the
                primary forwards + logs like any other write.
  subscriber  — a replica polls the table: on a REPLICATED partition it
                calls `fetch_replica_state(have_seq=...)` exactly like
                a rejoining backup (full state first, then applied-op
                TAILS — O(new writes), not O(table)); on a plain table
                it falls back to `state_dict` + digest compare. Every
                observed change is handed to `on_adopt(weights,
                version)` — the serving scheduler installs it between
                micro-batches and bumps the weight epoch (server.py).

Gate: PADDLE_SERVE_WEIGHT_SYNC=0 disables the subscriber entirely —
serving is then byte-identical to a static frozen model (the flag-off
drill in tests/test_serving.py).
"""
from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..distributed.ps import ShardedHostTable
from ..telemetry import get_registry

_REG = get_registry()

ENV_SYNC = "PADDLE_SERVE_WEIGHT_SYNC"
ENV_TABLE = "PADDLE_SERVE_WEIGHT_TABLE"
ENV_ENDPOINTS = "PADDLE_SERVE_WEIGHT_ENDPOINTS"
ENV_POLL = "PADDLE_SERVE_WEIGHT_POLL_SECS"

DEFAULT_DIM = 64
DEFAULT_NUM_SHARDS = 4


# ---------------------------------------------------------------------------
# deterministic packing
# ---------------------------------------------------------------------------


@dataclass
class PackPlan:
    """Row layout of a parameter set inside a [total_rows, dim] table.
    Derived ONLY from sorted (name, shape, dtype) — the trainer and
    every replica compute the identical plan from the same frozen
    model, no manifest wire exchange needed."""

    dim: int
    entries: List[Tuple[str, tuple, str, int, int]]  # name, shape, dtype, row_offset, n_rows
    total_rows: int

    def names(self) -> List[str]:
        return [e[0] for e in self.entries]


def pack_plan(shapes: Dict[str, tuple], dtypes: Optional[Dict[str, str]]
              = None, dim: int = DEFAULT_DIM) -> PackPlan:
    entries = []
    offset = 0
    for name in sorted(shapes):
        shape = tuple(int(d) for d in shapes[name])
        size = int(np.prod(shape)) if shape else 1
        n_rows = max(1, -(-size // dim))
        dtype = str((dtypes or {}).get(name, "float32"))
        entries.append((name, shape, dtype, offset, n_rows))
        offset += n_rows
    return PackPlan(dim=int(dim), entries=entries, total_rows=offset)


def plan_for_frozen(frozen, dim: int = DEFAULT_DIM) -> PackPlan:
    """PackPlan over a FrozenModel's captured weights."""
    shapes, dtypes = {}, {}
    for n in frozen.param_names:
        v = frozen.scope.find_var(n)
        shapes[n] = np.shape(v)
        dtypes[n] = str(np.asarray(v).dtype)
    return pack_plan(shapes, dtypes, dim=dim)


def pack(plan: PackPlan, values: Dict[str, np.ndarray]) -> np.ndarray:
    out = np.zeros((plan.total_rows, plan.dim), np.float32)
    for name, shape, _dtype, offset, n_rows in plan.entries:
        v = values.get(name)
        if v is None:
            raise KeyError(f"pack: missing value for {name!r}")
        flat = np.asarray(v, np.float32).reshape(-1)
        out[offset:offset + n_rows].reshape(-1)[:flat.size] = flat
    return out


def unpack(plan: PackPlan, rows: np.ndarray) -> Dict[str, np.ndarray]:
    out = {}
    for name, shape, dtype, offset, n_rows in plan.entries:
        size = int(np.prod(shape)) if shape else 1
        flat = np.asarray(rows[offset:offset + n_rows],
                          np.float32).reshape(-1)[:size]
        out[name] = flat.reshape(shape).astype(np.dtype(dtype))
    return out


# ---------------------------------------------------------------------------
# publisher (trainer side)
# ---------------------------------------------------------------------------


def table_shape(plan: PackPlan) -> tuple:
    return (plan.total_rows, plan.dim)


def table_kwargs(plan: PackPlan) -> dict:
    """The weight table's creation kwargs (pair with table_shape).
    SGD/lr are inert — the publisher only ever replaces state — but the
    spec is table identity on the server, so every party must build the
    same one: `RemoteTable(name, table_shape(p), eps, **table_kwargs(p))`."""
    return {"dtype": "float32", "num_shards": DEFAULT_NUM_SHARDS,
            "optimizer": "sgd", "learning_rate": 0.0, "seed": 0}


def _server_states(packed: np.ndarray, n_servers: int,
                   num_shards: int = DEFAULT_NUM_SHARDS) -> List[dict]:
    """Split packed rows into per-server ShardedHostTable state_dicts
    matching RemoteTable's row placement (global row r -> server r % n,
    local r // n; within a server, shard s holds local % num_shards ==
    s at local // num_shards)."""
    states = []
    for s in range(n_servers):
        rows_s = packed[s::n_servers]
        shards = [np.ascontiguousarray(rows_s[k::num_shards])
                  for k in range(num_shards)]
        states.append({"shards": shards, "accum": [None] * num_shards,
                       "optimizer": "sgd", "learning_rate": 0.0})
    return states


class WeightPublisher:
    """Push a scope's parameters into the weight table. `table` is any
    ShardedHostTable duck type (in-process table or RemoteTable)."""

    def __init__(self, table, plan: PackPlan):
        self.table = table
        self.plan = plan
        self.pushes = 0

    def publish(self, scope_or_values) -> int:
        values = scope_or_values
        if hasattr(scope_or_values, "find_var"):
            values = {n: scope_or_values.find_var(n)
                      for n in self.plan.names()}
        packed = pack(self.plan, values)
        n = getattr(self.table, "_n", None)
        if n is None:  # in-process ShardedHostTable
            k = self.table.num_shards
            self.table.load_state_dict(_server_states(packed, 1, k)[0])
        else:
            k = self.table._specs[0]["num_shards"]
            self.table.load_state_dict(
                {"servers": _server_states(packed, n, k)})
        self.pushes += 1
        _REG.counter("serve_weight_pushes_total").inc()
        return self.pushes


# ---------------------------------------------------------------------------
# subscriber (replica side)
# ---------------------------------------------------------------------------


class WeightSubscriber:
    """Poll the weight table and deliver fresh parameter sets.

    Replicated partitions are followed like a rejoining backup follows
    its primary: `fetch_replica_state(have_seq)` hands back either the
    applied-op tail since have_seq (cheap steady state) or a full state
    transfer (first contact / ring overrun), applied to a local mirror
    table with the server's own arithmetic — the mirror is
    bit-identical to the primary's copy by construction. Plain tables
    fall back to polled `state_dict` + sha256 digest compare.

    on_adopt(weights, version) runs on the poll thread; the consumer
    (server.py) stages the delivery and installs it under its own epoch
    fence.
    """

    def __init__(self, endpoints: Sequence[str], name: str, plan: PackPlan,
                 on_adopt: Callable[[Dict[str, np.ndarray], int], None],
                 poll_secs: float = 2.0, create: bool = False):
        from ..distributed.ps_server import _Conn

        self.endpoints = list(endpoints)
        self.name = name
        self.plan = plan
        self.on_adopt = on_adopt
        self.poll_secs = float(poll_secs)
        self._n = len(self.endpoints)
        self._conns = [_Conn(ep, deadline=5.0, io_timeout=15.0)
                       for ep in self.endpoints]
        self._create = bool(create)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.version = 0
        self._seq: Dict[int, int] = {}       # partition -> last seq
        self._mirrors: Dict[int, ShardedHostTable] = {}
        self._digest: Optional[str] = None   # plain-table mode
        self._replicated: Optional[bool] = None

    # -- partition plumbing ----------------------------------------------
    def _part_rows(self, p: int) -> int:
        return (self.plan.total_rows - p + self._n - 1) // self._n

    def _mirror(self, p: int) -> ShardedHostTable:
        m = self._mirrors.get(p)
        if m is None:
            kw = table_kwargs(self.plan)
            kw.pop("dtype", None)
            m = ShardedHostTable(self.name,
                                 (self._part_rows(p), self.plan.dim),
                                 **kw)
            self._mirrors[p] = m
        return m

    def _probe_replicated(self) -> Optional[bool]:
        """True: follow replicated partitions; False: plain polling;
        None: the table does not exist YET — decide on a later poll
        (latching a mode before the publisher created the table would
        pin the subscriber to the wrong key shape forever)."""
        # the replicated key first; missing replica state on an
        # existing table reports role None, a missing table raises
        try:
            st = self._conns[0].call("replica_status", name=self.name,
                                     partition=0)
            return st.get("role") is not None
        except Exception:  # noqa: BLE001 — fall back to the plain key
            try:
                st = self._conns[0].call("replica_status", name=self.name)
                return st.get("role") is not None
            except Exception:  # noqa: BLE001
                return None

    def _fetch_partition(self, p: int) -> bool:
        """Pull partition p up to date; True when new writes landed."""
        from ..distributed.ps_server import NotPrimaryError, \
            StalePrimaryError, _table_key

        key = _table_key(self.name, p)
        mirror = self._mirror(p)
        have = self._seq.get(p, -1)
        last_err: Optional[BaseException] = None
        # primary discovery: partition p's chain starts at server p
        for off in range(self._n):
            j = (p + off) % self._n
            try:
                out = self._conns[j].call("fetch_replica_state", key=key,
                                          have_seq=have)
            except (NotPrimaryError, StalePrimaryError, ConnectionError,
                    KeyError) as e:
                last_err = e
                continue
            if "state" in out:
                state = dict(out["state"])
                state.pop("replica_meta", None)
                mirror.load_state_dict(state)
            else:
                for _seq, op, ids, payload, _dedup in out["tail"]:
                    if op == "push_gradients":
                        mirror.push_gradients(ids, payload)
                    elif op == "push_delta":
                        mirror.push_delta(ids, payload)
                    elif op == "load_state":
                        mirror.load_state_dict(dict(payload))
                    else:
                        raise ValueError(
                            f"weight sync: unknown replicated op {op!r}")
            new_seq = int(out["seq"])
            changed = new_seq != have
            self._seq[p] = new_seq
            return changed
        raise ConnectionError(
            f"weight table {self.name!r} partition {p}: no replica "
            f"answered fetch_replica_state: {last_err}")

    def _poll_plain(self) -> bool:
        """Unreplicated fallback: full state_dict per server + digest."""
        states = []
        for s in range(self._n):
            states.append(self._conns[s].call("state_dict",
                                              name=self.name))
        blob = pickle.dumps(states, protocol=pickle.HIGHEST_PROTOCOL)
        digest = hashlib.sha256(blob).hexdigest()
        if digest == self._digest:
            return False
        self._digest = digest
        for s, st in enumerate(states):
            st = dict(st)
            st.pop("replica_meta", None)
            m = self._mirror(s)
            m.load_state_dict(st)
        return True

    # -- the poll --------------------------------------------------------
    def poll_once(self) -> bool:
        """One subscription round; True when fresh weights were adopted
        (on_adopt ran). Deterministic — tests drive it directly."""
        if self._replicated is None:
            self._replicated = self._probe_replicated()
            if self._replicated is None:
                return False  # table not created yet; retry next poll
        if self._replicated:
            changed = False
            for p in range(self._n):
                changed |= self._fetch_partition(p)
        else:
            changed = self._poll_plain()
        if not changed:
            return False
        packed = np.empty((self.plan.total_rows, self.plan.dim),
                          np.float32)
        for p in range(self._n):
            packed[p::self._n] = self._mirrors[p].to_dense()
        self.version += 1
        _REG.counter("serve_weight_adoptions_total").inc()
        self.on_adopt(unpack(self.plan, packed), self.version)
        return True

    # -- thread lifecycle ------------------------------------------------
    def start(self) -> "WeightSubscriber":
        def loop():
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception as e:  # noqa: BLE001 — serving survives
                    _REG.counter("serve_weight_poll_errors_total").inc()
                    import sys

                    print(f"[weight_sync] poll failed: {e}",
                          file=sys.stderr, flush=True)
                self._stop.wait(self.poll_secs)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="serve-weight-sync")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        for c in self._conns:
            c.close()


def sync_enabled() -> bool:
    return os.environ.get(ENV_SYNC, "1") not in ("0", "false", "off")


def maybe_start_subscriber(frozen, on_adopt) -> Optional[WeightSubscriber]:
    """Env-driven arming: needs PADDLE_SERVE_WEIGHT_TABLE plus endpoints
    (PADDLE_SERVE_WEIGHT_ENDPOINTS, falling back to the PS list), and
    PADDLE_SERVE_WEIGHT_SYNC must not be 0. Returns the started
    subscriber or None."""
    if not sync_enabled():
        return None
    name = os.environ.get(ENV_TABLE)
    if not name:
        return None
    raw = os.environ.get(ENV_ENDPOINTS) or os.environ.get(
        "PADDLE_PSERVERS_IP_PORT_LIST", "")
    endpoints = [e.strip() for e in raw.split(",") if e.strip()]
    if not endpoints:
        return None
    poll = float(os.environ.get(ENV_POLL, 2.0) or 2.0)
    plan = plan_for_frozen(frozen)
    return WeightSubscriber(endpoints, name, plan, on_adopt,
                            poll_secs=poll).start()
