"""MovieLens-1M recommender data (reference
python/paddle/dataset/movielens.py: train()/test() yielding
[user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
rating]). Synthetic fallback: latent-factor users x movies with ratings
= clipped dot product — the recommender book model can fit it."""
from __future__ import annotations

import os

import numpy as np

CACHE = os.path.expanduser("~/.cache/paddle/dataset/movielens/ml-1m.zip")
N_USERS, N_MOVIES = 400, 300
N_AGE, N_JOB, N_CATEGORY, TITLE_VOCAB, TITLE_LEN = 7, 21, 18, 500, 4
TRAIN_N, TEST_N = 6000, 1200


def max_user_id():
    return N_USERS


def max_movie_id():
    return N_MOVIES


def max_job_id():
    return N_JOB - 1


def age_table():
    return [1, 18, 25, 35, 45, 50, 56]


def movie_categories():
    return [f"genre_{i}" for i in range(N_CATEGORY)]


def get_movie_title_dict():
    return {f"t{i:03d}": i for i in range(TITLE_VOCAB)}


def _movie_meta():
    rng = np.random.RandomState(7)
    cats = [rng.choice(N_CATEGORY, size=rng.randint(1, 4), replace=False)
            for _ in range(N_MOVIES + 1)]
    titles = rng.randint(0, TITLE_VOCAB, size=(N_MOVIES + 1, TITLE_LEN))
    return cats, titles


def _latents():
    rng = np.random.RandomState(11)
    u = rng.randn(N_USERS + 1, 8) * 0.7
    m = rng.randn(N_MOVIES + 1, 8) * 0.7
    return u, m


def _samples(n, seed):
    rng = np.random.RandomState(seed)
    u_lat, m_lat = _latents()
    cats, titles = _movie_meta()
    meta_rng = np.random.RandomState(13)
    genders = meta_rng.randint(0, 2, N_USERS + 1)
    ages = meta_rng.randint(0, N_AGE, N_USERS + 1)
    jobs = meta_rng.randint(0, N_JOB, N_USERS + 1)
    for _ in range(n):
        uid = rng.randint(1, N_USERS + 1)
        mid = rng.randint(1, N_MOVIES + 1)
        score = float(np.clip(
            3.0 + (u_lat[uid] * m_lat[mid]).sum() + 0.3 * rng.randn(),
            1.0, 5.0))
        yield [
            uid, int(genders[uid]), int(ages[uid]), int(jobs[uid]),
            mid, list(int(c) for c in cats[mid]),
            list(int(t) for t in titles[mid]), score,
        ]


def train():
    def reader():
        yield from _samples(TRAIN_N, 0)

    return reader


def test():
    def reader():
        yield from _samples(TEST_N, 1)

    return reader
