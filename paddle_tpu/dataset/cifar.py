"""CIFAR loader (reference python/paddle/dataset/cifar.py — train10/
test10/train100/test100 yield (image[3072] float32 in [0,1], label)).
Synthetic fallback: per-class color/texture prototypes + noise."""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

CACHE = os.path.expanduser("~/.cache/paddle/dataset/cifar")
TRAIN_N, TEST_N = 4000, 800


def _synthetic(n, n_cls, seed):
    rng = np.random.RandomState(seed)
    protos = np.random.RandomState(7).rand(n_cls, 3072).astype(np.float32)
    labels = rng.randint(0, n_cls, n).astype(np.int64)
    imgs = 0.6 * protos[labels] + 0.4 * rng.rand(n, 3072).astype(np.float32)
    return imgs.astype(np.float32), labels


def _real(tar_path, n_cls, split):
    imgs, labels = [], []
    want = "test" if split == "test" else "data"
    with tarfile.open(tar_path) as tf:
        for m in tf.getmembers():
            base = os.path.basename(m.name)
            if n_cls == 10 and base.startswith(want + "_batch"):
                d = pickle.load(tf.extractfile(m), encoding="bytes")
            elif n_cls == 100 and base == ("test" if split == "test" else "train"):
                d = pickle.load(tf.extractfile(m), encoding="bytes")
            else:
                continue
            imgs.append(np.asarray(d[b"data"], np.float32) / 255.0)
            key = b"labels" if n_cls == 10 else b"fine_labels"
            labels.append(np.asarray(d[key], np.int64))
    return np.concatenate(imgs), np.concatenate(labels)


def _load(n_cls, split):
    tar = os.path.join(
        CACHE, "cifar-10-python.tar.gz" if n_cls == 10 else "cifar-100-python.tar.gz"
    )
    if os.path.exists(tar):
        return _real(tar, n_cls, split)
    n = TRAIN_N if split == "train" else TEST_N
    return _synthetic(n, n_cls, seed=0 if split == "train" else 1)


def _reader(images, labels):
    def reader():
        for i in range(images.shape[0]):
            yield images[i], int(labels[i])

    return reader


def train10():
    return _reader(*_load(10, "train"))


def test10():
    return _reader(*_load(10, "test"))


def train100():
    return _reader(*_load(100, "train"))


def test100():
    return _reader(*_load(100, "test"))
