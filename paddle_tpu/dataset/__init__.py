"""Built-in datasets (reference python/paddle/dataset/: mnist, cifar,
imdb, uci_housing — same reader-creator API: `train()` returns a callable
producing a sample generator).

This environment has no network egress, so each loader first looks for
the reference's cache files under ~/.cache/paddle/dataset/ and otherwise
falls back to a DETERMINISTIC SYNTHETIC set with the exact shapes/dtypes
of the real data (class-prototype images + noise for mnist/cifar, a
linear task for uci_housing, a keyword task for imdb). The synthetic
sets are learnable, so end-to-end examples and tests behave like the
real pipelines.
"""
from . import (  # noqa: F401
    cifar,
    common,
    conll05,
    flowers,
    image,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)
