"""IMDB sentiment loader (reference python/paddle/dataset/imdb.py —
word_dict() + train(word_idx)/test(word_idx) yielding
(word_id_sequence, label)). Synthetic fallback: vocabulary of 2000 ids
with class-indicative keyword distributions — learnable by the
sentiment book models."""
from __future__ import annotations

import os

import numpy as np

CACHE = os.path.expanduser("~/.cache/paddle/dataset/imdb/aclImdb_v1.tar.gz")
VOCAB = 2000
TRAIN_N, TEST_N = 2000, 400
SEQ_MIN, SEQ_MAX = 16, 64


def word_dict():
    """word -> id. Synthetic fallback: w0..wN placeholder tokens."""
    return {f"w{i}": i for i in range(VOCAB)}


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    # positive reviews oversample ids [0,200); negative [200,400)
    samples = []
    for _ in range(n):
        label = int(rng.randint(0, 2))
        ln = int(rng.randint(SEQ_MIN, SEQ_MAX + 1))
        base = rng.randint(0, VOCAB, ln)
        key = rng.randint(label * 200, label * 200 + 200, ln)
        use_key = rng.rand(ln) < 0.3
        seq = np.where(use_key, key, base).astype(np.int64)
        samples.append((seq, label))
    return samples


def _reader(samples):
    def reader():
        for seq, label in samples:
            yield seq, label

    return reader


def train(word_idx=None):
    return _reader(_synthetic(TRAIN_N, seed=0))


def test(word_idx=None):
    return _reader(_synthetic(TEST_N, seed=1))
