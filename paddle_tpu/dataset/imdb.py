"""IMDB sentiment loader (reference python/paddle/dataset/imdb.py —
word_dict() + train(word_idx)/test(word_idx) yielding
(word_id_sequence, label)). Synthetic fallback: vocabulary of 2000 ids
with class-indicative keyword distributions — learnable by the
sentiment book models."""
from __future__ import annotations

import os

import numpy as np

CACHE = os.path.expanduser("~/.cache/paddle/dataset/imdb/aclImdb_v1.tar.gz")
VOCAB = 2000
TRAIN_N, TEST_N = 2000, 400
SEQ_MIN, SEQ_MAX = 16, 64


_scan_cache = {}


def _scan_split(split):
    """Tokenized (tokens, label) pairs for one tarball split, scanned at
    most once per process (the single source of the parse/regex logic)."""
    import re
    import tarfile

    key = ("samples", split)
    if key not in _scan_cache:
        out = []
        with tarfile.open(CACHE) as tf:
            for m in tf.getmembers():
                mm = re.match(rf"aclImdb/{split}/(pos|neg)/.*\.txt$", m.name)
                if not mm:
                    continue
                text = tf.extractfile(m).read().decode("utf-8", "ignore").lower()
                toks = re.findall(r"[a-z']+", text)
                out.append((toks, 1 if mm.group(1) == "pos" else 0))
        _scan_cache[key] = out
    return _scan_cache[key]


def _real_samples(split, word_idx=None):
    """Encode a tarball split with word_idx (default: word_dict())."""
    wd = word_idx if word_idx is not None else word_dict()
    unk = len(wd)
    return [
        (np.asarray([wd.get(t, unk) for t in toks], np.int64), label)
        for toks, label in _scan_split(split)
    ]


def word_dict():
    """word -> id (reference imdb.word_dict). Real tarball: the VOCAB most
    frequent training words; synthetic fallback: w0..wN placeholders."""
    if os.path.exists(CACHE):
        if "word_dict" not in _scan_cache:
            import collections

            counts = collections.Counter()
            # reuse the cached raw scan of the training split
            for toks, _ in _scan_split("train"):
                counts.update(toks)
            _scan_cache["word_dict"] = {
                w: i for i, (w, _) in enumerate(counts.most_common(VOCAB - 1))
            }
        return _scan_cache["word_dict"]
    return {f"w{i}": i for i in range(VOCAB)}



def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    # positive reviews oversample ids [0,200); negative [200,400)
    samples = []
    for _ in range(n):
        label = int(rng.randint(0, 2))
        ln = int(rng.randint(SEQ_MIN, SEQ_MAX + 1))
        base = rng.randint(0, VOCAB, ln)
        key = rng.randint(label * 200, label * 200 + 200, ln)
        use_key = rng.rand(ln) < 0.3
        seq = np.where(use_key, key, base).astype(np.int64)
        samples.append((seq, label))
    return samples


def _reader(samples):
    def reader():
        for seq, label in samples:
            yield seq, label

    return reader


def train(word_idx=None):
    if os.path.exists(CACHE):
        return _reader(_real_samples("train", word_idx))
    return _reader(_synthetic(TRAIN_N, seed=0))


def test(word_idx=None):
    if os.path.exists(CACHE):
        return _reader(_real_samples("test", word_idx))
    return _reader(_synthetic(TEST_N, seed=1))
