"""VOC2012 segmentation (reference python/paddle/dataset/voc2012.py:
train()/test()/val() yielding (image CHW float32, label mask HW int32)).
Synthetic fallback: images containing colored rectangles whose class is
recoverable from the color — a learnable toy segmentation task."""
from __future__ import annotations

import numpy as np

N_CLASSES, SIZE = 21, 64
TRAIN_N, TEST_N, VAL_N = 600, 120, 120


def _samples(n, seed):
    rng = np.random.RandomState(seed)
    class_colors = np.random.RandomState(9).rand(N_CLASSES, 3).astype(np.float32)
    for _ in range(n):
        img = 0.05 * rng.rand(3, SIZE, SIZE).astype(np.float32)
        mask = np.zeros((SIZE, SIZE), np.int32)
        for _obj in range(rng.randint(1, 4)):
            cls = rng.randint(1, N_CLASSES)
            x0, y0 = rng.randint(0, SIZE - 16, size=2)
            w, h = rng.randint(8, 16, size=2)
            img[:, y0:y0 + h, x0:x0 + w] = class_colors[cls][:, None, None]
            mask[y0:y0 + h, x0:x0 + w] = cls
        yield img, mask


def train():
    def reader():
        yield from _samples(TRAIN_N, 0)

    return reader


def test():
    def reader():
        yield from _samples(TEST_N, 1)

    return reader


def val():
    def reader():
        yield from _samples(VAL_N, 2)

    return reader
