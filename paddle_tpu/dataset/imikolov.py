"""PTB language-model n-grams (reference python/paddle/dataset/imikolov.py:
build_dict() + train(word_idx, n)/test(word_idx, n) yielding n-gram id
tuples). Synthetic fallback: a deterministic order-2 Markov corpus over
1000 words — predictable structure the word2vec book model can learn."""
from __future__ import annotations

import os

import numpy as np

CACHE = os.path.expanduser(
    "~/.cache/paddle/dataset/imikolov/simple-examples.tgz")
VOCAB = 1000
TRAIN_SENT, TEST_SENT = 2000, 400


def _markov_corpus(n_sent, seed):
    """Sentences from a sparse, fixed transition table (learnable)."""
    rng = np.random.RandomState(42)
    # each word has 4 plausible successors — fixed for every call
    nxt = rng.randint(0, VOCAB, size=(VOCAB, 4))
    gen = np.random.RandomState(seed)
    sents = []
    for _ in range(n_sent):
        length = gen.randint(5, 20)
        w = gen.randint(0, VOCAB)
        sent = [w]
        for _ in range(length - 1):
            w = nxt[w, gen.randint(0, 4)]
            sent.append(w)
        sents.append(sent)
    return sents


def _real_sentences(split):
    import tarfile

    name = f"./simple-examples/data/ptb.{split}.txt"
    with tarfile.open(CACHE) as tf:
        f = tf.extractfile(name)
        return [line.decode().split() for line in f.read().splitlines()]


def build_dict(min_word_freq=50):
    """word -> id; synthetic mode uses "w0001"-style tokens."""
    if os.path.exists(CACHE):
        from collections import Counter

        c = Counter()
        for sent in _real_sentences("train"):
            c.update(sent)
        words = [w for w, f in c.items() if f >= min_word_freq and w != "<unk>"]
        word_idx = {w: i for i, w in enumerate(sorted(words))}
        word_idx["<unk>"] = len(word_idx)
        return word_idx
    return {f"w{i:04d}": i for i in range(VOCAB)}


def _ngram_reader(sentences, word_idx, n):
    unk = word_idx.get("<unk>", len(word_idx))

    def to_id(w):
        if isinstance(w, (int, np.integer)):
            return int(w)
        return word_idx.get(w, unk)

    def reader():
        for sent in sentences:
            ids = [to_id(w) for w in sent]
            for i in range(len(ids) - n + 1):
                yield tuple(ids[i : i + n])

    return reader


def train(word_idx, n):
    if os.path.exists(CACHE):
        return _ngram_reader(_real_sentences("train"), word_idx, n)
    return _ngram_reader(_markov_corpus(TRAIN_SENT, 0), word_idx, n)


def test(word_idx, n):
    if os.path.exists(CACHE):
        return _ngram_reader(_real_sentences("valid"), word_idx, n)
    return _ngram_reader(_markov_corpus(TEST_SENT, 1), word_idx, n)
