"""MNIST loader (reference python/paddle/dataset/mnist.py — same reader
API: train()/test() return creators yielding (image[784] float32 in
[-1,1], label int)). Falls back to a deterministic synthetic set (10
blurred digit prototypes + noise) when the idx-ubyte cache is absent."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

CACHE = os.path.expanduser("~/.cache/paddle/dataset/mnist")
TRAIN_N, TEST_N = 8000, 1600  # synthetic sizes (real: 60000/10000)


def _real(path_img, path_lbl):
    with gzip.open(path_lbl, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), np.uint8)
    with gzip.open(path_img, "rb") as f:
        magic, n, r, c = struct.unpack(">IIII", f.read(16))
        imgs = np.frombuffer(f.read(), np.uint8).reshape(n, r * c)
    imgs = imgs.astype(np.float32) / 127.5 - 1.0
    return imgs, labels.astype(np.int64)


def _prototypes(rng):
    """10 class prototypes: smoothed random blobs, fixed by seed."""
    protos = rng.randn(10, 28, 28).astype(np.float32)
    # cheap blur for spatial structure
    k = np.ones((5, 5), np.float32) / 25.0
    out = np.zeros_like(protos)
    pp = np.pad(protos, [(0, 0), (2, 2), (2, 2)], mode="edge")
    for i in range(28):
        for j in range(28):
            out[:, i, j] = (pp[:, i:i + 5, j:j + 5] * k).sum((1, 2))
    return out.reshape(10, 784) * 3.0


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    protos = _prototypes(np.random.RandomState(42))
    labels = rng.randint(0, 10, n).astype(np.int64)
    imgs = protos[labels] + 0.35 * rng.randn(n, 784).astype(np.float32)
    return np.clip(imgs, -1.0, 1.0).astype(np.float32), labels


def _reader(images, labels):
    def reader():
        for i in range(images.shape[0]):
            yield images[i], int(labels[i])

    return reader


def _load(split):
    img = os.path.join(CACHE, f"{split}-images-idx3-ubyte.gz")
    lbl = os.path.join(CACHE, f"{split}-labels-idx1-ubyte.gz")
    if os.path.exists(img) and os.path.exists(lbl):
        return _real(img, lbl)
    if split == "train":
        return _synthetic(TRAIN_N, seed=0)
    return _synthetic(TEST_N, seed=1)


def train():
    return _reader(*_load("train"))


def test():
    return _reader(*_load("t10k"))
