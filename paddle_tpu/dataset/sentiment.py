"""Movie-review sentiment (reference python/paddle/dataset/sentiment.py —
NLTK movie_reviews based; same reader contract as imdb). Delegates to the
imdb loader's vocabulary/synthetic machinery."""
from __future__ import annotations

from . import imdb as _imdb

NUM_TRAINING_INSTANCES = _imdb.TRAIN_N
NUM_TOTAL_INSTANCES = _imdb.TRAIN_N + _imdb.TEST_N


def get_word_dict():
    return sorted(_imdb.word_dict().items(), key=lambda kv: kv[1])


def train():
    return _imdb.train(_imdb.word_dict())


def test():
    return _imdb.test(_imdb.word_dict())
