"""MQ2007 learning-to-rank (reference python/paddle/dataset/mq2007.py:
train/test with format "pointwise"/"pairwise"/"listwise"). Synthetic
fallback: 46-dim query-doc features whose first dims correlate with the
relevance label, grouped by query."""
from __future__ import annotations

import numpy as np

N_FEAT = 46
N_QUERY_TRAIN, N_QUERY_TEST, DOCS_PER_QUERY = 120, 30, 8


def _queries(n_query, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n_query):
        rel = rng.randint(0, 3, DOCS_PER_QUERY)
        feats = rng.randn(DOCS_PER_QUERY, N_FEAT).astype(np.float32) * 0.3
        feats[:, 0] += rel  # relevance signal
        feats[:, 1] += 0.5 * rel
        yield rel, feats


def _reader(n_query, seed, format):
    def pointwise():
        for rel, feats in _queries(n_query, seed):
            for r, f in zip(rel, feats):
                yield float(r), f

    def pairwise():
        for rel, feats in _queries(n_query, seed):
            for i in range(DOCS_PER_QUERY):
                for j in range(DOCS_PER_QUERY):
                    if rel[i] > rel[j]:
                        yield 1.0, feats[i], feats[j]

    def listwise():
        for rel, feats in _queries(n_query, seed):
            yield rel.astype(np.float32), feats

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train(format="pairwise"):
    return _reader(N_QUERY_TRAIN, 0, format)


def test(format="pairwise"):
    return _reader(N_QUERY_TEST, 1, format)
