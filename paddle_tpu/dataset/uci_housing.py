"""UCI housing loader (reference python/paddle/dataset/uci_housing.py —
train()/test() yield (features[13] float32, price[1] float32)).
Synthetic fallback: fixed linear model + noise (feature-normalized like
the real pipeline)."""
from __future__ import annotations

import os

import numpy as np

CACHE = os.path.expanduser("~/.cache/paddle/dataset/uci_housing/housing.data")
FEATURES = 13
TRAIN_N, TEST_N = 404, 102


def _load_all():
    if os.path.exists(CACHE):
        data = np.loadtxt(CACHE).astype(np.float32)
        x, y = data[:, :-1], data[:, -1:]
    else:
        rng = np.random.RandomState(0)
        w = np.random.RandomState(3).randn(FEATURES, 1).astype(np.float32)
        x = rng.randn(TRAIN_N + TEST_N, FEATURES).astype(np.float32)
        y = x @ w + 0.1 * rng.randn(TRAIN_N + TEST_N, 1).astype(np.float32)
    mu, sd = x.mean(0), x.std(0) + 1e-6
    x = (x - mu) / sd
    return x.astype(np.float32), y.astype(np.float32)


def _reader(x, y):
    def reader():
        for i in range(x.shape[0]):
            yield x[i], y[i]

    return reader


def train():
    x, y = _load_all()
    return _reader(x[:TRAIN_N], y[:TRAIN_N])


def test():
    x, y = _load_all()
    return _reader(x[TRAIN_N:TRAIN_N + TEST_N], y[TRAIN_N:TRAIN_N + TEST_N])
