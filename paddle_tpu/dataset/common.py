"""Dataset cache helpers (reference python/paddle/dataset/common.py).
This environment has no network egress: download() only RETURNS a
pre-populated cache path and raises otherwise (the loaders' synthetic
fallbacks cover the missing-cache case)."""
from __future__ import annotations

import hashlib
import os

DATA_HOME = os.path.expanduser("~/.cache/paddle/dataset")


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name or url.split("/")[-1])
    if os.path.exists(filename) and (
            not md5sum or md5file(filename) == md5sum):
        return filename
    raise RuntimeError(
        f"dataset file {filename} not cached and this environment has no "
        f"network egress; place the file there manually or rely on the "
        f"loader's synthetic fallback"
    )
