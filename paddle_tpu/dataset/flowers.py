"""Oxford-102 flowers (reference python/paddle/dataset/flowers.py:
train()/test()/valid() yielding (image CHW float32, label)). Synthetic
fallback: 102 color-texture class prototypes + noise at 3x64x64 (the
reference yields variable-size jpegs; a fixed small size keeps shapes
static for TPU examples)."""
from __future__ import annotations

import numpy as np

N_CLASSES, SIZE = 102, 64
TRAIN_N, TEST_N, VALID_N = 2040, 612, 510


def _protos():
    rng = np.random.RandomState(6)
    base = rng.rand(N_CLASSES, 3, 8, 8).astype(np.float32)
    return base.repeat(SIZE // 8, axis=2).repeat(SIZE // 8, axis=3)


def _samples(n, seed):
    rng = np.random.RandomState(seed)
    protos = _protos()
    for _ in range(n):
        y = rng.randint(0, N_CLASSES)
        img = protos[y] + 0.15 * rng.randn(3, SIZE, SIZE).astype(np.float32)
        yield np.clip(img, 0.0, 1.0), int(y)


def train(mapper=None, buffered_size=1024, use_xmap=False, cycle=False):
    def reader():
        while True:
            yield from _samples(TRAIN_N, 0)
            if not cycle:
                return

    return reader


def test(mapper=None, buffered_size=1024, use_xmap=False, cycle=False):
    def reader():
        while True:
            yield from _samples(TEST_N, 1)
            if not cycle:
                return

    return reader


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    def reader():
        yield from _samples(VALID_N, 2)

    return reader
