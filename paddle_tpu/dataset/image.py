"""Image helpers (reference python/paddle/dataset/image.py — cv2 based).
numpy-only equivalents: nearest-neighbor resize, center/random crop,
flip, simple_transform; enough for the dataset readers and examples
without an OpenCV dependency."""
from __future__ import annotations

import numpy as np


def resize_short(im, size):
    """Resize (HWC) so the short side == size (nearest neighbor)."""
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, int(round(w * size / h))
    else:
        nh, nw = int(round(h * size / w)), size
    ys = (np.arange(nh) * h / nh).astype(int)
    xs = (np.arange(nw) * w / nw).astype(int)
    return im[ys][:, xs]


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    y0 = max((h - size) // 2, 0)
    x0 = max((w - size) // 2, 0)
    return im[y0:y0 + size, x0:x0 + size]


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    h, w = im.shape[:2]
    y0 = rng.randint(0, max(h - size, 0) + 1)
    x0 = rng.randint(0, max(w - size, 0) + 1)
    return im[y0:y0 + size, x0:x0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize short side -> crop (random+flip when training) -> CHW."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2):
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        im -= np.asarray(mean, np.float32).reshape(-1, 1, 1)
    return im


def load_image(path, is_color=True):
    """Load .npy images (no cv2/PIL in this environment)."""
    return np.load(path)
