"""WMT14 Fr-En pairs (reference python/paddle/dataset/wmt14.py — same
reader contract as wmt16: (src_ids, trg_ids, trg_ids_next)). The
synthetic task is shared with wmt16 (fixed bijection + reversal)."""
from __future__ import annotations

from . import wmt16 as _w


def train(dict_size):
    return _w.train(dict_size, dict_size, "fr")


def test(dict_size):
    return _w.test(dict_size, dict_size, "fr")


def get_dict(dict_size, reverse=False):
    src = _w.get_dict("fr", dict_size, reverse)
    trg = _w.get_dict("en", dict_size, reverse)
    return src, trg
