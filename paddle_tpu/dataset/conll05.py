"""CoNLL-2005 semantic role labeling (reference
python/paddle/dataset/conll05.py: test() yielding the 8-slot SRL sample
(word, ctx_n2..ctx_p2, verb, mark, label ids) + get_dict/get_embedding).
Synthetic fallback: template sentences where the label is a fixed
function of (word-class, distance-to-predicate) — learnable by the
label_semantic_roles book model."""
from __future__ import annotations

import numpy as np

WORD_VOCAB, LABEL_N = 800, 9  # labels: O + 4 * (B-, I-) roles
TEST_N = 1500


def word_dict():
    return {f"w{i:03d}": i for i in range(WORD_VOCAB)}


def verb_dict():
    return {f"v{i:02d}": i for i in range(40)}


def label_dict():
    labels = ["O"]
    for r in range((LABEL_N - 1) // 2):
        labels += [f"B-A{r}", f"I-A{r}"]
    return {l: i for i, l in enumerate(labels)}


def get_dict():
    return word_dict(), verb_dict(), label_dict()


def get_embedding():
    """Deterministic pretrained-style embedding table [WORD_VOCAB, 32]."""
    rng = np.random.RandomState(3)
    return rng.randn(WORD_VOCAB, 32).astype(np.float32) * 0.1


def _samples(n, seed):
    rng = np.random.RandomState(seed)
    n_roles = (LABEL_N - 1) // 2
    for _ in range(n):
        length = rng.randint(5, 15)
        words = rng.randint(0, WORD_VOCAB, size=length)
        pred_pos = rng.randint(0, length)
        verb = int(words[pred_pos]) % 40
        # deterministic labeling: role = word class; B at segment starts
        labels = np.zeros(length, np.int64)
        role = (words % n_roles).astype(np.int64)
        for i in range(length):
            if i == pred_pos:
                labels[i] = 0
            elif i == 0 or role[i] != role[i - 1]:
                labels[i] = 1 + 2 * role[i]
            else:
                labels[i] = 2 + 2 * role[i]
        ctx = {}
        for off in (-2, -1, 0, 1, 2):
            pos = min(max(pred_pos + off, 0), length - 1)
            ctx[off] = int(words[pos])
        mark = (np.arange(length) == pred_pos).astype(np.int64)
        yield (
            words.tolist(),
            [ctx[-2]] * length, [ctx[-1]] * length, [ctx[0]] * length,
            [ctx[1]] * length,
            [verb] * length, mark.tolist(), labels.tolist(),
        )


def test():
    def reader():
        yield from _samples(TEST_N, 1)

    return reader


# the reference exposes only test() publicly for conll05; keep a train()
# convenience for the book model
def train():
    def reader():
        yield from _samples(4 * TEST_N, 0)

    return reader
