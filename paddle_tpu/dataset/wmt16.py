"""WMT16 En-De translation pairs (reference
python/paddle/dataset/wmt16.py: train/test/validation readers yielding
(src_ids, trg_ids, trg_ids_next) and get_dict()). Synthetic fallback:
source sentences over a small vocab with the target defined by a FIXED
bijective word map + reversal — a learnable toy translation task for the
machine-translation book model and Transformer configs."""
from __future__ import annotations

import os

import numpy as np

CACHE = os.path.expanduser("~/.cache/paddle/dataset/wmt16/wmt16.tar.gz")
TRAIN_N, TEST_N, VALID_N = 4000, 600, 600


def _special():
    return {"<s>": 0, "<e>": 1, "<unk>": 2}


def get_dict(lang, dict_size, reverse=False):
    """id table for "en"/"de"; synthetic tokens are f"{lang}{i}"."""
    dict_size = max(dict_size, 8)
    d = dict(_special())
    for i in range(3, dict_size):
        d[f"{lang}{i}"] = i
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _word_map(dict_size):
    """Fixed bijection src id -> trg id (ids >= 3)."""
    rng = np.random.RandomState(5)
    ids = np.arange(3, dict_size)
    perm = rng.permutation(ids)
    m = np.arange(dict_size)
    m[3:] = perm
    return m


def _samples(n, seed, src_dict_size, trg_dict_size):
    src_size = max(src_dict_size, 8)
    trg_size = max(trg_dict_size, 8)
    wmap = _word_map(min(src_size, trg_size))
    rng = np.random.RandomState(seed)
    for _ in range(n):
        length = rng.randint(3, 12)
        src = rng.randint(3, min(src_size, len(wmap)), size=length)
        trg = wmap[src][::-1]  # bijective map + reversal
        trg_in = np.concatenate([[0], trg])       # <s> prefix
        trg_next = np.concatenate([trg, [1]])     # <e> suffix
        yield (src.tolist(), trg_in.tolist(), trg_next.tolist())


def train(src_dict_size, trg_dict_size, src_lang="en"):
    def reader():
        yield from _samples(TRAIN_N, 0, src_dict_size, trg_dict_size)

    return reader


def test(src_dict_size, trg_dict_size, src_lang="en"):
    def reader():
        yield from _samples(TEST_N, 1, src_dict_size, trg_dict_size)

    return reader


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    def reader():
        yield from _samples(VALID_N, 2, src_dict_size, trg_dict_size)

    return reader
