"""Multi-process launcher: `python -m paddle_tpu.distributed.launch
[--nproc_per_node N] [--ips a,b] train.py args...`

Parity surface: reference python/paddle/distributed/launch.py:193 +
utils.py (get_cluster:230, start_local_trainers:340,
watch_local_trainers:407 — abort the whole job when any child dies).

Env protocol per trainer (identical to the reference, consumed by
parallel/env.py):
  PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
  PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT

TPU notes: one process per HOST is the normal topology (all local chips
belong to one PJRT client); --nproc_per_node exists for CPU fleets and
tests. Rendezvous is the JAX coordination service bootstrapped from the
first endpoint (no gen_nccl_id gRPC exchange).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


class Trainer:
    def __init__(self, rank: int, endpoint: str):
        self.rank = rank
        self.endpoint = endpoint
        self.proc: Optional[subprocess.Popen] = None
        self.log = None


def get_cluster(ips: List[str], nproc_per_node: int, start_port: int):
    """[(rank, ip:port)] across all nodes (reference utils.get_cluster)."""
    out = []
    rank = 0
    for ip in ips:
        for i in range(nproc_per_node):
            out.append(Trainer(rank, f"{ip}:{start_port + i}"))
            rank += 1
    return out


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn and watch per-node trainer processes",
    )
    p.add_argument("--ips", "--cluster_node_ips", default="127.0.0.1",
                   help="comma-separated node ips (this script runs on each)")
    p.add_argument("--node_ip", default=None,
                   help="this node's ip (default: first of --ips)")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--log_dir", default=None)
    p.add_argument(
        "--elastic_retries", type=int, default=0,
        help="restart the local trainer group up to N times after a "
        "failure (trainers resume from their own checkpoints; "
        "PADDLE_ELASTIC_RESTART carries the attempt number). 0 = "
        "reference behavior: fail fast (utils.py:407)",
    )
    p.add_argument(
        "--heartbeat_timeout", type=float, default=0.0,
        help="treat a trainer as hung when its heartbeat file "
        "(distributed/heartbeat.py; stamped by init_parallel_env) goes "
        "stale for this many seconds — catches collective deadlocks that "
        "never exit. 0 = off",
    )
    p.add_argument(
        "--server_num", type=int, default=0,
        help="spawn N local parameter-server processes "
        "(distributed/ps_server.py) on free ports and export "
        "PADDLE_PSERVERS_IP_PORT_LIST to the trainers (reference "
        "launch_ps.py). Servers outlive elastic restarts, so hosted "
        "tables survive a trainer-group respawn",
    )
    p.add_argument(
        "--servers", default="",
        help="explicit pserver endpoint list host:port,... — endpoints "
        "whose host matches this node are spawned here; the full list "
        "is exported to trainers (multi-node PS). Overrides --server_num",
    )
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def start_pservers(server_num: int, servers: str, node_ip: str,
                   log_dir: Optional[str] = None):
    """Spawn this node's pserver processes (reference launch_ps.py
    start_procs). Returns (procs, full_endpoint_list). --server_num
    spawns on launcher-chosen free ports (the child binds port 0 and
    reports the bound port on stdout, so there is no pick-then-bind
    race); --servers spawns the endpoints whose host is this node."""
    procs, endpoints = [], []
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)

    def spawn(port: int, host: str, idx: int):
        env = dict(os.environ)
        env["PADDLE_TRAINING_ROLE"] = "PSERVER"
        cmd = [sys.executable, "-u", "-m",
               "paddle_tpu.distributed.ps_server",
               "--port", str(port), "--host", host]
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        line = proc.stdout.readline()  # "[ps_server] listening on h:p"
        if "listening on" not in line:
            proc.kill()
            raise RuntimeError(f"pserver {idx} failed to start: {line!r}")
        bound = int(line.rsplit(":", 1)[1])
        if log_dir:
            log = open(os.path.join(log_dir, f"serverlog.{idx}"), "w")
            log.write(line)

            def drain(p=proc, f=log):
                for ln in p.stdout:
                    f.write(ln)
                f.close()
        else:
            def drain(p=proc):
                for _ in p.stdout:
                    pass
        import threading

        threading.Thread(target=drain, daemon=True).start()
        procs.append(proc)
        return bound

    try:
        if servers:
            eps = [e.strip() for e in servers.split(",") if e.strip()]
            for i, ep in enumerate(eps):
                host, port = ep.rsplit(":", 1)
                if host in (node_ip, "127.0.0.1", "localhost"):
                    spawn(int(port), host, i)
            endpoints = eps
        else:
            for i in range(server_num):
                bound = spawn(0, "127.0.0.1", i)
                endpoints.append(f"127.0.0.1:{bound}")
    except BaseException:
        # partial startup must not orphan the servers already running
        terminate_pservers(procs)
        raise
    return procs, endpoints


def terminate_pservers(procs):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            p.kill()


def start_local_trainers(cluster: List[Trainer], node_ip: str, script: str,
                         script_args: List[str], log_dir: Optional[str],
                         restart_count: int = 0,
                         heartbeat_dir: Optional[str] = None):
    """Fork this node's trainers with the env protocol (reference
    utils.start_local_trainers:340)."""
    endpoints = ",".join(t.endpoint for t in cluster)
    local = [t for t in cluster if t.endpoint.split(":")[0] == node_ip]
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    for t in local:
        env = dict(os.environ)
        env.update(
            PADDLE_TRAINER_ID=str(t.rank),
            PADDLE_TRAINERS_NUM=str(len(cluster)),
            PADDLE_TRAINER_ENDPOINTS=endpoints,
            PADDLE_CURRENT_ENDPOINT=t.endpoint,
            PADDLE_ELASTIC_RESTART=str(restart_count),
        )
        if heartbeat_dir:
            env["PADDLE_HEARTBEAT_DIR"] = heartbeat_dir
        cmd = [sys.executable, "-u", script] + list(script_args)
        if log_dir:
            mode = "a" if restart_count else "w"
            t.log = open(os.path.join(log_dir, f"workerlog.{t.rank}"), mode)
            t.proc = subprocess.Popen(cmd, env=env, stdout=t.log,
                                      stderr=subprocess.STDOUT)
        else:
            t.proc = subprocess.Popen(cmd, env=env)
    return local


def terminate_local_trainers(trainers: List[Trainer]):
    for t in trainers:
        if t.proc and t.proc.poll() is None:
            t.proc.terminate()
    deadline = time.time() + 5
    for t in trainers:
        if not t.proc:
            continue
        while t.proc.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if t.proc.poll() is None:
            t.proc.kill()
    for t in trainers:
        if t.log:
            t.log.close()


def watch_local_trainers(trainers: List[Trainer], poll_interval=0.2,
                         monitor=None) -> int:
    """Block until all trainers exit. Any nonzero exit — or a stale
    heartbeat when `monitor` (heartbeat.HeartBeatMonitor) is given —
    aborts the whole local group (reference watch_local_trainers:407:
    fail fast; heartbeat parity: heart_beat_monitor.h:54). Returns the
    job's exit code."""
    try:
        while True:
            alive = False
            for t in trainers:
                rc = t.proc.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    print(
                        f"[launch] trainer {t.rank} ({t.endpoint}) exited "
                        f"with {rc}; aborting the job",
                        file=sys.stderr,
                    )
                    terminate_local_trainers(trainers)
                    return rc
            if not alive:
                return 0
            if monitor is not None:
                running = [t.rank for t in trainers if t.proc.poll() is None]
                stale = monitor.stale_ranks(ranks=running)
                if stale:
                    print(
                        f"[launch] trainer rank(s) {stale} stopped "
                        f"heartbeating for >{monitor.timeout}s (hang?); "
                        f"aborting the group",
                        file=sys.stderr,
                    )
                    terminate_local_trainers(trainers)
                    return 124  # timeout-style exit code
            time.sleep(poll_interval)
    except KeyboardInterrupt:
        terminate_local_trainers(trainers)
        return 128 + signal.SIGINT


def launch(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    ips = [s.strip() for s in args.ips.split(",") if s.strip()]
    node_ip = args.node_ip or ips[0]
    cluster = get_cluster(ips, args.nproc_per_node, args.started_port)

    heartbeat_dir = None
    own_heartbeat_dir = False
    if args.heartbeat_timeout > 0:
        heartbeat_dir = os.environ.get("PADDLE_HEARTBEAT_DIR")
        if not heartbeat_dir:
            import tempfile

            heartbeat_dir = tempfile.mkdtemp(prefix="paddle_tpu_hb_")
            own_heartbeat_dir = True

    pservers = []
    try:
        if args.server_num or args.servers:
            pservers, endpoints = start_pservers(
                args.server_num, args.servers, node_ip, args.log_dir)
            # trainers inherit the list through start_local_trainers' env
            os.environ["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(endpoints)
            os.environ.setdefault("PADDLE_TRAINING_ROLE", "TRAINER")
        return _launch_attempts(args, ips, node_ip, cluster, heartbeat_dir)
    finally:
        terminate_pservers(pservers)
        if own_heartbeat_dir:
            import shutil

            shutil.rmtree(heartbeat_dir, ignore_errors=True)


def _launch_attempts(args, ips, node_ip, cluster, heartbeat_dir) -> int:
    attempt = 0
    while True:
        local = start_local_trainers(
            cluster, node_ip, args.training_script, args.training_script_args,
            args.log_dir, restart_count=attempt, heartbeat_dir=heartbeat_dir,
        )
        if not local:
            print(f"[launch] node_ip {node_ip} not in --ips {ips}", file=sys.stderr)
            return 2
        monitor = None
        if heartbeat_dir:
            from .heartbeat import HeartBeatMonitor

            # created AFTER spawn: a fresh monitor ignores stamps older
            # than itself, so leftovers from a previous attempt/job in a
            # reused shared dir never read as hangs
            monitor = HeartBeatMonitor(
                heartbeat_dir, [t.rank for t in local], args.heartbeat_timeout
            )
        rc = watch_local_trainers(local, monitor=monitor)
        if rc == 0 or attempt >= args.elastic_retries or rc == 128 + signal.SIGINT:
            return rc
        attempt += 1
        print(
            f"[launch] elastic restart {attempt}/{args.elastic_retries} "
            f"after exit code {rc} (trainers resume from checkpoint)",
            file=sys.stderr,
        )
        if heartbeat_dir:
            # drop stale stamps so the new group starts with a clean slate
            from .heartbeat import _stamp_path

            for t in local:
                try:
                    os.remove(_stamp_path(heartbeat_dir, t.rank))
                except OSError:
                    pass


if __name__ == "__main__":
    sys.exit(launch())
