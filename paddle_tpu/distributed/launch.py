"""Multi-process launcher: `python -m paddle_tpu.distributed.launch
[--nproc_per_node N] [--ips a,b] train.py args...`

Parity surface: reference python/paddle/distributed/launch.py:193 +
utils.py (get_cluster:230, start_local_trainers:340,
watch_local_trainers:407 — abort the whole job when any child dies).

Env protocol per trainer (identical to the reference, consumed by
parallel/env.py):
  PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
  PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT

Fault tolerance: --elastic_retries supervises BOTH sides of the PS data
plane. Trainer groups are respawned after a failure (reference behavior
is fail-fast only), and pserver processes are watched the same way —
a dead pserver is restarted on its ORIGINAL port with --preload_dir
pointed at its periodic snapshot directory (ps_server.PSServer.snapshot:
atomic state_dict pickles), so trainers' retrying RPC clients reconnect
and the job loses at most one snapshot interval of table updates instead
of hanging (the reference launcher only watches trainers; a dead pserver
is a whole-job hang there).

Preemption: SIGTERM to the LAUNCHER is forwarded to every trainer and
the job gets --sigterm_grace seconds to finish its final checkpoints
(fluid/checkpoint.py training loops honor the signal at the next step
boundary) before being terminated — the TPU-pod eviction contract. A
SIGTERM'd TRAINER that checkpointed exits with
checkpoint.PREEMPTED_EXIT_CODE (75); like any nonzero exit it consumes
one --elastic_retries attempt, and the respawned trainer auto-resumes
from the latest valid checkpoint (Model.fit(resume=...)).

Cross-job PS state: when PADDLE_PS_SNAPSHOT_DIR names a STABLE directory
(not this launcher's tempdir), freshly spawned pservers preload from it
on FIRST start too — a new job adopts the previous job's tables (epoch +
generation recorded in the snapshot manifest.json) the way
fleet.init_server(model_dir) does manually.

TPU notes: one process per HOST is the normal topology (all local chips
belong to one PJRT client); --nproc_per_node exists for CPU fleets and
tests. Rendezvous is the JAX coordination service bootstrapped from the
first endpoint (no gen_nccl_id gRPC exchange).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time
from typing import List, Optional


class Trainer:
    def __init__(self, rank: int, endpoint: str, tag: Optional[str] = None):
        self.rank = rank
        self.endpoint = endpoint
        # stable membership identity: ranks are RE-NUMBERED when an
        # elastic resize shrinks the world, tags are not — per-rank
        # restart budgets and the coordinator's lease table key on tags
        self.tag = tag if tag is not None else f"trainer{rank}"
        self.proc: Optional[subprocess.Popen] = None
        self.log = None


class PServer:
    """One supervised pserver child: the respawn identity (idx, host,
    bound port) needed to restart it in place."""

    def __init__(self, idx: int, host: str, port: int,
                 proc: subprocess.Popen):
        self.idx = idx
        self.host = host
        self.port = port  # bound port — respawns MUST rebind it
        self.proc = proc

    @property
    def tag(self) -> str:
        return f"ps{self.idx}"


def get_cluster(ips: List[str], nproc_per_node: int, start_port: int):
    """[(rank, ip:port)] across all nodes (reference utils.get_cluster)."""
    out = []
    rank = 0
    for ip in ips:
        for i in range(nproc_per_node):
            out.append(Trainer(rank, f"{ip}:{start_port + i}"))
            rank += 1
    return out


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn and watch per-node trainer processes",
    )
    p.add_argument("--ips", "--cluster_node_ips", default="127.0.0.1",
                   help="comma-separated node ips (this script runs on each)")
    p.add_argument("--node_ip", default=None,
                   help="this node's ip (default: first of --ips)")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--log_dir", default=None)
    p.add_argument(
        "--elastic_retries", type=int, default=0,
        help="JOB-LEVEL cap on trainer-group restarts (trainers resume "
        "from their own checkpoints; PADDLE_ELASTIC_RESTART carries the "
        "attempt number), and restart budget for dead pservers "
        "(snapshot recovery). 0 = reference behavior: fail fast "
        "(utils.py:407) — unless --elastic_retries_per_rank arms the "
        "control plane on its own",
    )
    p.add_argument(
        "--elastic_retries_per_rank", type=int, default=None,
        help="PER-RANK restart budget (default: = --elastic_retries). "
        "A rank that fails MORE times than its budget is EVICTED from "
        "the membership instead of burning the job: the coordinator "
        "bumps the membership epoch and the surviving ranks restart "
        "from the last checkpoint at the REDUCED world size (elastic "
        "resize; needs PADDLE_ELASTIC_RESHARD-aware checkpoints). A "
        "permanently-lost host therefore costs its own budget, not the "
        "whole fleet's",
    )
    p.add_argument(
        "--min_world_size", type=int, default=1,
        help="abort instead of resizing below this many trainers",
    )
    p.add_argument(
        "--lease_secs", type=float, default=None,
        help="arm the lease-based job control plane "
        "(distributed/coordinator.py): the launcher hosts a membership "
        "coordinator, heartbeat stamps become lease renewals "
        "(PADDLE_COORDINATOR_ENDPOINT / PADDLE_LEASE_SECS exported to "
        "every child), a trainer lease expired for 2 periods is "
        "treated like a hang (kill + per-rank budget), and an expired "
        "PSERVER primary lease promotes a caught-up backup directly — "
        "no client in the loop. Default: PADDLE_LEASE_SECS if set, "
        "else off",
    )
    p.add_argument(
        "--coordinator_standby", action="store_true",
        help="control-plane HA (ISSUE 18): spawn a WARM-STANDBY "
        "coordinator beside the durable primary. The standby follows "
        "the primary's snapshot+WAL stream (repl_pull) and promotes "
        "itself when the primary's incarnation lease lapses; clients "
        "hold the ordered endpoint list (primary,standby) and fail "
        "over, with split-brain fenced by the incarnation number. "
        "Implies the process-hosted durable coordinator (as does "
        "setting PADDLE_COORD_SNAPSHOT_SECS); requires --lease_secs",
    )
    p.add_argument(
        "--straggler_eject_factor", type=float, default=0.0,
        help="EJECT (kill + per-rank budget, reason 'straggler "
        "ejection') a trainer whose step time exceeds this multiple of "
        "the median across ranks — the enforcement sibling of the "
        "diagnosis-only --straggler_factor. 0 = off",
    )
    p.add_argument(
        "--sigterm_grace", type=float, default=30.0,
        help="seconds the job gets to checkpoint after the launcher "
        "receives SIGTERM (forwarded to every trainer; training loops "
        "with a CheckpointManager write a final checkpoint and exit). "
        "After the grace window remaining trainers are terminated",
    )
    p.add_argument(
        "--heartbeat_timeout", type=float, default=0.0,
        help="treat a trainer as hung when its heartbeat file "
        "(distributed/heartbeat.py; stamped by init_parallel_env) goes "
        "stale for this many seconds — catches collective deadlocks that "
        "never exit. 0 = off",
    )
    p.add_argument(
        "--straggler_factor", type=float, default=0.0,
        help="log a structured `straggler` event when a trainer's step "
        "time exceeds this multiple of the median across ranks (step "
        "rates ride the heartbeat stamps; fluid/monitor.py publishes "
        "them automatically). Diagnosis only — the job keeps running. "
        "0 = off",
    )
    p.add_argument(
        "--trace_dir", default=None,
        help="collect per-process traces: trainers record host spans "
        "(PADDLE_TRACE_DIR contract, fluid/profiler.py) and dump "
        "trace.<rank>.json here at exit; causal step tracing "
        "(telemetry/tracing.py) is armed in every child — pservers and "
        "the coordinator dump span lanes + flightrec.<tag>.json flight "
        "records here too (tools/tracetop.py merges those into per-round "
        "critical paths). After the job the launcher merges everything "
        "into <trace_dir>/timeline.json (pid=rank — open in Perfetto / "
        "chrome://tracing)",
    )
    p.add_argument(
        "--fleetz_port", type=int, default=None,
        help="arm the FLEET goodput view (telemetry/goodput.py): "
        "every child classifies its wall-clock into a goodput/badput "
        "ledger (PADDLE_GOODPUT=1) and ships a bounded metrics "
        "snapshot + ledger summary on each lease renewal "
        "(PADDLE_FLEET_METRICS=1); the launcher serves debugz on THIS "
        "port with /fleetz (per-rank rollup, job goodput %%, worst "
        "incidents) and /fleetz/metrics (fleet-wide Prometheus "
        "exposition, per-rank labels — scrape ONE endpoint instead of "
        "N). Implies --lease_secs 5 when the lease plane is off. "
        "Default: PADDLE_FLEETZ_PORT if set, else off",
    )
    p.add_argument(
        "--debugz_port", type=int, default=None,
        help="arm every trainer's live introspection server "
        "(telemetry/debugz.py: /metrics /statusz /steps /proftop "
        "/healthz) with deterministic per-rank ports: rank r serves on "
        "debugz_port + r. Default: PADDLE_DEBUGZ_PORT if set (same "
        "offset rule), else off",
    )
    p.add_argument(
        "--server_num", type=int, default=0,
        help="spawn N local parameter-server processes "
        "(distributed/ps_server.py) on free ports and export "
        "PADDLE_PSERVERS_IP_PORT_LIST to the trainers (reference "
        "launch_ps.py). Servers outlive elastic restarts, so hosted "
        "tables survive a trainer-group respawn",
    )
    p.add_argument(
        "--servers", default="",
        help="explicit pserver endpoint list host:port,... — endpoints "
        "whose host matches this node are spawned here; the full list "
        "is exported to trainers (multi-node PS). Overrides --server_num",
    )
    p.add_argument(
        "--ps_snapshot_secs", type=float, default=None,
        help="pserver snapshot interval (atomic per-table state_dict "
        "pickles a supervised restart recovers from). Default: "
        "PADDLE_PS_SNAPSHOT_SECS if set, else 1.0 when --elastic_retries "
        "> 0 (supervision without snapshots would restart pservers "
        "EMPTY), else 0 (off)",
    )
    p.add_argument(
        "--ps_snapshot_mode", default=None,
        choices=[None, "full", "incremental"],
        help="pserver snapshot format: 'full' rewrites every table each "
        "tick (the default); 'incremental' writes a periodic base plus "
        "checksummed dirty-row delta files — O(touched rows) per tick, "
        "which makes sub-second --ps_snapshot_secs viable on multi-GB "
        "tables. Default: PADDLE_PS_SNAPSHOT_MODE if set, else full",
    )
    p.add_argument(
        "--ps_replication", type=int, default=None,
        help="replication factor R for hosted PS tables: each row "
        "partition gets a primary pserver plus R-1 prefix-consistent "
        "backups on distinct pservers (needs --server_num >= R). "
        "Trainers fail over to a backup when a primary dies — no "
        "respawn-wait — and hedge slow reads to backups; the supervisor "
        "respawn then rejoins via anti-entropy resync. Default: "
        "PADDLE_PS_REPLICATION if set, else 1 (today's unreplicated "
        "data plane)",
    )
    p.add_argument(
        "--serve", action="store_true",
        help="SERVING mode (paddle_tpu.inference.server): the "
        "positional argument is a saved inference-model dir, and each "
        "'trainer' slot runs one serving replica bound to its cluster "
        "endpoint (started_port + rank). The whole supervision stack "
        "applies unchanged — heartbeats, per-rank restart budgets, "
        "elastic respawn, --lease_secs lease renewals (kind="
        "'inference'), SIGTERM graceful drain — and extra args after "
        "the model dir pass through to the server (--max_batch, "
        "--queue_depth, ...)",
    )
    p.add_argument(
        "--serve_kv_cache", choices=["0", "1"], default=None,
        help="serving replicas: force the paged-KV generation path on "
        "(1) or off (0, the r19 padded recompute baseline) — exported "
        "as PADDLE_SERVE_KV_CACHE to every replica",
    )
    p.add_argument(
        "--serve_kv_pages", type=int, default=None,
        help="serving replicas: KV pool size in pages per replica "
        "(PADDLE_SERVE_KV_PAGES; default sizes from the HBM budget)",
    )
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _spawn_pserver(idx: int, host: str, port: int,
                   log_dir: Optional[str] = None,
                   snapshot_root: Optional[str] = None,
                   snapshot_secs: float = 0.0,
                   preload_snapshots: bool = False,
                   heartbeat_dir: Optional[str] = None,
                   log_mode: str = "w",
                   clear_fault_spec: bool = False) -> subprocess.Popen:
    """Fork one pserver child and wait for its bound-port banner; the
    caller learns the bound port via proc.ps_bound_port. Snapshots live
    in a PER-SERVER subdir of snapshot_root — each server hosts its own
    row PARTITION of a table under the same name, and a shared dir would
    let server 1's respawn silently preload server 0's rows whenever the
    partition geometries coincide. Respawns pass the original port and
    preload_snapshots=True (recovery)."""
    env = dict(os.environ)
    env["PADDLE_TRAINING_ROLE"] = "PSERVER"
    env["PADDLE_PS_RANK_TAG"] = f"ps{idx}"
    if clear_fault_spec:
        # a RESPAWNED pserver must not replay the deterministic fault
        # schedule from RPC-count zero — a `kill:*:N` drill means "kill
        # this server once", not "kill every incarnation", which would
        # burn the whole restart budget on one rule
        env.pop("PADDLE_PS_FAULT_SPEC", None)
    if heartbeat_dir:
        env["PADDLE_HEARTBEAT_DIR"] = heartbeat_dir
    snap = os.path.join(snapshot_root, f"ps{idx}") if snapshot_root else None
    cmd = [sys.executable, "-u", "-m",
           "paddle_tpu.distributed.ps_server",
           "--port", str(port), "--host", host]
    if preload_snapshots and snap:
        cmd += ["--preload_dir", snap]
    if snap and snapshot_secs > 0:
        cmd += ["--snapshot_dir", snap,
                "--snapshot_secs", str(snapshot_secs)]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    line = proc.stdout.readline()  # "[ps_server] listening on h:p"
    if "listening on" not in line:
        proc.kill()
        raise RuntimeError(f"pserver {idx} failed to start: {line!r}")
    proc.ps_bound_port = int(line.rsplit(":", 1)[1])
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        log = open(os.path.join(log_dir, f"serverlog.{idx}"), log_mode)
        log.write(line)

        def drain(p=proc, f=log):
            for ln in p.stdout:
                f.write(ln)
            f.close()
    else:
        def drain(p=proc):
            for _ in p.stdout:
                pass

    threading.Thread(target=drain, daemon=True).start()
    return proc


def start_pservers(server_num: int, servers: str, node_ip: str,
                   log_dir: Optional[str] = None,
                   snapshot_dir: Optional[str] = None,
                   snapshot_secs: float = 0.0,
                   heartbeat_dir: Optional[str] = None,
                   adopt_snapshots: bool = False):
    """Spawn this node's pserver processes (reference launch_ps.py
    start_procs). Returns (List[PServer], full_endpoint_list).
    --server_num spawns on launcher-chosen free ports (the child binds
    port 0 and reports the bound port on stdout, so there is no
    pick-then-bind race); --servers spawns the endpoints whose host is
    this node. adopt_snapshots (stable PADDLE_PS_SNAPSHOT_DIR): preload
    each server's snapshot partition on FIRST spawn, not just respawn —
    a new job adopts a previous job's tables."""
    pservers: List[PServer] = []

    def spawn(port: int, host: str, idx: int) -> int:
        proc = _spawn_pserver(idx, host, port, log_dir=log_dir,
                              snapshot_root=snapshot_dir,
                              snapshot_secs=snapshot_secs,
                              preload_snapshots=adopt_snapshots,
                              heartbeat_dir=heartbeat_dir)
        pservers.append(PServer(idx, host, proc.ps_bound_port, proc))
        return proc.ps_bound_port

    try:
        if servers:
            eps = [e.strip() for e in servers.split(",") if e.strip()]
            for i, ep in enumerate(eps):
                host, port = ep.rsplit(":", 1)
                if host in (node_ip, "127.0.0.1", "localhost"):
                    spawn(int(port), host, i)
            endpoints = eps
        else:
            endpoints = []
            for i in range(server_num):
                bound = spawn(0, "127.0.0.1", i)
                endpoints.append(f"127.0.0.1:{bound}")
    except BaseException:
        # partial startup must not orphan the servers already running
        terminate_pservers(pservers)
        raise
    return pservers, endpoints


def terminate_pservers(pservers: List[PServer]):
    for p in pservers:
        if p.proc.poll() is None:
            p.proc.terminate()
    for p in pservers:
        try:
            p.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            p.proc.kill()


class PServerSupervisor:
    """Poll pserver children and respawn the dead ones in place (same
    host:port — trainers hold the endpoint list; their RPC retry loop
    rides out the gap). Recovery state comes from the snapshot dir: the
    respawn preloads the latest atomic snapshot, and trainers that find
    their table missing re-create it (RemoteTable._call), restoring the
    Downpour bounded-staleness contract instead of losing the job.

    A shared restart budget (--elastic_retries) bounds flapping; with
    heartbeats enabled, a pserver process that freezes (stamps stale) is
    killed and handled through the same respawn path."""

    def __init__(self, pservers: List[PServer], retries: int,
                 log_dir: Optional[str], snapshot_dir: Optional[str],
                 snapshot_secs: float, heartbeat_dir: Optional[str] = None,
                 heartbeat_timeout: float = 0.0):
        self.pservers = pservers
        self.retries_left = int(retries)
        self.log_dir = log_dir
        self.snapshot_dir = snapshot_dir
        self.snapshot_secs = snapshot_secs
        self.heartbeat_dir = heartbeat_dir
        self.aborted = False  # budget gone: no point restarting trainers
        self.monitor = None
        if heartbeat_dir and heartbeat_timeout > 0:
            from .heartbeat import HeartBeatMonitor

            self.monitor = HeartBeatMonitor(
                heartbeat_dir, [p.tag for p in pservers], heartbeat_timeout)

    def check(self) -> Optional[int]:
        """None = all healthy (possibly after respawns); an int = abort
        the job with that exit code (restart budget exhausted)."""
        if self.monitor is not None:
            running = [p for p in self.pservers if p.proc.poll() is None]
            stale = set(self.monitor.stale_ranks(
                ranks=[p.tag for p in running]))
            for p in running:
                if p.tag in stale:
                    print(f"[launch] pserver {p.idx} ({p.host}:{p.port}) "
                          f"stopped heartbeating (frozen?); killing it "
                          f"for respawn", file=sys.stderr)
                    p.proc.kill()
                    p.proc.wait()
        for p in self.pservers:
            rc = p.proc.poll()
            if rc is None:
                continue
            if self.retries_left <= 0:
                print(f"[launch] pserver {p.idx} ({p.host}:{p.port}) "
                      f"exited with {rc} and no restarts remain; "
                      f"aborting the job", file=sys.stderr)
                self.aborted = True
                return rc if rc != 0 else 1
            self.retries_left -= 1
            print(f"[launch] pserver {p.idx} ({p.host}:{p.port}) exited "
                  f"with {rc}; restarting it on the same port "
                  f"(snapshot recovery, {self.retries_left} restarts "
                  f"left)", file=sys.stderr)
            try:
                p.proc = _spawn_pserver(
                    p.idx, p.host, p.port, log_dir=self.log_dir,
                    snapshot_root=self.snapshot_dir,
                    snapshot_secs=self.snapshot_secs,
                    preload_snapshots=True,
                    heartbeat_dir=self.heartbeat_dir, log_mode="a",
                    clear_fault_spec=True)
            except RuntimeError as e:
                print(f"[launch] pserver {p.idx} respawn failed: {e}; "
                      f"aborting the job", file=sys.stderr)
                self.aborted = True
                return 1
        return None


def _spawn_coordinator(host: str, port: int, state_dir: Optional[str],
                       lease_secs: float, per_rank: int,
                       snapshot_secs: float,
                       log_dir: Optional[str] = None,
                       standby_of: Optional[str] = None,
                       log_mode: str = "w",
                       clear_fault_spec: bool = False) -> subprocess.Popen:
    """Fork one process-hosted coordinator (durable control plane,
    ISSUE 18) and wait for its bound-port banner — the _spawn_pserver
    idiom: first spawns bind port 0 and report the bound port; respawns
    pass the original port so clients reconnect in place. The caller
    learns the port via proc.coord_bound_port."""
    env = dict(os.environ)
    role = "standby" if standby_of else "primary"
    # fault tag-scoping identity: PADDLE_PS_FAULT_TAGS=coord arms kill/
    # crash rules in the PRIMARY only (the standby answers to
    # coord-standby)
    env["PADDLE_PS_RANK_TAG"] = ("coord-standby" if standby_of
                                 else "coord")
    # the coordinator must not hold a lease on itself
    env.pop("PADDLE_COORDINATOR_ENDPOINT", None)
    env.pop("PADDLE_CKPT_BARRIER_ENDPOINT", None)
    if clear_fault_spec:
        # same rule as pserver respawns: a `crash:coord_verb:N` drill
        # means "crash the coordinator once", not every incarnation
        env.pop("PADDLE_PS_FAULT_SPEC", None)
    cmd = [sys.executable, "-u", "-m",
           "paddle_tpu.distributed.coordinator",
           "--host", host, "--port", str(port),
           "--lease_secs", str(lease_secs),
           "--retries_per_rank", str(per_rank),
           "--snapshot_secs", str(snapshot_secs)]
    if state_dir:
        cmd += ["--state_dir", state_dir]
    if standby_of:
        cmd += ["--standby_of", standby_of]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    line = proc.stdout.readline()  # "[coordinator] listening on h:p"
    if "listening on" not in line:
        proc.kill()
        raise RuntimeError(
            f"{role} coordinator failed to start: {line!r}")
    proc.coord_bound_port = int(line.rsplit(":", 1)[1])
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        log = open(os.path.join(log_dir, f"coordlog.{role}"), log_mode)
        log.write(line)

        def drain(p=proc, f=log):
            for ln in p.stdout:
                f.write(ln)
            f.close()
    else:
        def drain(p=proc):
            for _ in p.stdout:
                pass

    threading.Thread(target=drain, daemon=True).start()
    return proc


class CoordinatorSupervisor:
    """Respawn a dead process-hosted coordinator in place — same port,
    same state dir, so the durable snapshot+WAL make the respawn resume
    exactly where the dead one stopped (bumped incarnation,
    reconciliation window armed). The budget is --elastic_retries, like
    the pserver supervisor — but unlike a pserver, a coordinator dead
    past its budget does NOT abort the job: the data plane keeps
    training in grace mode, and a warm standby (when armed) promotes
    itself."""

    def __init__(self, children: dict, retries: int, ledger=None):
        # children: role -> spawn record (proc + the _spawn_coordinator
        # kwargs needed to respawn it in place)
        self.children = children
        self.retries_left = int(retries)
        self.ledger = ledger

    def check(self) -> None:
        for role, ent in self.children.items():
            proc = ent.get("proc")
            if proc is None or proc.poll() is None:
                continue
            rc = proc.poll()
            detect_ts = time.time()
            if self.retries_left <= 0:
                if not ent.get("dead_reported"):
                    ent["dead_reported"] = True
                    print(f"[launch] {role} coordinator exited with "
                          f"{rc} and no restarts remain; clients stay "
                          f"in grace mode"
                          + (" (warm standby will promote itself)"
                             if len(self.children) > 1
                             and role == "primary" else ""),
                          file=sys.stderr)
                ent["proc"] = None
                continue
            self.retries_left -= 1
            print(f"[launch] {role} coordinator (port {ent['port']}) "
                  f"exited with {rc}; respawning on the same port from "
                  f"its durable state ({self.retries_left} restarts "
                  f"left)", file=sys.stderr)
            try:
                ent["proc"] = _spawn_coordinator(
                    ent["host"], ent["port"], ent["state_dir"],
                    ent["lease_secs"], ent["per_rank"],
                    ent["snapshot_secs"], log_dir=ent.get("log_dir"),
                    standby_of=ent.get("standby_of"), log_mode="a",
                    clear_fault_spec=True)
            except RuntimeError as e:
                print(f"[launch] {role} coordinator respawn failed: "
                      f"{e}; clients stay in grace mode",
                      file=sys.stderr)
                ent["proc"] = None
                continue
            if self.ledger is not None:
                try:
                    self.ledger.event(
                        event="coord_respawn", role=role, rc=rc,
                        detect_ts=round(detect_ts, 6),
                        respawn_ts=round(time.time(), 6))
                except Exception:  # noqa: BLE001 — accounting only
                    pass


class SigtermGrace:
    """Launcher-side preemption protocol: on SIGTERM, forward the signal
    to every live trainer (their training loops checkpoint and exit) and
    give the group `grace_secs` to drain before the watcher terminates
    whatever is left. install() chains any previous handler; trainers
    are registered per elastic attempt."""

    def __init__(self, grace_secs: float):
        self.grace_secs = float(grace_secs)
        self.requested = threading.Event()
        self.deadline: Optional[float] = None
        self.trainers: List[Trainer] = []

    def install(self) -> bool:
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _handler(sig, frame):
                self.requested.set()
                self.deadline = time.time() + self.grace_secs
                print("[launch] SIGTERM: forwarding to trainers for a "
                      f"final checkpoint ({self.grace_secs}s grace)",
                      file=sys.stderr)
                for t in self.trainers:
                    if t.proc is not None and t.proc.poll() is None:
                        try:
                            t.proc.send_signal(signal.SIGTERM)
                        except OSError:
                            pass
                if callable(prev) and prev not in (signal.SIG_IGN,
                                                   signal.SIG_DFL):
                    prev(sig, frame)

            signal.signal(signal.SIGTERM, _handler)
            return True
        except ValueError:  # not the main thread (tests calling launch())
            return False

    def expired(self) -> bool:
        return self.deadline is not None and time.time() > self.deadline


def start_local_trainers(cluster: List[Trainer], node_ip: str, script: str,
                         script_args: List[str], log_dir: Optional[str],
                         restart_count: int = 0,
                         heartbeat_dir: Optional[str] = None,
                         debugz_base_port: Optional[int] = None,
                         membership_epoch: int = 0,
                         module: Optional[str] = None,
                         only_tags=None):
    """Fork this node's trainers with the env protocol (reference
    utils.start_local_trainers:340). debugz_base_port arms each rank's
    introspection server on base + rank (deterministic: operators and
    scrape configs can address any rank's /metrics without discovery).
    PADDLE_TRAINER_TAG carries the stable membership identity and
    PADDLE_MEMBERSHIP_EPOCH the coordinator's membership epoch — both
    survive resizes where the rank numbering does not."""
    endpoints = ",".join(t.endpoint for t in cluster)
    local = [t for t in cluster if t.endpoint.split(":")[0] == node_ip]
    if only_tags is not None:
        # per-replica respawn (--serve): spawn ONLY the named members,
        # with the env protocol still derived from the full cluster
        local = [t for t in local if t.tag in only_tags]
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    for t in local:
        env = dict(os.environ)
        env.update(
            PADDLE_TRAINER_ID=str(t.rank),
            PADDLE_TRAINERS_NUM=str(len(cluster)),
            PADDLE_TRAINER_ENDPOINTS=endpoints,
            PADDLE_CURRENT_ENDPOINT=t.endpoint,
            PADDLE_ELASTIC_RESTART=str(restart_count),
            PADDLE_TRAINER_TAG=t.tag,
            PADDLE_MEMBERSHIP_EPOCH=str(membership_epoch),
        )
        if debugz_base_port is not None:
            env["PADDLE_DEBUGZ_PORT"] = str(debugz_base_port + t.rank)
        if heartbeat_dir:
            env["PADDLE_HEARTBEAT_DIR"] = heartbeat_dir
        # module mode (launch --serve): run `-m <module>` instead of a
        # script file — the serving replica binds its cluster endpoint's
        # port via PADDLE_CURRENT_ENDPOINT
        if module is not None:
            cmd = [sys.executable, "-u", "-m", module] + list(script_args)
        else:
            cmd = [sys.executable, "-u", script] + list(script_args)
        if log_dir:
            mode = "a" if restart_count else "w"
            t.log = open(os.path.join(log_dir, f"workerlog.{t.rank}"), mode)
            t.proc = subprocess.Popen(cmd, env=env, stdout=t.log,
                                      stderr=subprocess.STDOUT)
        else:
            t.proc = subprocess.Popen(cmd, env=env)
    return local


def terminate_local_trainers(trainers: List[Trainer]):
    for t in trainers:
        if t.proc and t.proc.poll() is None:
            t.proc.terminate()
    deadline = time.time() + 5
    for t in trainers:
        if not t.proc:
            continue
        while t.proc.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if t.proc.poll() is None:
            t.proc.kill()
    for t in trainers:
        if t.log:
            t.log.close()


class ServeRespawner:
    """Per-replica supervision for launch --serve: serving replicas are
    INDEPENDENT — one dying must never blip the rest of the fleet, so
    (unlike sync training, where the barrier demands a group restart) a
    dead replica is respawned IN PLACE on its original endpoint, budget
    `--elastic_retries` per replica. Past budget the death falls through
    to the group-abort path so the job still ends loudly."""

    def __init__(self, cluster: List[Trainer], node_ip: str, script: str,
                 script_args: List[str], log_dir: Optional[str],
                 retries: int, heartbeat_dir: Optional[str] = None,
                 debugz_base_port: Optional[int] = None,
                 membership_epoch: int = 0,
                 module: Optional[str] = None):
        self.cluster = cluster
        self.node_ip = node_ip
        self.script = script
        self.script_args = list(script_args)
        self.log_dir = log_dir
        self.retries = int(retries)
        self.heartbeat_dir = heartbeat_dir
        self.debugz_base_port = debugz_base_port
        self.membership_epoch = membership_epoch
        self.module = module
        self._counts: dict = {}

    def respawn(self, t: Trainer) -> bool:
        n = self._counts.get(t.tag, 0)
        if n >= self.retries:
            return False
        self._counts[t.tag] = n + 1
        print(f"[launch] serving replica {t.rank} ({t.tag}, "
              f"{t.endpoint}) died; respawning in place "
              f"({n + 1}/{self.retries}); the rest of the fleet keeps "
              f"serving", file=sys.stderr, flush=True)
        start_local_trainers(
            self.cluster, self.node_ip, self.script, self.script_args,
            self.log_dir, restart_count=n + 1,
            heartbeat_dir=self.heartbeat_dir,
            debugz_base_port=self.debugz_base_port,
            membership_epoch=self.membership_epoch, module=self.module,
            only_tags={t.tag})
        return True


def watch_local_trainers(trainers: List[Trainer], poll_interval=0.2,
                         monitor=None, ps_supervisor=None,
                         grace: Optional[SigtermGrace] = None,
                         straggler=None, failure: Optional[dict] = None,
                         coordinator=None, straggler_eject=False,
                         serve_respawner: Optional[ServeRespawner] = None,
                         fleet_ledger=None, incident_coord=None,
                         coord_supervisor=None,
                         ) -> int:
    """Block until all trainers exit. Any nonzero exit — or a stale
    heartbeat when `monitor` (heartbeat.HeartBeatMonitor) is given —
    aborts the whole local group (reference watch_local_trainers:407:
    fail fast; heartbeat parity: heart_beat_monitor.h:54). A
    `ps_supervisor` (PServerSupervisor) is polled on the same cadence:
    it respawns dead pservers in place, or returns an exit code to abort
    with when the restart budget is gone. Under a SIGTERM `grace` the
    watcher waits for the (already signaled) trainers to finish their
    final checkpoints, terminating stragglers when the grace window
    expires, and reports 128+SIGTERM. Returns the job's exit code.

    `failure` (out-param dict) receives {"trainer", "tag", "reason"}
    for the trainer whose death ended the watch — the attempts loop
    charges the right PER-RANK budget and names the culprit in the
    restart line. `coordinator` (coordinator.Coordinator) is swept on
    the poll cadence: an expired TRAINER lease is treated like a hang
    (kill + reason "lease expired"), and expired PSERVER primary
    leases trigger backup promotion inside the sweep. With
    `straggler_eject`, a straggler event kills the dragging rank
    (reason "straggler ejection") instead of only logging it."""

    def _fail(t: Optional[Trainer], reason: str) -> None:
        if failure is not None and t is not None:
            failure.update(trainer=t, tag=t.tag, rank=t.rank,
                           reason=reason)

    try:
        while True:
            if grace is not None and grace.requested.is_set():
                # preemption drain: children got SIGTERM from the grace
                # handler; each checkpoints and exits on its own
                while (any(t.proc.poll() is None for t in trainers)
                       and not grace.expired()):
                    time.sleep(poll_interval)
                terminate_local_trainers(trainers)
                return 128 + signal.SIGTERM
            alive = False
            for t in trainers:
                rc = t.proc.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    if serve_respawner is not None \
                            and serve_respawner.respawn(t):
                        alive = True  # replaced in place; fleet serves on
                        continue
                    print(
                        f"[launch] trainer {t.rank} ({t.tag}, "
                        f"{t.endpoint}) exited with {rc}; aborting the "
                        f"job",
                        file=sys.stderr,
                    )
                    _fail(t, f"nonzero exit (code {rc})")
                    terminate_local_trainers(trainers)
                    return rc
            if not alive:
                return 0
            if monitor is not None:
                running = [t.rank for t in trainers if t.proc.poll() is None]
                stale = monitor.stale_ranks(ranks=running)
                if stale:
                    print(
                        f"[launch] trainer rank(s) {stale} stopped "
                        f"heartbeating for >{monitor.timeout}s (hang?); "
                        f"aborting the group",
                        file=sys.stderr,
                    )
                    culprit = next((t for t in trainers
                                    if t.rank in stale), None)
                    _fail(culprit, "heartbeat stale (hang)")
                    terminate_local_trainers(trainers)
                    return 124  # timeout-style exit code
            if coordinator is not None:
                # lease plane: sweep expiries (and pserver primary
                # elections) on the watch cadence, then react to
                # expired TRAINER leases exactly like stale heartbeats
                events = coordinator.sweep()
                running_tags = {t.tag: t for t in trainers
                                if t.proc.poll() is None}
                for ev in events:
                    if (ev.get("event") == "lease_expired"
                            and ev.get("kind") == "trainer"
                            and ev.get("tag") in running_tags):
                        t = running_tags[ev["tag"]]
                        print(f"[launch] trainer {t.rank} ({t.tag}) "
                              f"lease expired ({ev.get('overdue_s')}s "
                              f"overdue — renewals stopped); killing "
                              f"the group", file=sys.stderr)
                        _fail(t, "lease expired (no renewals)")
                        terminate_local_trainers(trainers)
                        return 124
            if straggler is not None:
                # one structured JSON line per episode
                # (heartbeat.StragglerMonitor); diagnosis by default,
                # ejection when the eject factor armed this watch
                from ..telemetry.straggler import format_event

                for ev in straggler.poll():
                    print(format_event(ev), file=sys.stderr, flush=True)
                    # goodput (ISSUE 15): a straggler episode is badput
                    # — one `stall` event in the launcher ledger (with
                    # the culprit's step trace_id, the same hop tracetop
                    # blames) and the coordinator's incident ring
                    culprit_tag = next(
                        (t.tag for t in trainers
                         if str(t.rank) == str(ev.get("rank"))), None)
                    stall_ev = {
                        "event": "stall", "rank": ev.get("rank"),
                        "tag": culprit_tag, "step": ev.get("step"),
                        "excess_ms": ev.get("excess_ms"),
                        "slowdown": ev.get("slowdown"),
                        "cause": ev.get("cause", "compute"),
                        "trace_id": ev.get("trace_id"),
                    }
                    if fleet_ledger is not None:
                        fleet_ledger.event(**stall_ev)
                    if incident_coord is not None:
                        try:
                            incident_coord.note_incident(stall_ev)
                        except Exception:  # noqa: BLE001 — accounting
                            pass
                    if straggler_eject:
                        culprit = next(
                            (t for t in trainers
                             if str(t.rank) == str(ev.get("rank"))
                             and t.proc.poll() is None), None)
                        if culprit is not None:
                            print(f"[launch] trainer {culprit.rank} "
                                  f"({culprit.tag}) ejected as a "
                                  f"straggler", file=sys.stderr)
                            _fail(culprit, "straggler ejection")
                            terminate_local_trainers(trainers)
                            return 124
            if ps_supervisor is not None:
                rc = ps_supervisor.check()
                if rc is not None:
                    terminate_local_trainers(trainers)
                    return rc
            if coord_supervisor is not None:
                # durable control plane (ISSUE 18): respawn a dead
                # coordinator in place; never aborts the job
                coord_supervisor.check()
            time.sleep(poll_interval)
    except KeyboardInterrupt:
        terminate_local_trainers(trainers)
        return 128 + signal.SIGINT


def launch(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    ips = [s.strip() for s in args.ips.split(",") if s.strip()]
    node_ip = args.node_ip or ips[0]
    cluster = get_cluster(ips, args.nproc_per_node, args.started_port)

    # lease plane (--lease_secs / PADDLE_LEASE_SECS): the launcher hosts
    # the membership coordinator and every child renews a lease on it
    lease_secs = args.lease_secs
    if lease_secs is None:
        try:
            lease_secs = float(os.environ.get("PADDLE_LEASE_SECS", 0) or 0)
        except ValueError:
            lease_secs = 0.0

    # fleet goodput view (--fleetz_port / PADDLE_FLEETZ_PORT): ledger in
    # every child, bounded snapshots on renewals, one launcher-side
    # scrape endpoint. Rides the lease plane — renewals ARE the push
    # channel — so arming it arms leases too
    fleetz_port = args.fleetz_port
    if fleetz_port is None:
        raw = os.environ.get("PADDLE_FLEETZ_PORT")
        if raw:
            try:
                fleetz_port = int(raw)
            except ValueError:
                fleetz_port = None
    if fleetz_port is not None:
        if lease_secs <= 0:
            lease_secs = 5.0
            print("[launch] --fleetz_port arms the lease plane "
                  "(renewals carry the fleet payloads); defaulting "
                  "--lease_secs 5", file=sys.stderr)
        # children inherit through the spawn env copies
        os.environ["PADDLE_GOODPUT"] = "1"
        os.environ["PADDLE_FLEET_METRICS"] = "1"

    heartbeat_dir = None
    own_heartbeat_dir = False
    # straggler detection and lease renewals ride the same heartbeat
    # channel (stamps carry step counts and double as renewals), so any
    # of these flags provisions the directory
    if (args.heartbeat_timeout > 0 or args.straggler_factor > 0
            or args.straggler_eject_factor > 0 or lease_secs > 0):
        heartbeat_dir = os.environ.get("PADDLE_HEARTBEAT_DIR")
        if not heartbeat_dir:
            import tempfile

            heartbeat_dir = tempfile.mkdtemp(prefix="paddle_tpu_hb_")
            own_heartbeat_dir = True

    if args.trace_dir:
        os.makedirs(args.trace_dir, exist_ok=True)
        # trainers inherit it via start_local_trainers' env copy and
        # auto-dump per-rank traces (profiler.maybe_start_trace_collection)
        os.environ["PADDLE_TRACE_DIR"] = args.trace_dir
        # --trace_dir is an explicit observability opt-in: arm causal
        # span tracing (telemetry/tracing.py) in every child AND this
        # launcher (the coordinator's lane) unless the operator pinned
        # it off; the flight recorder then dumps per-process spans here
        os.environ.setdefault("PADDLE_TRACING", "1")

    # snapshot interval: explicit flag > env > supervision-implied default
    snapshot_secs = args.ps_snapshot_secs
    if snapshot_secs is None:
        env_secs = os.environ.get("PADDLE_PS_SNAPSHOT_SECS")
        if env_secs:
            snapshot_secs = float(env_secs)
        else:
            snapshot_secs = 1.0 if args.elastic_retries > 0 else 0.0

    grace = SigtermGrace(args.sigterm_grace)
    grace.install()

    # the job control plane: the coordinator owns membership, epochs and
    # per-rank budgets whenever elastic supervision is on; it is SERVED
    # over TCP (lease renewals) only when --lease_secs arms leases.
    # DURABLE mode (ISSUE 18 — PADDLE_COORD_SNAPSHOT_SECS set, or
    # --coordinator_standby): the coordinator moves OUT of the launcher
    # into a supervised child process with snapshot+WAL state, and the
    # launcher talks to it through CoordinatorProxy; neither armed =
    # the in-process coordinator, byte-identical on the wire
    from .coordinator import (Coordinator, CoordinatorProxy,
                              serve_coordinator, stop_coordinator)

    per_rank = (args.elastic_retries_per_rank
                if args.elastic_retries_per_rank is not None
                else args.elastic_retries)
    durable_snap_secs = None
    raw_snap = os.environ.get("PADDLE_COORD_SNAPSHOT_SECS")
    if raw_snap:
        try:
            durable_snap_secs = float(raw_snap)
        except ValueError:
            durable_snap_secs = None
    durable_coord = lease_secs > 0 and (durable_snap_secs is not None
                                        or args.coordinator_standby)
    if args.coordinator_standby and lease_secs <= 0:
        print("[launch] --coordinator_standby needs the lease plane; "
              "arm it with --lease_secs", file=sys.stderr)
        return 2
    coord_server = None
    coord_children = None
    own_coord_state = False
    coord_state_root = None
    coord_ep = None
    if durable_coord:
        snap_secs = (durable_snap_secs
                     if durable_snap_secs is not None else 1.0)
        if args.log_dir:
            coord_state_root = os.path.join(args.log_dir, "coord_state")
        else:
            import tempfile

            coord_state_root = tempfile.mkdtemp(
                prefix="paddle_tpu_coord_")
            own_coord_state = True
        os.makedirs(coord_state_root, exist_ok=True)
        primary_state = os.path.join(coord_state_root, "primary")
        primary = _spawn_coordinator(
            "127.0.0.1", 0, primary_state, lease_secs, per_rank,
            snap_secs, log_dir=args.log_dir)
        primary_ep = f"127.0.0.1:{primary.coord_bound_port}"
        coord_children = {"primary": {
            "proc": primary, "host": "127.0.0.1",
            "port": primary.coord_bound_port,
            "state_dir": primary_state, "lease_secs": lease_secs,
            "per_rank": per_rank, "snapshot_secs": snap_secs,
            "log_dir": args.log_dir, "standby_of": None}}
        endpoints = [primary_ep]
        if args.coordinator_standby:
            standby_state = os.path.join(coord_state_root, "standby")
            standby = _spawn_coordinator(
                "127.0.0.1", 0, standby_state, lease_secs, per_rank,
                snap_secs, log_dir=args.log_dir, standby_of=primary_ep)
            coord_children["standby"] = {
                "proc": standby, "host": "127.0.0.1",
                "port": standby.coord_bound_port,
                "state_dir": standby_state, "lease_secs": lease_secs,
                "per_rank": per_rank, "snapshot_secs": snap_secs,
                "log_dir": args.log_dir, "standby_of": primary_ep}
            endpoints.append(f"127.0.0.1:{standby.coord_bound_port}")
        coord_ep = ",".join(endpoints)
        # children inherit the ORDERED list through the spawn env copies
        os.environ["PADDLE_COORDINATOR_ENDPOINT"] = coord_ep
        os.environ["PADDLE_LEASE_SECS"] = str(lease_secs)
        coord = CoordinatorProxy(coord_ep, lease_secs, per_rank)
        print(f"[launch] durable job coordinator on {coord_ep} (lease "
              f"{lease_secs}s, per-rank budget {per_rank}, snapshots "
              f"every {snap_secs}s"
              + (", warm standby" if args.coordinator_standby else "")
              + ")", file=sys.stderr)
    else:
        coord = Coordinator(lease_secs=lease_secs or 5.0,
                            retries_per_rank=per_rank)
        if lease_secs > 0:
            coord_server, coord_ep = serve_coordinator(coord)
            # children inherit both through the spawn env copies
            os.environ["PADDLE_COORDINATOR_ENDPOINT"] = coord_ep
            os.environ["PADDLE_LEASE_SECS"] = str(lease_secs)
            print(f"[launch] job coordinator on {coord_ep} (lease "
                  f"{lease_secs}s, per-rank budget {per_rank})",
                  file=sys.stderr)

    # goodput ledgers (PADDLE_GOODPUT, armed by --fleetz_port or set by
    # the operator): children persist per-incarnation interval files and
    # the launcher keeps a lifecycle ledger (restart detect/respawn
    # timestamps, straggler stalls) goodtop stitches them with
    fleet_ledger = None
    fleet_exporter = None
    goodput_armed = os.environ.get("PADDLE_GOODPUT", "") not in (
        "", "0", "false")
    if goodput_armed:
        goodput_dir = (os.environ.get("PADDLE_GOODPUT_DIR")
                       or os.environ.get("PADDLE_TRACE_DIR"))
        if not goodput_dir and args.log_dir:
            goodput_dir = os.path.join(args.log_dir, "goodput")
        if goodput_dir:
            os.makedirs(goodput_dir, exist_ok=True)
            # children inherit it through the spawn env copies
            os.environ["PADDLE_GOODPUT_DIR"] = goodput_dir
            from ..telemetry.goodput import LauncherLedger

            fleet_ledger = LauncherLedger(goodput_dir)
            fleet_ledger.event(event="job_start", world=len(cluster),
                               tags=[t.tag for t in cluster],
                               lease_secs=lease_secs)
    if durable_coord:
        # the proxy records coord_outage windows into the same ledger
        # goodtop stitches (distinct from rank-death restarts)
        coord.ledger = fleet_ledger
    if fleetz_port is not None:
        from ..telemetry import debugz as _debugz

        try:
            fleet_srv = _debugz.serve(fleetz_port)
            print(f"[launch] fleet view on port "
                  f"{fleet_srv.server_address[1]}: /fleetz (rollup), "
                  f"/fleetz/metrics (one-endpoint Prometheus scrape)",
                  file=sys.stderr)
        except OSError as e:
            print(f"[launch] could not bind --fleetz_port {fleetz_port}:"
                  f" {e}; fleet view disabled", file=sys.stderr)
        # fleet-wide push (ISSUE 15 satellite): ONE aggregated POST from
        # the coordinator per interval instead of N per-rank pushes —
        # the URL is consumed here so children never see it (per-rank
        # mode unchanged when fleet aggregation is not armed)
        push_url = os.environ.pop("PADDLE_METRICS_PUSH_URL", None)
        if push_url:
            from ..telemetry import export as _export

            fleet_exporter = _export.start_fleet(
                push_url, coord.fleet_status, coord.fleet_metrics,
                interval_s=float(os.environ.get(
                    "PADDLE_METRICS_PUSH_SECS", "15") or 15),
                retries=int(os.environ.get(
                    "PADDLE_METRICS_PUSH_RETRIES", "3") or 3))
            print(f"[launch] fleet metrics push -> {push_url} "
                  f"(aggregated; per-rank pushes suppressed)",
                  file=sys.stderr)

    # sharded-checkpoint commit barrier (fluid/checkpoint.py): every
    # multi-rank job gets one — it costs a daemon thread and only
    # matters once PADDLE_CKPT_SHARDED arms sharded saves in the
    # trainers. Lease-armed jobs reach it through the coordinator's
    # port (ckpt_* verbs delegate); otherwise the coordinator's barrier
    # object is served standalone
    ckpt_barrier_server = None
    if len(cluster) > 1:
        if durable_coord:
            # the barrier rides the durable coordinator's port(s): the
            # ORDERED endpoint list makes a mid-flight sharded
            # checkpoint survive a coordinator respawn or promotion
            os.environ["PADDLE_CKPT_BARRIER_ENDPOINT"] = coord_ep
        elif coord_server is not None:
            os.environ["PADDLE_CKPT_BARRIER_ENDPOINT"] = coord_ep
        else:
            from .coordinator import serve_ckpt_barrier

            ckpt_barrier_server, bar_ep = serve_ckpt_barrier(
                coord.ckpt_barrier)
            os.environ["PADDLE_CKPT_BARRIER_ENDPOINT"] = bar_ep

    pservers: List[PServer] = []
    ps_supervisor = None
    snapshot_dir = None
    own_snapshot_dir = False
    adopt_snapshots = False
    if args.ps_snapshot_mode:
        # pservers inherit it through _spawn_pserver's env copy
        os.environ["PADDLE_PS_SNAPSHOT_MODE"] = args.ps_snapshot_mode
    if args.ps_replication is not None:
        if args.ps_replication > 1:
            if args.servers:
                n_ps = len([e for e in args.servers.split(",")
                            if e.strip()])
            else:
                n_ps = args.server_num
            if n_ps < args.ps_replication:
                print(f"[launch] --ps_replication {args.ps_replication} "
                      f"needs at least that many pservers, got {n_ps} "
                      f"(--server_num / --servers)", file=sys.stderr)
                return 2
        # trainers inherit it through start_local_trainers' env copy;
        # RemoteTable reads it as the default replication factor
        os.environ["PADDLE_PS_REPLICATION"] = str(args.ps_replication)

    try:
        if args.server_num or args.servers:
            if snapshot_secs > 0:
                snapshot_dir = os.environ.get("PADDLE_PS_SNAPSHOT_DIR")
                if snapshot_dir:
                    # stable cross-job dir: a previous job's snapshots
                    # (+ manifest) are adopted on first spawn
                    adopt_snapshots = True
                else:
                    if args.log_dir:
                        snapshot_dir = os.path.join(
                            args.log_dir, "ps_snapshots")
                    else:
                        import tempfile

                        snapshot_dir = tempfile.mkdtemp(
                            prefix="paddle_tpu_ps_")
                        own_snapshot_dir = True
                os.makedirs(snapshot_dir, exist_ok=True)
            pservers, endpoints = start_pservers(
                args.server_num, args.servers, node_ip, args.log_dir,
                snapshot_dir=snapshot_dir, snapshot_secs=snapshot_secs,
                heartbeat_dir=heartbeat_dir,
                adopt_snapshots=adopt_snapshots)
            # trainers inherit the list through start_local_trainers' env
            os.environ["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(endpoints)
            os.environ.setdefault("PADDLE_TRAINING_ROLE", "TRAINER")
            if args.elastic_retries > 0:
                ps_supervisor = PServerSupervisor(
                    pservers, args.elastic_retries, args.log_dir,
                    snapshot_dir, snapshot_secs,
                    heartbeat_dir=heartbeat_dir,
                    heartbeat_timeout=args.heartbeat_timeout)
        coord_supervisor = None
        if coord_children is not None:
            coord_supervisor = CoordinatorSupervisor(
                coord_children, args.elastic_retries,
                ledger=fleet_ledger)
        rc = _launch_attempts(args, ips, node_ip, cluster, heartbeat_dir,
                              ps_supervisor, grace, coord=coord,
                              lease_armed=lease_secs > 0,
                              fleet_ledger=fleet_ledger,
                              coord_supervisor=coord_supervisor)
        if args.trace_dir:
            # pservers dump their span timelines on SIGTERM — stop them
            # BEFORE the merge so timeline.json spans the whole job
            # (trainer ranks + pserver + coordinator lanes)
            terminate_pservers(pservers)
            pservers = []
            try:
                from ..telemetry import tracing as _tracing

                # the coordinator serves inside THIS process: its
                # renewal/election spans live in the launcher's ring
                _tracing.dump_chrome(directory=args.trace_dir,
                                     tag="coord")
                _tracing.flight_dump("exit", directory=args.trace_dir,
                                     tag="coord")
            except Exception:  # noqa: BLE001 — merge anyway
                pass
            from ..telemetry.timeline import merge_traces

            merged = merge_traces(args.trace_dir)
            if merged:
                print(f"[launch] merged timeline: {merged} (open in "
                      f"Perfetto / chrome://tracing)", file=sys.stderr)
        return rc
    finally:
        terminate_pservers(pservers)
        if fleet_exporter is not None:
            try:
                fleet_exporter.stop(final_flush=True)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if coord_server is not None:
            stop_coordinator(coord_server)
        if ckpt_barrier_server is not None:
            stop_coordinator(ckpt_barrier_server)  # same teardown shape
        if coord_children is not None:
            # SIGTERM = graceful: the coordinator writes a final
            # snapshot, so a follow-up job adopting the state dir
            # restarts lossless
            for ent in coord_children.values():
                p = ent.get("proc")
                if p is not None and p.poll() is None:
                    p.terminate()
            for ent in coord_children.values():
                p = ent.get("proc")
                if p is not None:
                    try:
                        p.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        p.kill()
            try:
                coord.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if own_coord_state:
            import shutil

            shutil.rmtree(coord_state_root, ignore_errors=True)
        if own_heartbeat_dir:
            import shutil

            shutil.rmtree(heartbeat_dir, ignore_errors=True)
        if own_snapshot_dir:
            import shutil

            shutil.rmtree(snapshot_dir, ignore_errors=True)


def _launch_attempts(args, ips, node_ip, cluster, heartbeat_dir,
                     ps_supervisor=None, grace=None, coord=None,
                     lease_armed=False, fleet_ledger=None,
                     coord_supervisor=None) -> int:
    """Supervision loop with per-rank budgets and elastic resize.

    Failure accounting lives in the coordinator: every group-ending
    trainer failure (nonzero exit, stale heartbeat, expired lease,
    straggler ejection) is charged to THAT member's per-rank budget
    (coordinator.report_failure). Within budget, the group restarts at
    the same world size (the sync-PS barrier demands a group restart
    either way); past budget the member is EVICTED — the membership
    epoch bumps and the survivors restart at world-1 from the last
    checkpoint (PADDLE_ELASTIC_RESHARD=1 is exported so their
    CheckpointManagers accept the resized resume). --elastic_retries
    stays the JOB-LEVEL restart cap."""
    debugz_base = args.debugz_port
    if debugz_base is None:
        raw = os.environ.get("PADDLE_DEBUGZ_PORT")
        if raw:
            try:
                debugz_base = int(raw)
            except ValueError:
                debugz_base = None
    # serving mode: each rank is one inference replica; the positional
    # arg is the model dir, extra args pass through to the server
    serve_module = None
    serve_args: List[str] = []
    if getattr(args, "serve", False):
        serve_module = "paddle_tpu.inference.server"
        serve_args = (["--model_dir", args.training_script]
                      + list(args.training_script_args))
        # KV-pool knobs ride the env protocol into every replica (the
        # same PADDLE_SERVE_* envs an operator would set by hand)
        if getattr(args, "serve_kv_cache", None) is not None:
            os.environ["PADDLE_SERVE_KV_CACHE"] = args.serve_kv_cache
        if getattr(args, "serve_kv_pages", None) is not None:
            os.environ["PADDLE_SERVE_KV_PAGES"] = str(
                args.serve_kv_pages)
        print(f"[launch] serving replicas: "
              f"{','.join(t.endpoint for t in cluster)}",
              file=sys.stderr)
    elastic_enabled = (args.elastic_retries > 0
                       or args.elastic_retries_per_rank is not None)
    # job-level cap: --elastic_retries when given; with only per-rank
    # budgets, a generous derived bound (every rank exhausting its own
    # budget plus its eviction restart)
    per_rank = (args.elastic_retries_per_rank
                if args.elastic_retries_per_rank is not None
                else args.elastic_retries)
    job_cap = (args.elastic_retries if args.elastic_retries > 0
               else (per_rank + 1) * len(cluster))
    trainers = list(cluster)  # survivors, re-ranked on resize
    attempt = 0
    epoch = coord.epoch if coord is not None else 0
    # goodput lifecycle (ISSUE 15): one `restart` event per group
    # respawn, carrying detect_ts (watch noticed the death) and
    # respawn_ts (replacement group spawned) — goodtop decomposes each
    # cross-incarnation gap against these
    pending_restart = None
    while True:
        local = start_local_trainers(
            trainers, node_ip, args.training_script,
            serve_args if serve_module else args.training_script_args,
            args.log_dir, restart_count=attempt,
            heartbeat_dir=heartbeat_dir, debugz_base_port=debugz_base,
            membership_epoch=epoch, module=serve_module,
        )
        if pending_restart is not None:
            pending_restart["respawn_ts"] = round(time.time(), 6)
            if fleet_ledger is not None:
                fleet_ledger.event(event="restart", **pending_restart)
            if coord is not None:
                coord.note_incident(
                    dict(pending_restart, event="restart"))
            pending_restart = None
        if not local:
            print(f"[launch] node_ip {node_ip} not in --ips {ips}", file=sys.stderr)
            return 2
        if grace is not None:
            grace.trainers = local
        if coord is not None and lease_armed:
            for t in local:
                coord.register(t.tag, kind="trainer", endpoint=t.endpoint)
        monitor = None
        if heartbeat_dir and args.heartbeat_timeout > 0:
            from .heartbeat import HeartBeatMonitor

            # created AFTER spawn: a fresh monitor ignores stamps older
            # than itself, so leftovers from a previous attempt/job in a
            # reused shared dir never read as hangs; it knows the
            # membership epoch so a future-epoch stamp (a member owned
            # by a NEWER coordinator) is never read as proof of life
            monitor = HeartBeatMonitor(
                heartbeat_dir, [t.rank for t in local],
                args.heartbeat_timeout, epoch=epoch,
            )
        straggler = None
        eject = args.straggler_eject_factor > 0
        if heartbeat_dir and (args.straggler_factor > 0 or eject):
            from .heartbeat import StragglerMonitor

            straggler = StragglerMonitor(
                heartbeat_dir, [t.rank for t in local],
                factor=(args.straggler_eject_factor
                        if eject else args.straggler_factor))
        serve_respawner = None
        if serve_module is not None and elastic_enabled:
            serve_respawner = ServeRespawner(
                trainers, node_ip, args.training_script, serve_args,
                args.log_dir, retries=per_rank,
                heartbeat_dir=heartbeat_dir, debugz_base_port=debugz_base,
                membership_epoch=epoch, module=serve_module)
        failure: dict = {}
        rc = watch_local_trainers(
            local, monitor=monitor, ps_supervisor=ps_supervisor,
            grace=grace, straggler=straggler, failure=failure,
            coordinator=coord if lease_armed else None,
            straggler_eject=eject, serve_respawner=serve_respawner,
            fleet_ledger=fleet_ledger, incident_coord=coord,
            coord_supervisor=coord_supervisor)
        detect_ts = time.time()  # the watch just noticed the death
        if (rc == 0
                or rc == 128 + signal.SIGINT
                or rc == 128 + signal.SIGTERM  # whole-job preemption
                or (ps_supervisor is not None and ps_supervisor.aborted)
                or not elastic_enabled):
            return rc
        # charge the failure to the culprit's per-rank budget; the
        # coordinator decides restart-in-place vs evict-and-resize
        tag = failure.get("tag", local[0].tag)
        rank = failure.get("rank", "?")
        reason = failure.get("reason", f"exit code {rc}")
        resized = False
        if coord is not None:
            verdict = coord.report_failure(tag, reason)
            if verdict["evicted"]:
                new_world = len(trainers) - 1
                if new_world < max(1, args.min_world_size):
                    print(f"[launch] {tag} (rank {rank}) exhausted its "
                          f"per-rank budget ({reason}) and the job "
                          f"cannot resize below "
                          f"--min_world_size={args.min_world_size}; "
                          f"aborting", file=sys.stderr)
                    return rc
                if len(ips) > 1:
                    print(f"[launch] {tag} (rank {rank}) exhausted its "
                          f"per-rank budget ({reason}); elastic resize "
                          f"is single-node only — aborting",
                          file=sys.stderr)
                    return rc
                survivors = [t for t in trainers if t.tag != tag]
                # re-rank 0..W-1 but keep each survivor's stable tag
                # (and endpoint — ports are identity on CPU fleets)
                trainers = [Trainer(i, t.endpoint, tag=t.tag)
                            for i, t in enumerate(survivors)]
                epoch = verdict["epoch"]
                resized = True
        if attempt >= job_cap:
            print(f"[launch] {tag} (rank {rank}) failed ({reason}) and "
                  f"the job-level restart cap ({job_cap}) is exhausted; "
                  f"aborting", file=sys.stderr)
            return rc
        attempt += 1
        pending_restart = {
            "tag": tag, "rank": rank, "reason": reason,
            "detect_ts": round(detect_ts, 6), "attempt": attempt,
            "world": len(trainers), "resized": resized,
        }
        if resized:
            # elastic resize: survivors re-shard their checkpoints
            # (CheckpointManager world-size gate) and the sync-PS
            # barrier adopts the new trainer_num via the generation bump
            os.environ["PADDLE_ELASTIC_RESHARD"] = "1"
            print(
                f"[launch] elastic restart {attempt}/{job_cap}: {tag} "
                f"(rank {rank}) evicted after {reason}; membership "
                f"epoch {epoch}, resizing to world_size="
                f"{len(trainers)} (survivors resume from checkpoint, "
                f"re-sharded)",
                file=sys.stderr,
            )
        else:
            print(
                f"[launch] elastic restart {attempt}/{job_cap}: {tag} "
                f"(rank {rank}) died ({reason}); group restarts at "
                f"world_size={len(trainers)} (trainers resume from "
                f"checkpoint)",
                file=sys.stderr,
            )
        if heartbeat_dir:
            # drop stale stamps so the new group starts with a clean slate
            from .heartbeat import _stamp_path

            for t in local:
                try:
                    os.remove(_stamp_path(heartbeat_dir, t.rank))
                except OSError:
                    pass


if __name__ == "__main__":
    sys.exit(launch())
