"""Multi-process launcher: `python -m paddle_tpu.distributed.launch
[--nproc_per_node N] [--ips a,b] train.py args...`

Parity surface: reference python/paddle/distributed/launch.py:193 +
utils.py (get_cluster:230, start_local_trainers:340,
watch_local_trainers:407 — abort the whole job when any child dies).

Env protocol per trainer (identical to the reference, consumed by
parallel/env.py):
  PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
  PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT

TPU notes: one process per HOST is the normal topology (all local chips
belong to one PJRT client); --nproc_per_node exists for CPU fleets and
tests. Rendezvous is the JAX coordination service bootstrapped from the
first endpoint (no gen_nccl_id gRPC exchange).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


class Trainer:
    def __init__(self, rank: int, endpoint: str):
        self.rank = rank
        self.endpoint = endpoint
        self.proc: Optional[subprocess.Popen] = None
        self.log = None


def get_cluster(ips: List[str], nproc_per_node: int, start_port: int):
    """[(rank, ip:port)] across all nodes (reference utils.get_cluster)."""
    out = []
    rank = 0
    for ip in ips:
        for i in range(nproc_per_node):
            out.append(Trainer(rank, f"{ip}:{start_port + i}"))
            rank += 1
    return out


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn and watch per-node trainer processes",
    )
    p.add_argument("--ips", "--cluster_node_ips", default="127.0.0.1",
                   help="comma-separated node ips (this script runs on each)")
    p.add_argument("--node_ip", default=None,
                   help="this node's ip (default: first of --ips)")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--log_dir", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def start_local_trainers(cluster: List[Trainer], node_ip: str, script: str,
                         script_args: List[str], log_dir: Optional[str]):
    """Fork this node's trainers with the env protocol (reference
    utils.start_local_trainers:340)."""
    endpoints = ",".join(t.endpoint for t in cluster)
    local = [t for t in cluster if t.endpoint.split(":")[0] == node_ip]
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    for t in local:
        env = dict(os.environ)
        env.update(
            PADDLE_TRAINER_ID=str(t.rank),
            PADDLE_TRAINERS_NUM=str(len(cluster)),
            PADDLE_TRAINER_ENDPOINTS=endpoints,
            PADDLE_CURRENT_ENDPOINT=t.endpoint,
        )
        cmd = [sys.executable, "-u", script] + list(script_args)
        if log_dir:
            t.log = open(os.path.join(log_dir, f"workerlog.{t.rank}"), "w")
            t.proc = subprocess.Popen(cmd, env=env, stdout=t.log,
                                      stderr=subprocess.STDOUT)
        else:
            t.proc = subprocess.Popen(cmd, env=env)
    return local


def terminate_local_trainers(trainers: List[Trainer]):
    for t in trainers:
        if t.proc and t.proc.poll() is None:
            t.proc.terminate()
    deadline = time.time() + 5
    for t in trainers:
        if not t.proc:
            continue
        while t.proc.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if t.proc.poll() is None:
            t.proc.kill()
    for t in trainers:
        if t.log:
            t.log.close()


def watch_local_trainers(trainers: List[Trainer], poll_interval=0.2) -> int:
    """Block until all trainers exit. Any nonzero exit aborts the whole
    local group (reference watch_local_trainers:407: fail fast, recovery
    is checkpoint+restart). Returns the job's exit code."""
    try:
        while True:
            alive = False
            for t in trainers:
                rc = t.proc.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    print(
                        f"[launch] trainer {t.rank} ({t.endpoint}) exited "
                        f"with {rc}; aborting the job",
                        file=sys.stderr,
                    )
                    terminate_local_trainers(trainers)
                    return rc
            if not alive:
                return 0
            time.sleep(poll_interval)
    except KeyboardInterrupt:
        terminate_local_trainers(trainers)
        return 128 + signal.SIGINT


def launch(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    ips = [s.strip() for s in args.ips.split(",") if s.strip()]
    node_ip = args.node_ip or ips[0]
    cluster = get_cluster(ips, args.nproc_per_node, args.started_port)
    local = start_local_trainers(
        cluster, node_ip, args.training_script, args.training_script_args,
        args.log_dir,
    )
    if not local:
        print(f"[launch] node_ip {node_ip} not in --ips {ips}", file=sys.stderr)
        return 2
    return watch_local_trainers(local)


if __name__ == "__main__":
    sys.exit(launch())
