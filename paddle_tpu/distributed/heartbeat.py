"""Trainer liveness via file heartbeats.

Parity surface: the reference's PS-side HeartBeatMonitor
(/root/reference/paddle/fluid/operators/distributed/heart_beat_monitor.h:54)
marks a trainer TIMEOUT when no UPDATE arrives within a window, and its
launcher aborts the job on any child failure (distributed/utils.py:407) —
detection only on hard exit, nothing for hangs.

TPU-native design: no parameter server exists to observe traffic, so
liveness is its own tiny channel — each trainer stamps a per-rank
heartbeat file (shared filesystem for multi-host) from a daemon thread,
and the launcher treats a stale stamp as a hang, which XLA collectives
otherwise turn into a silent whole-job stall (one lost participant blocks
every psum). Detection feeds the launcher's elastic restart
(launch.py --elastic_retries): kill the group, respawn, resume from
checkpoint.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, List, Optional, Tuple, Union

from ..telemetry import tracing as _tracing

ENV_DIR = "PADDLE_HEARTBEAT_DIR"

# a "rank" is an int trainer rank or a string tag (pservers stamp as
# "ps<idx>" — ps_server.serve / launch.py supervision share this channel)
Rank = Union[int, str]

# step-rate payload for the stamps: fluid/monitor.py registers its
# (global step, avg step seconds) sampler here on the first executed
# step, so launched trainers carry progress in their heartbeats without
# code changes — the launcher's straggler detection reads it back
_step_provider: Optional[Callable[[], Tuple[int, Optional[float]]]] = None

# extra stamp fields (ISSUE 15): fluid/monitor registers a provider
# returning e.g. {"data_frac": 0.7} — the input-skew signal straggler
# attribution reads back. None values are dropped, so an unarmed
# telemetry layer leaves the stamp bytes unchanged
_aux_provider: Optional[Callable[[], dict]] = None


def set_step_provider(fn: Callable[[], Tuple[int, Optional[float]]]) -> None:
    global _step_provider
    _step_provider = fn


def set_aux_provider(fn: Callable[[], dict]) -> None:
    global _aux_provider
    _aux_provider = fn


def _stamp_path(directory: str, rank: Rank) -> str:
    return os.path.join(directory, f"heartbeat.{rank}")


def read_stamp(directory: str, rank: Rank) -> Optional[dict]:
    """Parsed stamp content: {"t": unix seconds[, "step": int,
    "avg_step_s": float]}. Pre-telemetry stamps (a bare repr(float))
    parse as {"t": value}. None when absent/torn."""
    try:
        with open(_stamp_path(directory, rank)) as f:
            raw = f.read()
    except OSError:
        return None
    try:
        d = json.loads(raw)
        return d if isinstance(d, dict) else {"t": float(d)}
    except ValueError:
        try:
            return {"t": float(raw)}
        except ValueError:
            return None


class HeartBeatWorker:
    """Daemon thread stamping this process's heartbeat file (trainers
    stamp their integer rank; pservers stamp a string tag). Stamps
    carry the member's membership-epoch view (PADDLE_MEMBERSHIP_EPOCH)
    when the launcher exported one, and `renew_cb` — when the job
    control plane is armed — turns every stamp into a coordinator
    lease renewal carrying the same payload (coordinator.py).

    Coordinator outages never stall the beat (ISSUE 18): the renewal
    callback is CoordinatorClient.renew, which raises ConnectionError
    on a transport failure AFTER entering grace mode — buffering the
    payload and re-registering idempotently on reconnect — and the
    `except` below swallows the raise, so file heartbeats keep stamping
    and training keeps stepping while the control plane is down."""

    def __init__(self, directory: str, rank: Rank, interval: float = 1.0,
                 renew_cb=None):
        self.path = _stamp_path(directory, rank)
        self.interval = interval
        self.renew_cb = renew_cb
        try:
            self.epoch = int(os.environ.get("PADDLE_MEMBERSHIP_EPOCH", 0)
                             or 0)
        except ValueError:
            self.epoch = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(directory, exist_ok=True)

    def _beat(self):
        stamp = {"t": time.time()}
        if self.epoch:
            stamp["epoch"] = self.epoch
        if _step_provider is not None:
            try:
                step, avg = _step_provider()
                stamp["step"] = int(step)
                if avg is not None:
                    stamp["avg_step_s"] = round(avg, 6)
            except Exception:  # noqa: BLE001 — liveness must never die
                pass
        # the latest step's trace_id (PADDLE_TRACING): straggler episode
        # events cite it, so tracetop can be pointed straight at the
        # culprit's step trace; absent when tracing is off
        tid = _tracing.last_step_trace_id()
        if tid is not None:
            stamp["trace_id"] = tid
        if _aux_provider is not None:
            try:
                for k, v in (_aux_provider() or {}).items():
                    if v is not None:
                        stamp[k] = v
            except Exception:  # noqa: BLE001 — liveness must never die
                pass
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(stamp))
        os.replace(tmp, self.path)  # atomic: monitor never reads a torn file
        if self.renew_cb is not None:
            try:
                payload = stamp
                # fleet aggregation (ISSUE 15): the renewal additionally
                # carries a bounded registry snapshot + the goodput
                # ledger summary when PADDLE_FLEET_METRICS armed it —
                # off = the stamp rides unchanged (wire bytes identical)
                try:
                    from ..telemetry import goodput as _goodput

                    extra = _goodput.fleet_payload()
                    if extra:
                        payload = dict(stamp)
                        payload.update(extra)
                except Exception:  # noqa: BLE001 — accounting only
                    pass
                self.renew_cb(payload)
            except Exception:  # noqa: BLE001 — a flapping coordinator
                pass  # must never kill the liveness thread

    def start(self):
        if self._thread is not None:
            return self
        self._beat()

        def loop():
            while not self._stop.wait(self.interval):
                self._beat()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def start_heartbeat(interval: float = 1.0):
    """Trainer-side entry: start stamping if the launcher enabled
    heartbeats (PADDLE_HEARTBEAT_DIR set); no-op otherwise. Called by
    parallel.env.init_parallel_env so launched trainers get liveness
    reporting without code changes.

    When the job control plane is armed (PADDLE_COORDINATOR_ENDPOINT +
    PADDLE_LEASE_SECS), every stamp doubles as a coordinator lease
    renewal; with a coordinator but no heartbeat dir, a pure
    lease-renewal worker runs instead — either way the trainer's lease
    stays live without code changes."""
    directory = os.environ.get(ENV_DIR)
    from . import coordinator as coord_mod

    endpoint = os.environ.get(coord_mod.ENV_ENDPOINT)
    lease = coord_mod.lease_secs_from_env()
    renew_cb = None
    if endpoint and lease > 0:
        if not directory:
            # lease-only liveness: no shared filesystem needed
            return coord_mod.maybe_start_lease_worker(kind="trainer")
        client = coord_mod.CoordinatorClient(endpoint, kind="trainer")
        try:
            client.register()
        except Exception:  # noqa: BLE001 — renewals keep trying
            pass
        renew_cb = client.renew
    if not directory:
        return None
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    return HeartBeatWorker(directory, rank, interval,
                           renew_cb=renew_cb).start()


class StragglerMonitor:
    """Launcher-side straggler detection over the heartbeat channel.

    Trainers' stamps carry (step, t) once fluid/monitor.py registers its
    step provider; poll() feeds every fresh sample into a
    telemetry.straggler.StragglerDetector and returns the structured
    `straggler` events it raised — a rank whose step time exceeds
    `factor` x the median of its peers (or that stopped advancing while
    peers run). The launcher prints each event as one JSON log line and
    keeps the job running: straggler detection is diagnosis, not
    enforcement (kill policy stays with --heartbeat_timeout)."""

    def __init__(self, directory: str, ranks: List[Rank],
                 factor: float = 3.0, min_steps: Optional[int] = None):
        from ..telemetry.straggler import StragglerDetector

        self.directory = directory
        self.ranks = list(ranks)
        kw = {} if min_steps is None else {"min_steps": min_steps}
        self.detector = StragglerDetector(factor=factor, **kw)
        # rank -> latest step trace_id seen in its stamps (PADDLE_TRACING
        # ride-along): a straggler episode names the culprit's trace so
        # tracetop can be pointed straight at the evidence
        self._last_trace: dict = {}
        # rank -> latest data-wait fraction (ISSUE 15): a flagged rank
        # whose input pipeline dominates its step time is data-starved,
        # not compute-slow — the event says which
        self._last_frac: dict = {}
        try:
            self.data_starved_frac = float(
                os.environ.get("PADDLE_DATA_STARVED_FRAC", 0.5) or 0.5)
        except ValueError:
            self.data_starved_frac = 0.5

    def poll(self) -> List[dict]:
        for r in self.ranks:
            stamp = read_stamp(self.directory, r)
            if stamp is None or "step" not in stamp:
                continue
            if stamp.get("trace_id"):
                self._last_trace[r] = stamp["trace_id"]
            if stamp.get("data_frac") is not None:
                self._last_frac[r] = float(stamp["data_frac"])
            self.detector.observe(r, int(stamp["step"]), float(stamp["t"]))
        events = self.detector.events()
        for ev in events:
            tid = self._last_trace.get(ev.get("rank"))
            if tid is not None:
                ev["trace_id"] = tid
            frac = self._last_frac.get(ev.get("rank"))
            if frac is not None:
                ev["data_frac"] = frac
                ev["cause"] = ("data_wait"
                               if frac >= self.data_starved_frac
                               else "compute")
        return events


class HeartBeatMonitor:
    """Launcher-side: which ranks have not stamped within `timeout`?

    A rank is only considered once it stamps AFTER this monitor was
    created: startup (imports, first XLA compile) can legitimately exceed
    the window, and a leftover stamp from a previous job in a reused
    shared directory must not kill the new group before it boots. But a
    rank that NEVER produces a fresh stamp is still flagged once the
    `startup_grace` window (default 30x the heartbeat timeout) runs out —
    otherwise the exact hang class the feature targets (deadlock during
    import or first compile) would go undetected forever.
    """

    def __init__(self, directory: str, ranks: List[Rank], timeout: float,
                 startup_grace: Optional[float] = None,
                 epoch: Optional[int] = None):
        self.directory = directory
        self.ranks = list(ranks)
        self.timeout = timeout
        self.startup_grace = (
            startup_grace if startup_grace is not None
            else float(os.environ.get("PADDLE_HEARTBEAT_STARTUP_GRACE",
                                      30 * timeout))
        )
        # split-brain guard: when this monitor knows its membership
        # epoch, a stamp claiming a FUTURE epoch is not proof of life —
        # the stamper answers to a NEWER coordinator, so this (stale)
        # supervisor must not keep making liveness calls on its basis
        self.epoch = epoch
        self._t0 = time.time()

    def stale_ranks(self, now: Optional[float] = None,
                    ranks: Optional[List[Rank]] = None) -> List[Rank]:
        """`ranks` narrows the check (the launcher passes only ranks whose
        process is still running — a trainer that already exited cleanly
        stops stamping and must not read as hung)."""
        now = time.time() if now is None else now
        stale = []
        for r in self.ranks if ranks is None else ranks:
            try:
                mtime = os.path.getmtime(_stamp_path(self.directory, r))
            except OSError:
                mtime = None  # no stamp file yet
            if mtime is None or mtime < self._t0:
                # never stamped under THIS monitor: flag only after the
                # (long) startup grace window
                if now - self._t0 > self.startup_grace:
                    stale.append(r)
                continue
            if self.epoch is not None:
                stamp = read_stamp(self.directory, r)
                if stamp and int(stamp.get("epoch", 0)) > self.epoch:
                    stale.append(r)  # future-epoch stamp: we are stale
                    continue
            if now - mtime > self.timeout:
                stale.append(r)
        return stale
