"""Lease-based job control plane (ISSUE 8).

The launcher hosts a tiny membership service — the `Coordinator` — and
every process in the job (trainers AND pservers) holds a renewable
lease on its membership. Heartbeat stamps become lease renewals: the
same JSON step payload trainers already stamp to the heartbeat file
rides each `renew` RPC, and pservers renew with their per-partition
replica summary (role / epoch / seq). Liveness decisions then live in
ONE place instead of being split between file mtimes and client retry
loops:

  trainers  — a lease that expires past the member's per-rank retry
              budget EVICTS the member: the coordinator bumps the
              membership epoch and the launcher restarts the surviving
              ranks at the reduced world size from the last checkpoint
              (elastic resize) instead of burning the whole restart
              budget on a permanently-lost host.
  pservers  — the coordinator is the lease-based primary elector the
              client-driven failover path (ps_server.RemoteTable) could
              not be: when a partition primary's lease expires, the
              coordinator promotes the best caught-up backup DIRECTLY
              (promote RPC, epoch fenced) — no client traffic needed.
              Clients discover the new primary through the
              StaleEpoch/NotPrimary bounce they already handle.

Transport: the `_TCPServer` / `_Handler` / `_Conn` stack from
ps_server.py, unchanged — the Coordinator just implements
`handle(method, kwargs)` + `shutdown_event` like PSServer does, so RPC
retries, deterministic fault injection (faults.py: `lease_expire`,
`netsplit` rules) and per-verb telemetry come for free.

Split-brain guard: every renewal carries the member's view of the
membership epoch (PADDLE_MEMBERSHIP_EPOCH, exported by the launcher at
spawn). A renewal from a FUTURE epoch means a newer coordinator exists
and THIS one is stale — the renewal is recorded but does not refresh
the lease, and the coordinator stops trusting its own membership view
for that member (heartbeat.HeartBeatMonitor applies the same rule to
file stamps).

Env contract:
  PADDLE_COORDINATOR_ENDPOINT  host:port of the launcher's coordinator
  PADDLE_LEASE_SECS            lease duration (launch.py --lease_secs)
  PADDLE_MEMBERSHIP_EPOCH      the member's membership-epoch view
  PADDLE_TRAINER_TAG           stable identity ("trainer2") across
                               resizes — budgets key on it, not on the
                               re-numbered rank
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..telemetry import get_registry

_REG = get_registry()

ENV_ENDPOINT = "PADDLE_COORDINATOR_ENDPOINT"
ENV_LEASE_SECS = "PADDLE_LEASE_SECS"
ENV_EPOCH = "PADDLE_MEMBERSHIP_EPOCH"
ENV_TAG = "PADDLE_TRAINER_TAG"

# a lease is EXPIRED once this many lease periods pass without a
# renewal (the "within 2 lease periods" promotion bound)
EXPIRE_PERIODS = float(os.environ.get("PADDLE_LEASE_EXPIRE_PERIODS", 2.0))


def lease_secs_from_env() -> float:
    try:
        return float(os.environ.get(ENV_LEASE_SECS, 0) or 0)
    except ValueError:
        return 0.0


def membership_epoch_from_env() -> int:
    try:
        return int(os.environ.get(ENV_EPOCH, 0) or 0)
    except ValueError:
        return 0


def member_tag() -> str:
    """This process's stable membership identity: the launcher-exported
    tag survives resizes (ranks are re-numbered, tags are not)."""
    tag = os.environ.get(ENV_TAG)
    if tag:
        return tag
    ps = os.environ.get("PADDLE_PS_RANK_TAG")
    if ps:
        return ps
    return f"trainer{os.environ.get('PADDLE_TRAINER_ID', 0)}"


class _Member:
    __slots__ = ("tag", "kind", "endpoint", "expires", "payload",
                 "failures", "alive", "evicted", "expired_reported",
                 "stale_reported", "last_renew")

    def __init__(self, tag: str, kind: str, endpoint: Optional[str],
                 expires: float):
        self.tag = tag
        self.kind = kind
        self.endpoint = endpoint
        self.expires = expires
        self.payload: Optional[dict] = None
        self.failures = 0
        self.alive = True
        self.evicted = False
        self.expired_reported = False  # one lease_expired event per lapse
        self.stale_reported = False  # one stale_coordinator event
        self.last_renew = 0.0

    def status(self, now: float) -> dict:
        return {
            "kind": self.kind, "endpoint": self.endpoint,
            "alive": self.alive, "evicted": self.evicted,
            "failures": self.failures,
            "lease_remaining_s": round(self.expires - now, 3),
            "payload": self.payload,
        }


class CkptBarrier:
    """Commit barrier for sharded checkpoints (fluid/checkpoint.py):
    every rank reports its landed shard manifest (`ckpt_shard_commit`)
    and rank 0 polls `ckpt_status` until all world_size shards are in,
    THEN writes the global manifest — the single commit point that
    makes a partially-saved step invisible to every restore. Implements
    the `_Handler` contract, so it serves standalone over the ps_server
    TCP transport (the launcher hosts one for every multi-rank job) or
    rides a `Coordinator`'s port when the lease plane is armed.

    State is bounded: only the newest _KEEP steps are remembered — a
    report for a long-gone step can only come from a rank so far behind
    that its job already failed."""

    _KEEP = 32

    def __init__(self):
        self.cond = threading.Condition()
        # step -> {"world": int, "shards": {rank: info}}
        self.steps: Dict[int, dict] = {}
        self.shutdown_event = threading.Event()  # _Handler contract

    def shard_commit(self, step: int, rank: int, world_size: int,
                     info: Optional[dict] = None) -> dict:
        with self.cond:
            ent = self.steps.setdefault(
                int(step), {"world": int(world_size), "shards": {}})
            ent["world"] = int(world_size)
            ent["shards"][int(rank)] = dict(info or {})
            while len(self.steps) > self._KEEP:
                self.steps.pop(min(self.steps))
            self.cond.notify_all()
            _REG.counter("ckpt_barrier_reports_total").inc()
            return {"complete": len(ent["shards"]) >= ent["world"]}

    def status(self, step: int) -> dict:
        with self.cond:
            ent = self.steps.get(int(step)) or {"world": 0, "shards": {}}
            return {"world": ent["world"],
                    "shards": {r: dict(i)
                               for r, i in ent["shards"].items()},
                    "complete": (ent["world"] > 0
                                 and len(ent["shards"]) >= ent["world"])}

    def wait_full(self, step: int, world_size: int,
                  timeout: float) -> dict:
        """Block until all `world_size` shards reported (in-process
        callers; remote rank 0 polls `status` instead so no handler
        thread sits in a long wait)."""
        deadline = time.monotonic() + float(timeout)
        with self.cond:
            while True:
                ent = self.steps.get(int(step))
                if ent is not None and \
                        len(ent["shards"]) >= int(world_size):
                    return {"complete": True,
                            "shards": {r: dict(i)
                                       for r, i in ent["shards"].items()}}
                left = deadline - time.monotonic()
                if left <= 0:
                    return {"complete": False,
                            "shards": {r: dict(i) for r, i in
                                       (ent or {"shards": {}})
                                       ["shards"].items()}}
                self.cond.wait(min(left, 0.2))

    def handle(self, method: str, kwargs: dict):
        if method == "ping":
            return "pong"
        if method == "ckpt_shard_commit":
            return self.shard_commit(kwargs["step"], kwargs["rank"],
                                     kwargs["world_size"],
                                     kwargs.get("info"))
        if method == "ckpt_status":
            return self.status(kwargs["step"])
        if method == "shutdown":
            self.shutdown_event.set()
            return 0
        raise ValueError(f"unknown ckpt-barrier method {method!r}")


def serve_ckpt_barrier(barrier: CkptBarrier, host: str = "127.0.0.1",
                       port: int = 0):
    """Host `barrier` over the ps_server TCP transport (daemon thread).
    Returns (server, "host:port"); the launcher exports the endpoint as
    PADDLE_CKPT_BARRIER_ENDPOINT so sharded checkpoint writers can
    reach the commit barrier."""
    from .ps_server import _Handler, _TCPServer

    srv = _TCPServer((host, port), _Handler)
    srv.ps = barrier  # type: ignore[attr-defined] — _Handler contract
    threading.Thread(target=srv.serve_forever,
                     kwargs={"poll_interval": 0.1}, daemon=True,
                     name="paddle-tpu-ckpt-barrier").start()
    return srv, f"{host}:{srv.server_address[1]}"


class Coordinator:
    """Membership + lease table. Hosted in the LAUNCHER process: the
    launcher calls the methods directly (it is the consumer of events);
    remote members reach the same object through serve() + the
    ps_server RPC transport. All state is guarded by one lock — verbs
    are tiny and never block on I/O except `sweep`'s promote RPCs,
    which run outside the lock. Also carries the sharded-checkpoint
    commit barrier (`ckpt_*` verbs delegate to an owned CkptBarrier),
    so a lease-armed job's barrier shares the coordinator's port."""

    def __init__(self, lease_secs: float = 5.0, retries_per_rank: int = 0,
                 expire_periods: float = EXPIRE_PERIODS,
                 startup_grace: Optional[float] = None):
        self.lease_secs = float(lease_secs)
        self.retries_per_rank = int(retries_per_rank)
        self.expire_periods = float(expire_periods)
        # first expiry deadline after register: imports + first XLA
        # compile legitimately exceed a lease period (same reasoning as
        # HeartBeatMonitor.startup_grace)
        self.startup_grace = (
            float(startup_grace) if startup_grace is not None
            else max(self.lease_secs * 10.0,
                     self.lease_secs * self.expire_periods))
        self.epoch = 0
        self.members: Dict[str, _Member] = {}
        self.events: deque = deque(maxlen=512)
        # fleet view (ISSUE 15): unlike `events` (drained by the
        # launcher log), incidents are RETAINED — the /fleetz "worst
        # badput incidents" table reads them on every scrape
        self.incidents: deque = deque(maxlen=64)
        self.lock = threading.RLock()
        self.shutdown_event = threading.Event()  # _Handler contract
        self.ckpt_barrier = CkptBarrier()
        # cross-replica SDC detection (ISSUE 12): dp ranks publish
        # params+merged-grad fingerprints every PADDLE_SDC_CHECK_EVERY
        # steps; the table compares checksums and names the
        # odd-rank-out (telemetry/numerics.py FingerprintTable)
        from ..telemetry.numerics import FingerprintTable

        self.fingerprints = FingerprintTable()
        self._sdc_evicted: set = set()

    # incident kinds worth keeping for the fleet view: anything that
    # costs the job badput (deaths, evictions, expiries, stragglers,
    # SDC verdicts, promotions)
    INCIDENT_EVENTS = frozenset((
        "member_failed", "member_evicted", "lease_expired", "straggler",
        "stall", "divergence", "ps_promoted", "ps_promotion_failed",
        "restart",
    ))

    # -- internals -------------------------------------------------------
    def _event(self, **ev) -> None:
        ev.setdefault("ts", time.time())
        self.events.append(ev)
        if ev.get("event") in self.INCIDENT_EVENTS:
            self.incidents.append(dict(ev))

    def _deadline(self, now: float) -> float:
        return now + self.lease_secs * self.expire_periods

    def _get(self, tag: str, kind: str = "trainer",
             endpoint: Optional[str] = None,
             now: Optional[float] = None) -> _Member:
        now = time.time() if now is None else now
        m = self.members.get(tag)
        if m is None:
            m = self.members[tag] = _Member(
                tag, kind, endpoint, now + self.startup_grace)
        return m

    # -- verbs (also called directly by the launcher) --------------------
    def register(self, tag: str, kind: str = "trainer",
                 endpoint: Optional[str] = None, payload: Optional[dict] = None,
                 epoch: Optional[int] = None, now: Optional[float] = None):
        """(Re)grant a lease. Registration is identity-stable: a
        respawned process re-registers under its old tag and keeps its
        failure count (budgets outlive incarnations). An EVICTED tag is
        told so — the member must not keep working."""
        now = time.time() if now is None else now
        with self.lock:
            m = self._get(tag, kind, endpoint, now)
            m.kind = kind
            if endpoint:
                m.endpoint = endpoint
            if payload is not None:
                m.payload = dict(payload)
            if m.evicted:
                return {"epoch": self.epoch, "lease_secs": self.lease_secs,
                        "evicted": True}
            m.alive = True
            m.expired_reported = False
            # fresh registrations get the startup grace, renewals the
            # plain lease window — registration IS process (re)birth
            m.expires = now + max(self.startup_grace,
                                  self.lease_secs * self.expire_periods)
            _REG.counter("coordinator_registrations_total",
                         kind=kind).inc()
            return {"epoch": self.epoch, "lease_secs": self.lease_secs,
                    "evicted": False}

    def renew(self, tag: str, payload: Optional[dict] = None,
              epoch: Optional[int] = None, now: Optional[float] = None):
        """One lease renewal — the heartbeat stamp as an RPC. The
        payload is stored verbatim (step/avg_step_s for trainers,
        partition replica summaries for pservers). A renewal claiming a
        FUTURE membership epoch does NOT refresh the lease: a newer
        coordinator owns that member and this one is stale
        (split-brain guard)."""
        now = time.time() if now is None else now
        ep = membership_epoch_from_env() if epoch is None else int(epoch)
        with self.lock:
            m = self._get(tag, now=now)
            if payload is not None:
                m.payload = dict(payload)
            if m.evicted:
                _REG.counter("coordinator_evicted_renewals_total").inc()
                return {"epoch": self.epoch, "evicted": True}
            if ep > self.epoch:
                _REG.counter("coordinator_stale_renewals_total").inc()
                if not m.stale_reported:
                    m.stale_reported = True
                    self._event(event="stale_coordinator", tag=tag,
                                member_epoch=ep, epoch=self.epoch)
                return {"epoch": self.epoch, "evicted": False,
                        "stale_coordinator": True}
            m.alive = True
            m.expired_reported = False
            m.last_renew = now
            m.expires = self._deadline(now)
            _REG.counter("coordinator_renewals_total", kind=m.kind).inc()
            return {"epoch": self.epoch, "evicted": False}

    def membership(self, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        with self.lock:
            trainers = [t for t, m in self.members.items()
                        if m.kind == "trainer" and not m.evicted]
            return {
                "epoch": self.epoch,
                "lease_secs": self.lease_secs,
                "retries_per_rank": self.retries_per_rank,
                "world_size": len(trainers),
                "members": {t: m.status(now)
                            for t, m in sorted(self.members.items())},
            }

    def report_failure(self, tag: str, reason: str = "") -> dict:
        """The launcher observed a failure (nonzero exit, stale
        heartbeat, expired lease, straggler ejection) for `tag`. The
        coordinator owns the budget: within the per-rank budget the
        member may be restarted; past it the member is EVICTED and the
        membership epoch bumps — the elastic-resize signal."""
        with self.lock:
            m = self._get(tag)
            m.alive = False
            m.failures += 1
            evicted = m.failures > self.retries_per_rank
            if evicted and not m.evicted:
                m.evicted = True
                self.epoch += 1
                _REG.counter("coordinator_evictions_total").inc()
                self._event(event="member_evicted", tag=tag, reason=reason,
                            failures=m.failures, epoch=self.epoch)
            elif not evicted:
                self._event(event="member_failed", tag=tag, reason=reason,
                            failures=m.failures,
                            retries_left=self.retries_per_rank - m.failures)
            return {"evicted": m.evicted, "epoch": self.epoch,
                    "failures": m.failures,
                    "retries_left": max(
                        0, self.retries_per_rank - m.failures)}

    def expired_tags(self, now: Optional[float] = None,
                     kind: Optional[str] = None) -> List[str]:
        now = time.time() if now is None else now
        with self.lock:
            return [t for t, m in self.members.items()
                    if m.alive and not m.evicted and now > m.expires
                    and (kind is None or m.kind == kind)]

    def drain_events(self) -> List[dict]:
        with self.lock:
            out, self.events = list(self.events), deque(maxlen=512)
            return out

    # -- fleet metrics aggregation (ISSUE 15) ----------------------------
    def note_incident(self, ev: dict) -> dict:
        """The launcher (or a tool) records one badput incident —
        straggler stall episodes and restart windows land here so the
        fleet view cites the same evidence goodtop stitches."""
        ev = dict(ev)
        ev.setdefault("event", "stall")
        with self.lock:
            self._event(**ev)
        return {"ok": True}

    def fleet_status(self) -> dict:
        """The one-endpoint fleet rollup: per-rank rows merged from the
        latest renewal payloads (step progress, goodput summaries),
        job-level goodput ratio + badput-by-cause, and the retained
        incident list — debugz /fleetz renders this verbatim."""
        from ..telemetry import goodput as _goodput

        now = time.time()
        with self.lock:
            payloads = {t: (dict(m.payload) if m.payload else None)
                        for t, m in self.members.items()}
            meta = {t: {"kind": m.kind, "alive": m.alive,
                        "evicted": m.evicted,
                        "lease_remaining_s": round(m.expires - now, 3)}
                    for t, m in self.members.items()}
            incidents = list(self.incidents)
            epoch = self.epoch
        merged = _goodput.merge_fleet(payloads)
        for tag, row in merged["ranks"].items():
            row.update(meta.get(tag, {}))
        merged["epoch"] = epoch
        merged["world_size"] = sum(
            1 for m in meta.values()
            if m["kind"] == "trainer" and not m["evicted"])
        merged["incidents"] = sorted(
            incidents, key=lambda e: e.get("ts", 0), reverse=True)
        merged["ts"] = round(now, 6)
        return merged

    def fleet_metrics(self) -> str:
        """Fleet-wide Prometheus exposition: every member's bounded
        snapshot with a rank label, plus goodput rollup lines — ONE
        scrape target instead of N per-rank /metrics pages."""
        from ..telemetry import goodput as _goodput

        with self.lock:
            payloads = {t: (dict(m.payload) if m.payload else None)
                        for t, m in self.members.items()}
        return _goodput.fleet_prometheus(payloads)

    # -- cross-replica SDC detection (ISSUE 12) --------------------------
    def numerics_report(self, tag: str, step: int, fingerprint: dict,
                        world_size: int = 0) -> dict:
        """One rank's params+merged-grad fingerprint for `step`. On a
        checksum mismatch across ranks: one structured `divergence`
        event naming the odd-rank-out, a counter, and — when
        PADDLE_SDC_EVICT is set in the coordinator's process — the
        corrupted rank is routed to the elastic eviction path exactly
        like a host whose lease expired past its budget."""
        out = self.fingerprints.record(step, tag, fingerprint,
                                       world_size)
        ev = out.get("event")
        if ev is not None and not any(
                e.get("event") == "divergence"
                and e.get("step") == ev["step"]
                for e in self.events):
            with self.lock:
                self._event(**ev)
            _REG.counter("coordinator_sdc_divergence_total",
                         help="SDC divergence events raised").inc()
        if ev is not None and os.environ.get(
                "PADDLE_SDC_EVICT", "") not in ("", "0", "false"):
            for odd in ev.get("odd_rank_out") or []:
                if odd in self._sdc_evicted:
                    continue
                self._sdc_evicted.add(odd)
                # past-budget failure report = eviction + epoch bump:
                # the launcher's next watch tick restarts the
                # survivors without the corrupted rank
                for _ in range(self.retries_per_rank + 1):
                    self.report_failure(odd, reason="sdc_divergence")
        return out

    def numerics_status(self) -> dict:
        return self.fingerprints.status()

    # -- lease sweep + pserver primary election --------------------------
    def sweep(self, now: Optional[float] = None) -> List[dict]:
        """One supervision tick: find expired leases, emit one
        `lease_expired` event per lapse, and for every expired PSERVER
        that held partition primaries, elect + promote a caught-up
        backup (the ROADMAP "promote without a client in the loop"
        path). Returns the events raised by THIS tick. The launcher
        calls this on its watch cadence; tests drive it with an
        explicit `now`."""
        now = time.time() if now is None else now
        raised: List[dict] = []
        elect: List[_Member] = []
        with self.lock:
            for tag, m in self.members.items():
                if m.evicted or not m.alive or now <= m.expires:
                    continue
                if m.expired_reported:
                    continue
                m.expired_reported = True
                ev = {"event": "lease_expired", "tag": tag, "kind": m.kind,
                      "overdue_s": round(now - m.expires, 3)}
                self._event(**ev)
                raised.append(ev)
                _REG.counter("coordinator_lease_expiries_total",
                             kind=m.kind).inc()
                if m.kind == "pserver":
                    m.alive = False  # stops being an election candidate
                    elect.append(m)
        for dead in elect:
            raised.extend(self._elect_primaries(dead))
        return raised

    def _partition_view(self, key: str):
        """(candidates, epochs, backups) for one partition key from the
        latest renewal payloads — candidates are caught-up live backups,
        epochs every epoch seen, backups the live replica endpoints."""
        cands, epochs, backups = [], [0], []
        with self.lock:
            for m in self.members.values():
                if m.kind != "pserver":
                    continue
                st = ((m.payload or {}).get("partitions") or {}).get(key)
                if st is None:
                    continue
                epochs.append(int(st.get("epoch", 0)))
                if not m.alive or m.evicted or not m.endpoint:
                    continue
                backups.append(m.endpoint)
                if st.get("role") == "backup" and not st.get("stale"):
                    cands.append((int(st.get("epoch", 0)),
                                  int(st.get("seq", 0)), m))
        return cands, epochs, backups

    def _elect_primaries(self, dead: _Member) -> List[dict]:
        """Promote a backup for every partition the dead pserver led.
        Runs OUTSIDE the coordinator lock (promote is a real RPC)."""
        parts = (dead.payload or {}).get("partitions") or {}
        raised: List[dict] = []
        for key, st in sorted(parts.items()):
            if st.get("role") != "primary":
                continue
            cands, epochs, backups = self._partition_view(key)
            if not cands:
                ev = {"event": "ps_promotion_skipped", "key": key,
                      "from": dead.tag, "reason": "no caught-up backup"}
                with self.lock:
                    self._event(**ev)
                raised.append(ev)
                continue
            cands.sort()
            _, seq, target = cands[-1]
            new_epoch = max(epochs) + 1
            name, _, part = key.rpartition("@p")
            try:
                from .ps_server import _Conn

                conn = _Conn(target.endpoint, deadline=5.0, io_timeout=10.0)
                try:
                    conn.call("promote", name=name, partition=int(part),
                              epoch=new_epoch,
                              backups=[b for b in backups
                                       if b != target.endpoint])
                finally:
                    conn.close()
            except Exception as e:  # noqa: BLE001 — election must not
                # take the launcher down; the next sweep retries nothing
                # (the client-driven failover path still exists)
                ev = {"event": "ps_promotion_failed", "key": key,
                      "from": dead.tag, "to": target.tag,
                      "error": f"{type(e).__name__}: {e}"}
                with self.lock:
                    self._event(**ev)
                raised.append(ev)
                continue
            _REG.counter("coordinator_ps_promotions_total").inc()
            ev = {"event": "ps_promoted", "key": key, "from": dead.tag,
                  "to": target.tag, "epoch": new_epoch, "seq": seq}
            with self.lock:
                self._event(**ev)
                # reflect the grant locally so a repeated sweep (the
                # dead server stays dead) does not re-promote; the next
                # real renewal from the target carries the truth anyway
                tparts = (target.payload or {}).setdefault("partitions", {})
                tparts.setdefault(key, {})["role"] = "primary"
                tparts[key]["epoch"] = new_epoch
                dparts = (dead.payload or {}).get("partitions") or {}
                if key in dparts:
                    dparts[key]["role"] = None
            raised.append(ev)
        return raised

    # -- RPC dispatch (ps_server._Handler contract) ----------------------
    def handle(self, method: str, kwargs: dict):
        from . import faults

        inj = faults.injector()
        if inj is not None:
            inj.on_server_call(method)
        if method == "ping":
            return "pong"
        if method.startswith("ckpt_"):
            # sharded-checkpoint commit barrier rides the same port
            return self.ckpt_barrier.handle(method, kwargs)
        if method == "register":
            return self.register(
                kwargs["tag"], kwargs.get("kind", "trainer"),
                kwargs.get("endpoint"), kwargs.get("payload"),
                kwargs.get("epoch"))
        if method == "renew":
            return self.renew(kwargs["tag"], kwargs.get("payload"),
                              kwargs.get("epoch"))
        if method == "membership":
            return self.membership()
        if method == "report_failure":
            return self.report_failure(kwargs["tag"],
                                       kwargs.get("reason", ""))
        if method == "fleet_status":
            return self.fleet_status()
        if method == "fleet_metrics":
            return self.fleet_metrics()
        if method == "note_incident":
            return self.note_incident(kwargs.get("incident") or {})
        if method == "numerics_report":
            return self.numerics_report(
                kwargs["tag"], kwargs["step"], kwargs["fingerprint"],
                kwargs.get("world_size", 0))
        if method == "numerics_status":
            return self.numerics_status()
        if method == "sweep":
            return self.sweep(kwargs.get("now"))
        if method == "events":
            return self.drain_events()
        if method == "shutdown":
            self.shutdown_event.set()
            return 0
        raise ValueError(f"unknown coordinator method {method!r}")


def serve_coordinator(coord: Coordinator, host: str = "127.0.0.1",
                      port: int = 0):
    """Host `coord` over the ps_server TCP transport (daemon thread).
    Returns (server, "host:port"). The launcher exports the endpoint as
    PADDLE_COORDINATOR_ENDPOINT so members can renew."""
    from .ps_server import _Handler, _TCPServer

    srv = _TCPServer((host, port), _Handler)
    srv.ps = coord  # type: ignore[attr-defined] — _Handler contract
    threading.Thread(target=srv.serve_forever,
                     kwargs={"poll_interval": 0.1}, daemon=True,
                     name="paddle-tpu-coordinator").start()
    return srv, f"{host}:{srv.server_address[1]}"


def stop_coordinator(srv) -> None:
    try:
        srv.shutdown()
        srv.close_all_connections()
        srv.server_close()
    except Exception:  # noqa: BLE001 — teardown best-effort
        pass


# ---------------------------------------------------------------------------
# member side
# ---------------------------------------------------------------------------


class CoordinatorClient:
    """Thin member-side client: register once, renew on a cadence. All
    RPCs ride ps_server._Conn (retries, deadline, telemetry), and every
    renewal consults faults.injector() so a `lease_expire:<tag>:<nth>`
    rule can swallow renewals deterministically (the lease-expiry
    drill) without touching the process's real liveness."""

    def __init__(self, endpoint: str, tag: Optional[str] = None,
                 kind: str = "trainer", self_endpoint: Optional[str] = None,
                 deadline: float = 3.0):
        from .ps_server import _Conn

        self.endpoint = endpoint
        self.tag = tag or member_tag()
        self.kind = kind
        self.self_endpoint = self_endpoint
        self._conn = _Conn(endpoint, deadline=deadline,
                           io_timeout=deadline + 10.0)

    def register(self, payload: Optional[dict] = None) -> dict:
        return self._conn.call(
            "register", tag=self.tag, kind=self.kind,
            endpoint=self.self_endpoint, payload=payload,
            epoch=membership_epoch_from_env())

    def renew(self, payload: Optional[dict] = None) -> dict:
        from . import faults

        inj = faults.injector()
        if inj is not None and inj.on_lease_renew():
            # swallowed client-side: the coordinator never sees it, the
            # lease runs out — exactly what a silently-dead host does
            _REG.counter("coordinator_client_renewals_suppressed_total").inc()
            return {"suppressed": True}
        out = self._conn.call(
            "renew", tag=self.tag, payload=payload,
            epoch=membership_epoch_from_env())
        if isinstance(out, dict) and out.get("evicted"):
            # lease-expiry eviction: this member is out of the job —
            # dump the flight record NOW, while the spans that led here
            # are still in the ring (no-op unless tracing is armed)
            from ..telemetry import tracing

            tracing.flight_dump("lease_evicted")
        return out

    def membership(self) -> dict:
        return self._conn.call("membership")

    def numerics_report(self, step: int, fingerprint: dict,
                        world_size: int = 0) -> dict:
        """Publish one SDC fingerprint (telemetry/numerics.SDCReporter
        drives this on the PADDLE_SDC_CHECK_EVERY cadence)."""
        return self._conn.call(
            "numerics_report", tag=self.tag, step=step,
            fingerprint=fingerprint, world_size=world_size)

    def numerics_status(self) -> dict:
        return self._conn.call("numerics_status")

    def fleet_status(self) -> dict:
        return self._conn.call("fleet_status")

    def fleet_metrics(self) -> str:
        return self._conn.call("fleet_metrics")

    def note_incident(self, incident: dict) -> dict:
        return self._conn.call("note_incident", incident=incident)

    def close(self) -> None:
        self._conn.close()


class LeaseWorker:
    """Daemon renewal thread for processes without a heartbeat worker
    cadence of their own (pservers; lease-only trainers). Registration
    + renewals never raise — a flapping coordinator must not take a
    healthy member down."""

    def __init__(self, client: CoordinatorClient, interval: float,
                 payload_fn: Optional[Callable[[], dict]] = None):
        self.client = client
        self.interval = max(0.05, float(interval))
        self.payload_fn = payload_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _payload(self) -> Optional[dict]:
        out = None
        if self.payload_fn is not None:
            try:
                out = self.payload_fn()
            except Exception:  # noqa: BLE001
                out = None
        try:
            # fleet aggregation (ISSUE 15): pservers and serving
            # replicas ship the same bounded snapshot + ledger summary
            # trainers ride on heartbeat renewals; off = unchanged
            from ..telemetry import goodput as _goodput

            extra = _goodput.fleet_payload()
            if extra:
                out = dict(out or {})
                out.update(extra)
        except Exception:  # noqa: BLE001 — accounting only
            pass
        return out

    def start(self) -> "LeaseWorker":
        if self._thread is not None:
            return self
        try:
            self.client.register(payload=self._payload())
        except Exception:  # noqa: BLE001 — renewals retry registration
            pass

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.client.renew(payload=self._payload())
                except Exception:  # noqa: BLE001 — keep renewing
                    continue

        self._thread = threading.Thread(
            target=loop, daemon=True,
            name=f"paddle-tpu-lease-{self.client.tag}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self.client.close()


def maybe_start_lease_worker(kind: str, tag: Optional[str] = None,
                             self_endpoint: Optional[str] = None,
                             payload_fn: Optional[Callable[[], dict]] = None,
                             ) -> Optional[LeaseWorker]:
    """Start lease renewals when the launcher armed the control plane
    (PADDLE_COORDINATOR_ENDPOINT + PADDLE_LEASE_SECS); no-op (two env
    reads) otherwise. Renewal cadence is lease_secs/3 so a healthy
    member always lands well inside the expiry window."""
    endpoint = os.environ.get(ENV_ENDPOINT)
    lease = lease_secs_from_env()
    if not endpoint or lease <= 0:
        return None
    client = CoordinatorClient(endpoint, tag=tag, kind=kind,
                               self_endpoint=self_endpoint)
    return LeaseWorker(client, interval=lease / 3.0,
                       payload_fn=payload_fn).start()


def query_membership(timeout: float = 2.0) -> Optional[dict]:
    """The coordinator's membership table, or None when no control
    plane is armed / reachable (status pages must never crash)."""
    return _query("membership", timeout)


def query_fleet(timeout: float = 2.0) -> Optional[dict]:
    """The coordinator's fleet rollup (debugz /fleetz), or None when no
    control plane is armed / reachable."""
    return _query("fleet_status", timeout)


def query_fleet_metrics(timeout: float = 2.0) -> Optional[str]:
    """The fleet-wide Prometheus exposition (debugz /fleetz/metrics),
    or None when no control plane is armed / reachable."""
    return _query("fleet_metrics", timeout)


def _query(verb: str, timeout: float):
    endpoint = os.environ.get(ENV_ENDPOINT)
    if not endpoint:
        return None
    try:
        client = CoordinatorClient(endpoint, deadline=timeout)
        try:
            return client._conn.call(verb)
        finally:
            client.close()
    except Exception:  # noqa: BLE001
        return None
