"""Lease-based job control plane (ISSUE 8).

The launcher hosts a tiny membership service — the `Coordinator` — and
every process in the job (trainers AND pservers) holds a renewable
lease on its membership. Heartbeat stamps become lease renewals: the
same JSON step payload trainers already stamp to the heartbeat file
rides each `renew` RPC, and pservers renew with their per-partition
replica summary (role / epoch / seq). Liveness decisions then live in
ONE place instead of being split between file mtimes and client retry
loops:

  trainers  — a lease that expires past the member's per-rank retry
              budget EVICTS the member: the coordinator bumps the
              membership epoch and the launcher restarts the surviving
              ranks at the reduced world size from the last checkpoint
              (elastic resize) instead of burning the whole restart
              budget on a permanently-lost host.
  pservers  — the coordinator is the lease-based primary elector the
              client-driven failover path (ps_server.RemoteTable) could
              not be: when a partition primary's lease expires, the
              coordinator promotes the best caught-up backup DIRECTLY
              (promote RPC, epoch fenced) — no client traffic needed.
              Clients discover the new primary through the
              StaleEpoch/NotPrimary bounce they already handle.

Transport: the `_TCPServer` / `_Handler` / `_Conn` stack from
ps_server.py, unchanged — the Coordinator just implements
`handle(method, kwargs)` + `shutdown_event` like PSServer does, so RPC
retries, deterministic fault injection (faults.py: `lease_expire`,
`netsplit` rules) and per-verb telemetry come for free.

Split-brain guard: every renewal carries the member's view of the
membership epoch (PADDLE_MEMBERSHIP_EPOCH, exported by the launcher at
spawn). A renewal from a FUTURE epoch means a newer coordinator exists
and THIS one is stale — the renewal is recorded but does not refresh
the lease, and the coordinator stops trusting its own membership view
for that member (heartbeat.HeartBeatMonitor applies the same rule to
file stamps).

Control-plane crash tolerance (ISSUE 18): the coordinator itself was
the last single point of failure — every data-plane component survives
crashes, but killing the launcher-hosted coordinator lost the lease
table, restart budgets, election grants and the in-flight checkpoint
barrier. Three layers close that hole, all OFF by default (the
in-launcher coordinator is byte-identical on the wire when
PADDLE_COORD_SNAPSHOT_SECS is unset and no standby is armed):

  durable state   — `state_dir` arms snapshot+WAL persistence: the full
                    authoritative state (leases with remaining windows,
                    membership epoch, budgets, election grants reflected
                    in member payloads, CkptBarrier shard reports,
                    incident ring) is pickled to `coord-<seq>.snap` via
                    the atomic tmp+os.replace path on a bounded cadence,
                    with an append-only verb WAL (`coord-<seq>.wal`)
                    between snapshots. A respawned coordinator (the
                    launcher supervises it like a pserver) reloads the
                    newest intact snapshot (torn newest falls back to
                    the previous one), replays the WAL tail, bumps its
                    INCARNATION, and treats the first
                    PADDLE_LEASE_EXPIRE_PERIODS lease periods as a
                    reconciliation window in which no lease may be
                    declared expired — a coordinator crash never falsely
                    evicts a healthy rank.
  grace mode      — CoordinatorClient buffers renewals while the
                    coordinator is unreachable (training continues) and
                    re-registers idempotently on reconnect.
  warm standby    — a second coordinator follows the primary via the
                    `repl_pull` snapshot+WAL stream and self-promotes
                    when the primary's incarnation lease lapses; clients
                    hold an ordered endpoint list. Split-brain is fenced
                    by the incarnation number riding every reply: a
                    deposed primary's replies are rejected client-side
                    and the deposed primary LATCHES stale when it sees a
                    renewal claiming a higher incarnation (the PS
                    StaleEpoch pattern, one layer up).

Env contract:
  PADDLE_COORDINATOR_ENDPOINT  host:port of the launcher's coordinator
                               (may be an ordered comma-separated list:
                               primary first, warm standby second)
  PADDLE_LEASE_SECS            lease duration (launch.py --lease_secs)
  PADDLE_MEMBERSHIP_EPOCH      the member's membership-epoch view
  PADDLE_TRAINER_TAG           stable identity ("trainer2") across
                               resizes — budgets key on it, not on the
                               re-numbered rank
  PADDLE_COORD_SNAPSHOT_SECS   durable-mode snapshot cadence; setting it
                               moves the coordinator out of the launcher
                               into a supervised child process
  PADDLE_COORD_CALL_DEADLINE_SECS
                               client-side control-plane verb deadline
                               (default 3.0 — renewals never block a
                               training step to exhaustion)
"""
from __future__ import annotations

import hashlib
import os
import pickle
import re
import struct
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..telemetry import get_registry

_REG = get_registry()

ENV_ENDPOINT = "PADDLE_COORDINATOR_ENDPOINT"
ENV_LEASE_SECS = "PADDLE_LEASE_SECS"
ENV_EPOCH = "PADDLE_MEMBERSHIP_EPOCH"
ENV_TAG = "PADDLE_TRAINER_TAG"

# a lease is EXPIRED once this many lease periods pass without a
# renewal (the "within 2 lease periods" promotion bound)
EXPIRE_PERIODS = float(os.environ.get("PADDLE_LEASE_EXPIRE_PERIODS", 2.0))

ENV_SNAPSHOT_SECS = "PADDLE_COORD_SNAPSHOT_SECS"
ENV_CALL_DEADLINE = "PADDLE_COORD_CALL_DEADLINE_SECS"
# size-based WAL compaction: once the current WAL segment exceeds this
# many bytes a snapshot is taken and the WAL rotates, regardless of the
# time-based snapshot cadence (0 = disabled, time/record triggers only)
ENV_WAL_MAX_BYTES = "PADDLE_COORD_WAL_MAX_BYTES"


def snapshot_secs_from_env(default: float = 1.0) -> float:
    try:
        return float(os.environ.get(ENV_SNAPSHOT_SECS) or default)
    except ValueError:
        return default


def wal_max_bytes_from_env(default: int = 0) -> int:
    try:
        return int(os.environ.get(ENV_WAL_MAX_BYTES) or default)
    except ValueError:
        return default


def call_deadline_from_env(default: float = 3.0) -> float:
    """Client-side control-plane verb deadline. 3.0s is the historical
    CoordinatorClient default — the env knob only SHORTENS how long a
    renewal may block a training step during a coordinator outage."""
    try:
        return float(os.environ.get(ENV_CALL_DEADLINE) or default)
    except ValueError:
        return default


def lease_secs_from_env() -> float:
    try:
        return float(os.environ.get(ENV_LEASE_SECS, 0) or 0)
    except ValueError:
        return 0.0


def membership_epoch_from_env() -> int:
    try:
        return int(os.environ.get(ENV_EPOCH, 0) or 0)
    except ValueError:
        return 0


def member_tag() -> str:
    """This process's stable membership identity: the launcher-exported
    tag survives resizes (ranks are re-numbered, tags are not)."""
    tag = os.environ.get(ENV_TAG)
    if tag:
        return tag
    ps = os.environ.get("PADDLE_PS_RANK_TAG")
    if ps:
        return ps
    return f"trainer{os.environ.get('PADDLE_TRAINER_ID', 0)}"


class _Member:
    __slots__ = ("tag", "kind", "endpoint", "expires", "payload",
                 "failures", "alive", "evicted", "expired_reported",
                 "stale_reported", "last_renew")

    def __init__(self, tag: str, kind: str, endpoint: Optional[str],
                 expires: float):
        self.tag = tag
        self.kind = kind
        self.endpoint = endpoint
        self.expires = expires
        self.payload: Optional[dict] = None
        self.failures = 0
        self.alive = True
        self.evicted = False
        self.expired_reported = False  # one lease_expired event per lapse
        self.stale_reported = False  # one stale_coordinator event
        self.last_renew = 0.0

    def status(self, now: float) -> dict:
        return {
            "kind": self.kind, "endpoint": self.endpoint,
            "alive": self.alive, "evicted": self.evicted,
            "failures": self.failures,
            "lease_remaining_s": round(self.expires - now, 3),
            "payload": self.payload,
        }

    def to_state(self, now: float) -> dict:
        """Snapshot row. `expires` is stored as a REMAINING window, not
        a wall-clock instant — the restoring process re-anchors it to
        its own `now` (and then floors it at the reconciliation window),
        so a long outage cannot make every lease look long-expired."""
        return {
            "tag": self.tag, "kind": self.kind, "endpoint": self.endpoint,
            "remaining": self.expires - now,
            "payload": (dict(self.payload)
                        if self.payload is not None else None),
            "failures": self.failures, "alive": self.alive,
            "evicted": self.evicted,
            "expired_reported": self.expired_reported,
            "stale_reported": self.stale_reported,
            "last_renew": self.last_renew,
        }

    @classmethod
    def from_state(cls, st: dict, now: float) -> "_Member":
        m = cls(st["tag"], st.get("kind", "trainer"), st.get("endpoint"),
                now + float(st.get("remaining", 0.0)))
        m.payload = (dict(st["payload"])
                     if st.get("payload") is not None else None)
        m.failures = int(st.get("failures", 0))
        m.alive = bool(st.get("alive", True))
        m.evicted = bool(st.get("evicted", False))
        m.expired_reported = bool(st.get("expired_reported", False))
        m.stale_reported = bool(st.get("stale_reported", False))
        m.last_renew = float(st.get("last_renew", 0.0))
        return m


class CkptBarrier:
    """Commit barrier for sharded checkpoints (fluid/checkpoint.py):
    every rank reports its landed shard manifest (`ckpt_shard_commit`)
    and rank 0 polls `ckpt_status` until all world_size shards are in,
    THEN writes the global manifest — the single commit point that
    makes a partially-saved step invisible to every restore. Implements
    the `_Handler` contract, so it serves standalone over the ps_server
    TCP transport (the launcher hosts one for every multi-rank job) or
    rides a `Coordinator`'s port when the lease plane is armed.

    State is bounded: only the newest _KEEP steps are remembered — a
    report for a long-gone step can only come from a rank so far behind
    that its job already failed."""

    _KEEP = 32

    def __init__(self):
        self.cond = threading.Condition()
        # step -> {"world": int, "shards": {rank: info}}
        self.steps: Dict[int, dict] = {}
        self.shutdown_event = threading.Event()  # _Handler contract

    def shard_commit(self, step: int, rank: int, world_size: int,
                     info: Optional[dict] = None) -> dict:
        with self.cond:
            ent = self.steps.setdefault(
                int(step), {"world": int(world_size), "shards": {}})
            ent["world"] = int(world_size)
            ent["shards"][int(rank)] = dict(info or {})
            while len(self.steps) > self._KEEP:
                self.steps.pop(min(self.steps))
            self.cond.notify_all()
            _REG.counter("ckpt_barrier_reports_total").inc()
            return {"complete": len(ent["shards"]) >= ent["world"]}

    def status(self, step: int) -> dict:
        with self.cond:
            ent = self.steps.get(int(step)) or {"world": 0, "shards": {}}
            return {"world": ent["world"],
                    "shards": {r: dict(i)
                               for r, i in ent["shards"].items()},
                    "complete": (ent["world"] > 0
                                 and len(ent["shards"]) >= ent["world"])}

    def wait_full(self, step: int, world_size: int,
                  timeout: float) -> dict:
        """Block until all `world_size` shards reported (in-process
        callers; remote rank 0 polls `status` instead so no handler
        thread sits in a long wait)."""
        deadline = time.monotonic() + float(timeout)
        with self.cond:
            while True:
                ent = self.steps.get(int(step))
                if ent is not None and \
                        len(ent["shards"]) >= int(world_size):
                    return {"complete": True,
                            "shards": {r: dict(i)
                                       for r, i in ent["shards"].items()}}
                left = deadline - time.monotonic()
                if left <= 0:
                    return {"complete": False,
                            "shards": {r: dict(i) for r, i in
                                       (ent or {"shards": {}})
                                       ["shards"].items()}}
                self.cond.wait(min(left, 0.2))

    def handle(self, method: str, kwargs: dict):
        if method == "ping":
            return "pong"
        if method == "ckpt_shard_commit":
            return self.shard_commit(kwargs["step"], kwargs["rank"],
                                     kwargs["world_size"],
                                     kwargs.get("info"))
        if method == "ckpt_status":
            return self.status(kwargs["step"])
        if method == "shutdown":
            self.shutdown_event.set()
            return 0
        raise ValueError(f"unknown ckpt-barrier method {method!r}")


def serve_ckpt_barrier(barrier: CkptBarrier, host: str = "127.0.0.1",
                       port: int = 0):
    """Host `barrier` over the ps_server TCP transport (daemon thread).
    Returns (server, "host:port"); the launcher exports the endpoint as
    PADDLE_CKPT_BARRIER_ENDPOINT so sharded checkpoint writers can
    reach the commit barrier."""
    from .ps_server import _Handler, _TCPServer

    srv = _TCPServer((host, port), _Handler)
    srv.ps = barrier  # type: ignore[attr-defined] — _Handler contract
    threading.Thread(target=srv.serve_forever,
                     kwargs={"poll_interval": 0.1}, daemon=True,
                     name="paddle-tpu-ckpt-barrier").start()
    return srv, f"{host}:{srv.server_address[1]}"


# ---------------------------------------------------------------------------
# durable state (ISSUE 18): framed+checksummed snapshots, verb WAL
# ---------------------------------------------------------------------------

_SNAP_MAGIC = b"PCOORD1\n"


def _atomic_write(path: str, blob: bytes) -> None:
    """tmp + fsync + os.replace — the same commit discipline every other
    durable artifact in the tree uses (snapshots, manifests)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_snapshot(path: str) -> Optional[dict]:
    """One snapshot file, or None when missing/torn/corrupt (the loader
    falls back to the previous intact snapshot + a longer WAL replay)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    if not blob.startswith(_SNAP_MAGIC):
        return None
    digest, payload = (blob[len(_SNAP_MAGIC):len(_SNAP_MAGIC) + 32],
                       blob[len(_SNAP_MAGIC) + 32:])
    if hashlib.sha256(payload).digest() != digest:
        return None
    try:
        state = pickle.loads(payload)
    except Exception:  # noqa: BLE001 — corrupt == torn for the loader
        return None
    return state if isinstance(state, dict) else None


def _read_wal(path: str) -> List[Tuple[str, dict]]:
    """Length-prefixed (verb, kwargs) records; a torn tail (the crash
    landed mid-append) truncates the replay at the last intact record
    instead of failing recovery."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return []
    out: List[Tuple[str, dict]] = []
    off = 0
    while off + 4 <= len(data):
        (n,) = struct.unpack_from(">I", data, off)
        if off + 4 + n > len(data):
            break
        try:
            rec = pickle.loads(data[off + 4:off + 4 + n])
        except Exception:  # noqa: BLE001 — torn tail
            break
        if isinstance(rec, tuple) and len(rec) == 2:
            out.append(rec)
        off += 4 + n
    return out


class Coordinator:
    """Membership + lease table. Hosted in the LAUNCHER process: the
    launcher calls the methods directly (it is the consumer of events);
    remote members reach the same object through serve() + the
    ps_server RPC transport. All state is guarded by one lock — verbs
    are tiny and never block on I/O except `sweep`'s promote RPCs,
    which run outside the lock. Also carries the sharded-checkpoint
    commit barrier (`ckpt_*` verbs delegate to an owned CkptBarrier),
    so a lease-armed job's barrier shares the coordinator's port."""

    def __init__(self, lease_secs: float = 5.0, retries_per_rank: int = 0,
                 expire_periods: float = EXPIRE_PERIODS,
                 startup_grace: Optional[float] = None,
                 state_dir: Optional[str] = None,
                 snapshot_secs: Optional[float] = None,
                 wal_max_bytes: Optional[int] = None,
                 role: str = "primary"):
        self.lease_secs = float(lease_secs)
        self.retries_per_rank = int(retries_per_rank)
        self.expire_periods = float(expire_periods)
        # first expiry deadline after register: imports + first XLA
        # compile legitimately exceed a lease period (same reasoning as
        # HeartBeatMonitor.startup_grace)
        self.startup_grace = (
            float(startup_grace) if startup_grace is not None
            else max(self.lease_secs * 10.0,
                     self.lease_secs * self.expire_periods))
        self.epoch = 0
        self.members: Dict[str, _Member] = {}
        self.events: deque = deque(maxlen=512)
        # fleet view (ISSUE 15): unlike `events` (drained by the
        # launcher log), incidents are RETAINED — the /fleetz "worst
        # badput incidents" table reads them on every scrape
        self.incidents: deque = deque(maxlen=64)
        self.lock = threading.RLock()
        self.shutdown_event = threading.Event()  # _Handler contract
        self.ckpt_barrier = CkptBarrier()
        # cross-replica SDC detection (ISSUE 12): dp ranks publish
        # params+merged-grad fingerprints every PADDLE_SDC_CHECK_EVERY
        # steps; the table compares checksums and names the
        # odd-rank-out (telemetry/numerics.py FingerprintTable)
        from ..telemetry.numerics import FingerprintTable

        self.fingerprints = FingerprintTable()
        self._sdc_evicted: set = set()
        # -- durable state + HA (ISSUE 18) -------------------------------
        # incarnation 0 == the legacy in-launcher coordinator: no reply
        # stamping, no WAL mirror, byte-identical wire behavior. A
        # durable (process-hosted) primary is incarnation >= 1.
        self.role = role  # "primary" | "standby"
        self.incarnation = 0
        self.stale_latched = False  # deposed primary (incarnation fence)
        self.state_dir = state_dir or None
        self.snapshot_secs = (float(snapshot_secs)
                              if snapshot_secs is not None
                              else snapshot_secs_from_env())
        self.wal_max_bytes = (int(wal_max_bytes)
                              if wal_max_bytes is not None
                              else wal_max_bytes_from_env())
        self._reconcile_until = 0.0  # no expiries before this instant
        self._snap_seq = 0
        self._last_snap = 0.0
        self._wal_f = None  # open WAL file (durable primary only)
        self._wal_mem: List[Tuple[str, dict]] = []  # repl_pull stream
        self._wal_bytes = 0  # serialized bytes in the current segment
        self._replaying = False  # WAL/replication apply in progress
        if self.state_dir:
            os.makedirs(self.state_dir, exist_ok=True)
        if self.state_dir and self.role == "primary":
            self._load_durable()
            with self.lock:
                # persist the incarnation bump NOW (and rotate the WAL)
                # so a crash right after recovery still fences below us
                self._snapshot_locked(time.time())
        elif self.role == "standby":
            # a standby mirrors the primary's state (and seq) through
            # repl_apply; its state_dir is only used AFTER promotion
            self.incarnation = 0

    # incident kinds worth keeping for the fleet view: anything that
    # costs the job badput (deaths, evictions, expiries, stragglers,
    # SDC verdicts, promotions, control-plane outages)
    INCIDENT_EVENTS = frozenset((
        "member_failed", "member_evicted", "lease_expired", "straggler",
        "stall", "divergence", "ps_promoted", "ps_promotion_failed",
        "restart", "coord_outage", "coord_recovered", "coord_promoted",
    ))

    # verbs replayed from the WAL (everything that mutates durable
    # state; reads and the fleet rollups are deliberately absent)
    _WAL_VERBS = frozenset((
        "register", "renew", "report_failure", "note_incident",
        "ckpt_shard_commit", "sweep",
    ))

    # -- internals -------------------------------------------------------
    def _event(self, **ev) -> None:
        ev.setdefault("ts", time.time())
        self.events.append(ev)
        if ev.get("event") in self.INCIDENT_EVENTS:
            self.incidents.append(dict(ev))

    def _deadline(self, now: float) -> float:
        return now + self.lease_secs * self.expire_periods

    def _get(self, tag: str, kind: str = "trainer",
             endpoint: Optional[str] = None,
             now: Optional[float] = None) -> _Member:
        now = time.time() if now is None else now
        m = self.members.get(tag)
        if m is None:
            m = self.members[tag] = _Member(
                tag, kind, endpoint, now + self.startup_grace)
        return m

    # -- durable state: snapshot + WAL (ISSUE 18) ------------------------
    def state_dict(self, now: Optional[float] = None) -> dict:
        """The full authoritative state, picklable: lease table (with
        REMAINING windows), budgets, membership epoch, member payloads
        (election grants live there), event + incident rings, CkptBarrier
        in-progress shard reports, SDC eviction set."""
        now = time.time() if now is None else now
        with self.lock:
            with self.ckpt_barrier.cond:
                ckpt_steps = {
                    int(s): {"world": int(e["world"]),
                             "shards": {int(r): dict(i)
                                        for r, i in e["shards"].items()}}
                    for s, e in self.ckpt_barrier.steps.items()}
            return {
                "format": 1,
                "seq": self._snap_seq,
                "incarnation": self.incarnation,
                "epoch": self.epoch,
                "lease_secs": self.lease_secs,
                "saved_at": now,
                "members": [m.to_state(now)
                            for _, m in sorted(self.members.items())],
                "events": [dict(e) for e in self.events],
                "incidents": [dict(e) for e in self.incidents],
                "ckpt_steps": ckpt_steps,
                "sdc_evicted": sorted(self._sdc_evicted),
            }

    def load_state_dict(self, state: dict,
                        now: Optional[float] = None) -> None:
        """Replace in-memory state with `state` (restore + replication
        apply). Does NOT touch incarnation/role — recovery and promotion
        own those transitions."""
        now = time.time() if now is None else now
        with self.lock:
            self.epoch = int(state.get("epoch", 0))
            self.members = {}
            for st in state.get("members", []):
                m = _Member.from_state(st, now)
                self.members[m.tag] = m
            self.events = deque((dict(e) for e in state.get("events", [])),
                                maxlen=512)
            self.incidents = deque(
                (dict(e) for e in state.get("incidents", [])), maxlen=64)
            with self.ckpt_barrier.cond:
                self.ckpt_barrier.steps = {
                    int(s): {"world": int(e["world"]),
                             "shards": {int(r): dict(i)
                                        for r, i in e["shards"].items()}}
                    for s, e in (state.get("ckpt_steps") or {}).items()}
                self.ckpt_barrier.cond.notify_all()
            self._sdc_evicted = set(state.get("sdc_evicted", []))

    def _snap_path(self, seq: int) -> str:
        return os.path.join(self.state_dir, f"coord-{seq:08d}.snap")

    def _wal_path(self, seq: int) -> str:
        return os.path.join(self.state_dir, f"coord-{seq:08d}.wal")

    def _snapshot_locked(self, now: float) -> None:
        """One snapshot + WAL rotation (caller holds the lock). The
        in-memory WAL mirror resets with the sequence number so
        repl_pull followers detect the rotation and pull a full
        snapshot."""
        self._snap_seq += 1
        self._last_snap = now
        if self.state_dir:
            payload = pickle.dumps(self.state_dict(now))
            _atomic_write(self._snap_path(self._snap_seq),
                          _SNAP_MAGIC + hashlib.sha256(payload).digest()
                          + payload)
            if self._wal_f is not None:
                try:
                    self._wal_f.close()
                except OSError:
                    pass
            self._wal_f = open(self._wal_path(self._snap_seq), "ab")
            # keep this snapshot and the previous one (the torn-newest
            # fallback); older generations are garbage
            for name in os.listdir(self.state_dir):
                mm = re.match(r"coord-(\d+)\.(snap|wal)$", name)
                if mm and int(mm.group(1)) <= self._snap_seq - 2:
                    try:
                        os.unlink(os.path.join(self.state_dir, name))
                    except OSError:
                        pass
        self._wal_mem = []
        self._wal_bytes = 0
        _REG.counter("coordinator_snapshots_total").inc()

    def snapshot(self, force: bool = False,
                 now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        with self.lock:
            if not force and now - self._last_snap < self.snapshot_secs:
                return
            self._snapshot_locked(now)

    def _mutated(self, verb: str, kw: dict) -> None:
        """One mutating verb landed: append it to the WAL (durable disk
        + the in-memory replication mirror) and maybe take a coalesced
        snapshot. No-op for the legacy in-launcher coordinator
        (incarnation 0) and during replay."""
        if self._replaying or self.incarnation <= 0:
            return
        with self.lock:
            rec = (verb, kw)
            self._wal_mem.append(rec)
            blob = pickle.dumps(rec)
            self._wal_bytes += 4 + len(blob)  # length prefix + payload
            if self._wal_f is not None:
                try:
                    self._wal_f.write(struct.pack(">I", len(blob)) + blob)
                    self._wal_f.flush()
                except OSError:
                    pass
            now = time.time()
            if (now - self._last_snap >= self.snapshot_secs
                    or len(self._wal_mem) > 4096
                    or (self.wal_max_bytes > 0
                        and self._wal_bytes >= self.wal_max_bytes)):
                self._snapshot_locked(now)

    def _apply(self, verb: str, kw: dict) -> None:
        """Replay one WAL record (recovery / replication). A bad record
        must not block recovery — everything it described is also in the
        next snapshot."""
        if verb not in self._WAL_VERBS:
            return
        try:
            if verb == "register":
                self.register(**kw)
            elif verb == "renew":
                self.renew(**kw)
            elif verb == "report_failure":
                self.report_failure(**kw)
            elif verb == "note_incident":
                self.note_incident(kw.get("incident") or {})
            elif verb == "ckpt_shard_commit":
                self.ckpt_barrier.shard_commit(**kw)
            elif verb == "sweep":
                self.sweep(**kw)
        except Exception:  # noqa: BLE001
            pass

    def _load_durable(self) -> None:
        """Recover from state_dir: newest intact snapshot (a torn newest
        falls back to the previous one), then the WAL tail(s) — wal-N
        holds mutations AFTER snap-N, so a fallback to snap-(N-1)
        replays wal-(N-1) and wal-N in order. Ends with the incarnation
        bump and the reconciliation window armed."""
        now = time.time()
        seqs = sorted(
            int(mm.group(1)) for name in os.listdir(self.state_dir)
            for mm in [re.match(r"coord-(\d+)\.snap$", name)] if mm)
        loaded, loaded_seq = None, 0
        for seq in reversed(seqs):
            state = _read_snapshot(self._snap_path(seq))
            if state is not None:
                loaded, loaded_seq = state, seq
                break
        prior_inc = 0
        if loaded is not None:
            prior_inc = int(loaded.get("incarnation", 0))
            self.load_state_dict(loaded, now=now)
            self._replaying = True
            try:
                for seq in [s for s in seqs if s >= loaded_seq]:
                    for verb, kw in _read_wal(self._wal_path(seq)):
                        self._apply(verb, kw)
            finally:
                self._replaying = False
        self._snap_seq = max(seqs) if seqs else 0
        self.incarnation = prior_inc + 1
        if loaded is not None:
            # reconciliation window: replayed register/renew recomputed
            # expiries from RECORDED times, and the outage itself ate
            # wall-clock — no lease may be declared expired until every
            # healthy member had EXPIRE_PERIODS renewal chances against
            # the recovered coordinator
            self._reconcile_until = (
                now + self.lease_secs * self.expire_periods)
            with self.lock:
                for m in self.members.values():
                    if not m.evicted:
                        m.expires = max(m.expires, self._reconcile_until)
                        m.expired_reported = False
                self._event(event="coord_recovered",
                            incarnation=self.incarnation,
                            snapshot_seq=loaded_seq,
                            members=len(self.members), epoch=self.epoch)
            _REG.counter("coordinator_recoveries_total").inc()

    # -- warm standby: replication + promotion (ISSUE 18) ----------------
    def repl_pull(self, have_seq: int = -1, have_off: int = 0) -> dict:
        """Primary side of the follower stream: a follower at (seq, off)
        gets the WAL records it is missing, or a full snapshot + WAL
        when its seq is stale (rotation happened, or first contact)."""
        with self.lock:
            out = {"seq": self._snap_seq, "incarnation": self.incarnation,
                   "role": self.role, "off": len(self._wal_mem)}
            if int(have_seq) != self._snap_seq:
                out["snapshot"] = self.state_dict()
                out["wal"] = list(self._wal_mem)
            else:
                out["wal"] = self._wal_mem[max(0, int(have_off)):]
            return out

    def repl_apply(self, pulled: dict,
                   now: Optional[float] = None) -> None:
        """Standby side: mirror one repl_pull reply (full snapshot when
        present, then the WAL tail), tracking the primary's seq and
        incarnation so promotion fences ABOVE everything seen."""
        now = time.time() if now is None else now
        with self.lock:
            self._replaying = True
            try:
                if pulled.get("snapshot") is not None:
                    self.load_state_dict(pulled["snapshot"], now=now)
                for verb, kw in pulled.get("wal") or []:
                    self._apply(verb, kw)
            finally:
                self._replaying = False
            self.incarnation = int(
                pulled.get("incarnation", self.incarnation))
            self._snap_seq = int(pulled.get("seq", self._snap_seq))

    def promote(self, now: Optional[float] = None) -> None:
        """Standby → primary. The fence bumps by TWO: a crash-respawned
        old primary bumps by one, so the promoted standby always wins
        the incarnation comparison (ties only on chained double
        failovers, which the ordered endpoint list still resolves by
        position). Arms the reconciliation window exactly like a
        respawn — the takeover must not falsely expire anyone either."""
        now = time.time() if now is None else now
        with self.lock:
            if self.role == "primary":
                return
            self.role = "primary"
            self.incarnation = int(self.incarnation) + 2
            self._reconcile_until = (
                now + self.lease_secs * self.expire_periods)
            for m in self.members.values():
                if not m.evicted:
                    m.expires = max(m.expires, self._reconcile_until)
                    m.expired_reported = False
            self._event(event="coord_promoted",
                        incarnation=self.incarnation, epoch=self.epoch)
            _REG.counter("coordinator_promotions_total").inc()
            if self.state_dir:
                os.makedirs(self.state_dir, exist_ok=True)
                self._snapshot_locked(now)

    def coord_status(self, now: Optional[float] = None) -> dict:
        """Control-plane self-description (debugz /statusz row)."""
        now = time.time() if now is None else now
        with self.lock:
            return {
                "incarnation": self.incarnation,
                "role": self.role,
                "stale": self.stale_latched,
                "durable": bool(self.state_dir),
                "epoch": self.epoch,
                "members": len(self.members),
                "snapshot_seq": self._snap_seq,
                "last_snapshot_age_s": (round(now - self._last_snap, 3)
                                        if self._last_snap else None),
                "wal_records": len(self._wal_mem),
                "wal_bytes": self._wal_bytes,
                "reconcile_remaining_s": round(
                    max(0.0, self._reconcile_until - now), 3),
            }

    def _check_client_incarnation(self, coord_inc, tag: str) -> None:
        """A member claiming a HIGHER coordinator incarnation has talked
        to a newer coordinator — THIS one was deposed (it crashed and a
        standby promoted over it, or it is a stale standby). Latch stale
        (the PS StaleEpoch pattern one layer up): authority verbs stop
        granting, sweeps stop expiring, and clients reject the latched
        replies."""
        if not coord_inc or self._replaying:
            return
        ci = int(coord_inc)
        if self.incarnation and ci > self.incarnation \
                and not self.stale_latched:
            self.stale_latched = True
            self._event(event="stale_coordinator_incarnation", tag=tag,
                        claimed=ci, incarnation=self.incarnation)
            _REG.counter("coordinator_stale_incarnation_total").inc()

    # -- verbs (also called directly by the launcher) --------------------
    def register(self, tag: str, kind: str = "trainer",
                 endpoint: Optional[str] = None, payload: Optional[dict] = None,
                 epoch: Optional[int] = None, now: Optional[float] = None,
                 coord_inc=None):
        """(Re)grant a lease. Registration is identity-stable: a
        respawned process re-registers under its old tag and keeps its
        failure count (budgets outlive incarnations). An EVICTED tag is
        told so — the member must not keep working. Registration is also
        the grace-mode reconnect verb: re-registering an existing tag is
        idempotent (budgets and payloads survive)."""
        now = time.time() if now is None else now
        with self.lock:
            self._check_client_incarnation(coord_inc, tag)
            if self.stale_latched:
                return {"epoch": self.epoch, "lease_secs": self.lease_secs,
                        "evicted": False, "stale_coordinator": True}
            m = self._get(tag, kind, endpoint, now)
            m.kind = kind
            if endpoint:
                m.endpoint = endpoint
            if payload is not None:
                m.payload = dict(payload)
            self._mutated("register", {
                "tag": tag, "kind": kind, "endpoint": endpoint,
                "payload": payload, "now": now})
            if m.evicted:
                return {"epoch": self.epoch, "lease_secs": self.lease_secs,
                        "evicted": True}
            m.alive = True
            m.expired_reported = False
            # fresh registrations get the startup grace, renewals the
            # plain lease window — registration IS process (re)birth
            m.expires = now + max(self.startup_grace,
                                  self.lease_secs * self.expire_periods)
            _REG.counter("coordinator_registrations_total",
                         kind=kind).inc()
            return {"epoch": self.epoch, "lease_secs": self.lease_secs,
                    "evicted": False}

    def renew(self, tag: str, payload: Optional[dict] = None,
              epoch: Optional[int] = None, now: Optional[float] = None,
              coord_inc=None):
        """One lease renewal — the heartbeat stamp as an RPC. The
        payload is stored verbatim (step/avg_step_s for trainers,
        partition replica summaries for pservers). A renewal claiming a
        FUTURE membership epoch does NOT refresh the lease: a newer
        coordinator owns that member and this one is stale
        (split-brain guard). Same rule one layer up: a renewal claiming
        a future coordinator INCARNATION latches this coordinator
        stale."""
        now = time.time() if now is None else now
        ep = membership_epoch_from_env() if epoch is None else int(epoch)
        with self.lock:
            self._check_client_incarnation(coord_inc, tag)
            if self.stale_latched:
                return {"epoch": self.epoch, "evicted": False,
                        "stale_coordinator": True}
            m = self._get(tag, now=now)
            if payload is not None:
                m.payload = dict(payload)
            self._mutated("renew", {"tag": tag, "payload": payload,
                                    "epoch": ep, "now": now})
            if m.evicted:
                _REG.counter("coordinator_evicted_renewals_total").inc()
                return {"epoch": self.epoch, "evicted": True}
            if ep > self.epoch:
                _REG.counter("coordinator_stale_renewals_total").inc()
                if not m.stale_reported:
                    m.stale_reported = True
                    self._event(event="stale_coordinator", tag=tag,
                                member_epoch=ep, epoch=self.epoch)
                return {"epoch": self.epoch, "evicted": False,
                        "stale_coordinator": True}
            m.alive = True
            m.expired_reported = False
            m.last_renew = now
            m.expires = self._deadline(now)
            _REG.counter("coordinator_renewals_total", kind=m.kind).inc()
            return {"epoch": self.epoch, "evicted": False}

    def membership(self, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        with self.lock:
            trainers = [t for t, m in self.members.items()
                        if m.kind == "trainer" and not m.evicted]
            return {
                "epoch": self.epoch,
                "lease_secs": self.lease_secs,
                "retries_per_rank": self.retries_per_rank,
                "world_size": len(trainers),
                "members": {t: m.status(now)
                            for t, m in sorted(self.members.items())},
            }

    def report_failure(self, tag: str, reason: str = "") -> dict:
        """The launcher observed a failure (nonzero exit, stale
        heartbeat, expired lease, straggler ejection) for `tag`. The
        coordinator owns the budget: within the per-rank budget the
        member may be restarted; past it the member is EVICTED and the
        membership epoch bumps — the elastic-resize signal."""
        with self.lock:
            m = self._get(tag)
            m.alive = False
            m.failures += 1
            self._mutated("report_failure", {"tag": tag, "reason": reason})
            evicted = m.failures > self.retries_per_rank
            if evicted and not m.evicted:
                m.evicted = True
                self.epoch += 1
                _REG.counter("coordinator_evictions_total").inc()
                self._event(event="member_evicted", tag=tag, reason=reason,
                            failures=m.failures, epoch=self.epoch)
            elif not evicted:
                self._event(event="member_failed", tag=tag, reason=reason,
                            failures=m.failures,
                            retries_left=self.retries_per_rank - m.failures)
            return {"evicted": m.evicted, "epoch": self.epoch,
                    "failures": m.failures,
                    "retries_left": max(
                        0, self.retries_per_rank - m.failures)}

    def expired_tags(self, now: Optional[float] = None,
                     kind: Optional[str] = None) -> List[str]:
        now = time.time() if now is None else now
        with self.lock:
            return [t for t, m in self.members.items()
                    if m.alive and not m.evicted and now > m.expires
                    and (kind is None or m.kind == kind)]

    def drain_events(self) -> List[dict]:
        with self.lock:
            out, self.events = list(self.events), deque(maxlen=512)
            return out

    # -- fleet metrics aggregation (ISSUE 15) ----------------------------
    def note_incident(self, ev: dict) -> dict:
        """The launcher (or a tool) records one badput incident —
        straggler stall episodes and restart windows land here so the
        fleet view cites the same evidence goodtop stitches."""
        ev = dict(ev)
        ev.setdefault("event", "stall")
        with self.lock:
            self._event(**ev)
            self._mutated("note_incident", {"incident": dict(ev)})
        return {"ok": True}

    def fleet_status(self) -> dict:
        """The one-endpoint fleet rollup: per-rank rows merged from the
        latest renewal payloads (step progress, goodput summaries),
        job-level goodput ratio + badput-by-cause, and the retained
        incident list — debugz /fleetz renders this verbatim."""
        from ..telemetry import goodput as _goodput

        now = time.time()
        with self.lock:
            payloads = {t: (dict(m.payload) if m.payload else None)
                        for t, m in self.members.items()}
            meta = {t: {"kind": m.kind, "alive": m.alive,
                        "evicted": m.evicted,
                        "lease_remaining_s": round(m.expires - now, 3)}
                    for t, m in self.members.items()}
            incidents = list(self.incidents)
            epoch = self.epoch
        merged = _goodput.merge_fleet(payloads)
        for tag, row in merged["ranks"].items():
            row.update(meta.get(tag, {}))
        merged["epoch"] = epoch
        merged["world_size"] = sum(
            1 for m in meta.values()
            if m["kind"] == "trainer" and not m["evicted"])
        merged["incidents"] = sorted(
            incidents, key=lambda e: e.get("ts", 0), reverse=True)
        outages = [e for e in incidents
                   if e.get("event") == "coord_outage"]
        if outages:
            # badput-visibility note (ISSUE 18): renewal payloads sent
            # during a control-plane outage were lost — the rollup
            # UNDER-reports badput for those windows, and /fleetz says so
            merged["coord_outage_note"] = (
                f"{len(outages)} coordinator outage window(s) "
                f"({round(sum(e.get('gap_s') or 0 for e in outages), 1)}s"
                " total): fleet badput during an outage is under-reported"
                " — renewal payloads were lost while the control plane"
                " was down")
        merged["ts"] = round(now, 6)
        return merged

    def fleet_metrics(self) -> str:
        """Fleet-wide Prometheus exposition: every member's bounded
        snapshot with a rank label, plus goodput rollup lines — ONE
        scrape target instead of N per-rank /metrics pages."""
        from ..telemetry import goodput as _goodput

        with self.lock:
            payloads = {t: (dict(m.payload) if m.payload else None)
                        for t, m in self.members.items()}
        return _goodput.fleet_prometheus(payloads)

    # -- cross-replica SDC detection (ISSUE 12) --------------------------
    def numerics_report(self, tag: str, step: int, fingerprint: dict,
                        world_size: int = 0) -> dict:
        """One rank's params+merged-grad fingerprint for `step`. On a
        checksum mismatch across ranks: one structured `divergence`
        event naming the odd-rank-out, a counter, and — when
        PADDLE_SDC_EVICT is set in the coordinator's process — the
        corrupted rank is routed to the elastic eviction path exactly
        like a host whose lease expired past its budget."""
        out = self.fingerprints.record(step, tag, fingerprint,
                                       world_size)
        ev = out.get("event")
        if ev is not None and not any(
                e.get("event") == "divergence"
                and e.get("step") == ev["step"]
                for e in self.events):
            with self.lock:
                self._event(**ev)
            _REG.counter("coordinator_sdc_divergence_total",
                         help="SDC divergence events raised").inc()
        if ev is not None and os.environ.get(
                "PADDLE_SDC_EVICT", "") not in ("", "0", "false"):
            for odd in ev.get("odd_rank_out") or []:
                if odd in self._sdc_evicted:
                    continue
                self._sdc_evicted.add(odd)
                # past-budget failure report = eviction + epoch bump:
                # the launcher's next watch tick restarts the
                # survivors without the corrupted rank
                for _ in range(self.retries_per_rank + 1):
                    self.report_failure(odd, reason="sdc_divergence")
        return out

    def numerics_status(self) -> dict:
        return self.fingerprints.status()

    # -- lease sweep + pserver primary election --------------------------
    def sweep(self, now: Optional[float] = None) -> List[dict]:
        """One supervision tick: find expired leases, emit one
        `lease_expired` event per lapse, and for every expired PSERVER
        that held partition primaries, elect + promote a caught-up
        backup (the ROADMAP "promote without a client in the loop"
        path). Returns the events raised by THIS tick. The launcher
        calls this on its watch cadence; tests drive it with an
        explicit `now`.

        Crash tolerance (ISSUE 18): inside the post-recovery
        RECONCILIATION WINDOW no lease may be declared expired — every
        replayed/restored expiry is an artifact of the outage until the
        member had its full expiry window against the RECOVERED
        coordinator. A stale-latched (deposed) coordinator and an
        unpromoted standby exercise no expiry authority at all."""
        now = time.time() if now is None else now
        if not self._replaying:
            if self.stale_latched or self.role == "standby":
                return []
            if now < self._reconcile_until:
                return []
        raised: List[dict] = []
        elect: List[_Member] = []
        with self.lock:
            for tag, m in self.members.items():
                if m.evicted or not m.alive or now <= m.expires:
                    continue
                if m.expired_reported:
                    continue
                m.expired_reported = True
                ev = {"event": "lease_expired", "tag": tag, "kind": m.kind,
                      "overdue_s": round(now - m.expires, 3)}
                self._event(**ev)
                raised.append(ev)
                _REG.counter("coordinator_lease_expiries_total",
                             kind=m.kind).inc()
                if m.kind == "pserver":
                    m.alive = False  # stops being an election candidate
                    elect.append(m)
        for dead in elect:
            raised.extend(self._elect_primaries(dead))
        if raised:
            # no-op sweeps (the launcher's 0.2s cadence) mutate nothing
            # and must not bloat the WAL; a sweep that RAISED is state
            self._mutated("sweep", {"now": now})
        return raised

    def _partition_view(self, key: str):
        """(candidates, epochs, backups) for one partition key from the
        latest renewal payloads — candidates are caught-up live backups,
        epochs every epoch seen, backups the live replica endpoints."""
        cands, epochs, backups = [], [0], []
        with self.lock:
            for m in self.members.values():
                if m.kind != "pserver":
                    continue
                st = ((m.payload or {}).get("partitions") or {}).get(key)
                if st is None:
                    continue
                epochs.append(int(st.get("epoch", 0)))
                if not m.alive or m.evicted or not m.endpoint:
                    continue
                backups.append(m.endpoint)
                if st.get("role") == "backup" and not st.get("stale"):
                    cands.append((int(st.get("epoch", 0)),
                                  int(st.get("seq", 0)), m))
        return cands, epochs, backups

    def _elect_primaries(self, dead: _Member) -> List[dict]:
        """Promote a backup for every partition the dead pserver led.
        Runs OUTSIDE the coordinator lock (promote is a real RPC)."""
        parts = (dead.payload or {}).get("partitions") or {}
        raised: List[dict] = []
        for key, st in sorted(parts.items()):
            if st.get("role") != "primary":
                continue
            cands, epochs, backups = self._partition_view(key)
            if not cands:
                ev = {"event": "ps_promotion_skipped", "key": key,
                      "from": dead.tag, "reason": "no caught-up backup"}
                with self.lock:
                    self._event(**ev)
                raised.append(ev)
                continue
            cands.sort()
            _, seq, target = cands[-1]
            new_epoch = max(epochs) + 1
            name, _, part = key.rpartition("@p")
            try:
                if not self._replaying:
                    # WAL replay / replication apply rebuilds the GRANT
                    # REFLECTION only — the promote RPC already happened
                    # in the previous incarnation
                    from .ps_server import _Conn

                    conn = _Conn(target.endpoint, deadline=5.0,
                                 io_timeout=10.0)
                    try:
                        conn.call("promote", name=name,
                                  partition=int(part), epoch=new_epoch,
                                  backups=[b for b in backups
                                           if b != target.endpoint])
                    finally:
                        conn.close()
            except Exception as e:  # noqa: BLE001 — election must not
                # take the launcher down; the next sweep retries nothing
                # (the client-driven failover path still exists)
                ev = {"event": "ps_promotion_failed", "key": key,
                      "from": dead.tag, "to": target.tag,
                      "error": f"{type(e).__name__}: {e}"}
                with self.lock:
                    self._event(**ev)
                raised.append(ev)
                continue
            _REG.counter("coordinator_ps_promotions_total").inc()
            ev = {"event": "ps_promoted", "key": key, "from": dead.tag,
                  "to": target.tag, "epoch": new_epoch, "seq": seq}
            with self.lock:
                self._event(**ev)
                # reflect the grant locally so a repeated sweep (the
                # dead server stays dead) does not re-promote; the next
                # real renewal from the target carries the truth anyway
                tparts = (target.payload or {}).setdefault("partitions", {})
                tparts.setdefault(key, {})["role"] = "primary"
                tparts[key]["epoch"] = new_epoch
                dparts = (dead.payload or {}).get("partitions") or {}
                if key in dparts:
                    dparts[key]["role"] = None
            raised.append(ev)
        return raised

    # verbs that exercise (or mutate) membership/commit AUTHORITY: an
    # unpromoted standby and a stale-latched deposed primary refuse
    # them with a reply that makes the client rotate down its endpoint
    # list (read-only verbs still answer — debugz works on a standby)
    _AUTHORITY_VERBS = frozenset((
        "register", "renew", "report_failure", "note_incident",
        "numerics_report", "sweep",
    ))

    # -- RPC dispatch (ps_server._Handler contract) ----------------------
    def handle(self, method: str, kwargs: dict):
        from . import faults

        inj = faults.injector()
        if inj is not None:
            inj.on_server_call(method)
            # deterministic chaos site: `crash:coord_verb:<nth>` kills
            # the process-hosted coordinator at its Nth handled verb
            # (the kill-and-respawn drill)
            inj.at_phase("coord_verb")
        result = self._dispatch(method, kwargs)
        if self.incarnation > 0 and isinstance(result, dict):
            # the fence rides every reply; absent entirely on the
            # legacy in-launcher coordinator (incarnation 0), keeping
            # the default wire format byte-identical
            result.setdefault("coord_incarnation", self.incarnation)
            if self.stale_latched:
                result.setdefault("stale_coordinator", True)
        return result

    def _dispatch(self, method: str, kwargs: dict):
        if method == "ping":
            return "pong"
        if self.role == "standby" and (method in self._AUTHORITY_VERBS
                                       or method.startswith("ckpt_")):
            # followers hold state but no authority until promoted
            return {"standby": True, "epoch": self.epoch}
        if method.startswith("ckpt_"):
            # sharded-checkpoint commit barrier rides the same port
            if self.stale_latched:
                # a deposed primary must not swallow commit reports —
                # "standby" makes _RPCBarrier rotate to the new primary
                return {"standby": True, "epoch": self.epoch}
            out = self.ckpt_barrier.handle(method, kwargs)
            if method == "ckpt_shard_commit":
                self._mutated("ckpt_shard_commit", {
                    "step": kwargs["step"], "rank": kwargs["rank"],
                    "world_size": kwargs["world_size"],
                    "info": kwargs.get("info")})
            return out
        if method == "register":
            return self.register(
                kwargs["tag"], kwargs.get("kind", "trainer"),
                kwargs.get("endpoint"), kwargs.get("payload"),
                kwargs.get("epoch"), coord_inc=kwargs.get("coord_inc"))
        if method == "renew":
            return self.renew(kwargs["tag"], kwargs.get("payload"),
                              kwargs.get("epoch"),
                              coord_inc=kwargs.get("coord_inc"))
        if method == "membership":
            return self.membership()
        if method == "report_failure":
            return self.report_failure(kwargs["tag"],
                                       kwargs.get("reason", ""))
        if method == "fleet_status":
            return self.fleet_status()
        if method == "fleet_metrics":
            return self.fleet_metrics()
        if method == "note_incident":
            return self.note_incident(kwargs.get("incident") or {})
        if method == "numerics_report":
            return self.numerics_report(
                kwargs["tag"], kwargs["step"], kwargs["fingerprint"],
                kwargs.get("world_size", 0))
        if method == "numerics_status":
            return self.numerics_status()
        if method == "sweep":
            return self.sweep(kwargs.get("now"))
        if method == "events":
            return self.drain_events()
        if method == "coord_status":
            return self.coord_status()
        if method == "repl_pull":
            return self.repl_pull(kwargs.get("have_seq", -1),
                                  kwargs.get("have_off", 0))
        if method == "shutdown":
            self.shutdown_event.set()
            return 0
        raise ValueError(f"unknown coordinator method {method!r}")


def serve_coordinator(coord: Coordinator, host: str = "127.0.0.1",
                      port: int = 0):
    """Host `coord` over the ps_server TCP transport (daemon thread).
    Returns (server, "host:port"). The launcher exports the endpoint as
    PADDLE_COORDINATOR_ENDPOINT so members can renew."""
    from .ps_server import _Handler, _TCPServer

    srv = _TCPServer((host, port), _Handler)
    srv.ps = coord  # type: ignore[attr-defined] — _Handler contract
    threading.Thread(target=srv.serve_forever,
                     kwargs={"poll_interval": 0.1}, daemon=True,
                     name="paddle-tpu-coordinator").start()
    return srv, f"{host}:{srv.server_address[1]}"


def stop_coordinator(srv) -> None:
    try:
        srv.shutdown()
        srv.close_all_connections()
        srv.server_close()
    except Exception:  # noqa: BLE001 — teardown best-effort
        pass


# ---------------------------------------------------------------------------
# member side
# ---------------------------------------------------------------------------


class CoordinatorClient:
    """Thin member-side client: register once, renew on a cadence. All
    RPCs ride ps_server._Conn (retries, deadline, telemetry), and every
    renewal consults faults.injector() so a `lease_expire:<tag>:<nth>`
    rule can swallow renewals deterministically (the lease-expiry
    drill) without touching the process's real liveness.

    Outage tolerance (ISSUE 18): `endpoint` may be an ordered
    comma-separated list (primary first, warm standby second). Every
    verb fails over down the list — always on a FRESH socket, because a
    coordinator respawned on the same port shares nothing with the dead
    connection — and a transport failure on `renew` puts the client in
    GRACE MODE: the error still propagates (callers like LeaseWorker /
    HeartBeatWorker swallow it and training continues), the payload is
    buffered, and the first successful contact re-registers
    idempotently before renewing so a recovered or promoted coordinator
    re-learns this member. Split-brain fence: the client tracks the
    highest coordinator incarnation it has seen and REJECTS replies
    from a lower one (a deposed primary)."""

    def __init__(self, endpoint: str, tag: Optional[str] = None,
                 kind: str = "trainer", self_endpoint: Optional[str] = None,
                 deadline: Optional[float] = None):
        self.endpoint = endpoint
        self.endpoints = [e.strip() for e in str(endpoint).split(",")
                          if e.strip()]
        self.tag = tag or member_tag()
        self.kind = kind
        self.self_endpoint = self_endpoint
        self.deadline = (call_deadline_from_env() if deadline is None
                         else float(deadline))
        self.grace = False
        self.last_incarnation = 0
        self.last_epoch = 0
        self._idx = 0
        self._buffered_payload: Optional[dict] = None
        self._conn = self._connect()

    def _connect(self):
        from .ps_server import _Conn

        ep = self.endpoints[self._idx % len(self.endpoints)]
        return _Conn(ep, deadline=self.deadline,
                     io_timeout=self.deadline + 10.0)

    def _rotate(self) -> None:
        """Drop the (possibly dead) socket and move to the next endpoint
        in the ordered list — a respawned or promoted coordinator is
        reached on a fresh connection, never by retrying a dead one to
        exhaustion."""
        try:
            self._conn.close()
        except Exception:  # noqa: BLE001
            pass
        self._idx = (self._idx + 1) % len(self.endpoints)
        self._conn = self._connect()

    def _id_kwargs(self) -> dict:
        # the incarnation view rides identity verbs ONLY once the client
        # has actually seen one (durable mode) — a legacy coordinator
        # never sends it, so the legacy wire format stays byte-identical
        if self.last_incarnation:
            return {"coord_inc": self.last_incarnation}
        return {}

    def call(self, verb: str, **kw):
        """One verb with endpoint failover + incarnation fencing. Raises
        ConnectionError once every endpoint failed (each attempt is
        bounded by the PADDLE_COORD_CALL_DEADLINE_SECS deadline, so a
        coordinator outage can never block a caller to exhaustion)."""
        last: Optional[Exception] = None
        for _ in range(len(self.endpoints)):
            try:
                out = self._conn.call(verb, **kw)
            except ConnectionError as e:
                last = e
                self._rotate()
                continue
            if isinstance(out, dict):
                inc = int(out.get("coord_incarnation") or 0)
                if inc and inc < self.last_incarnation:
                    # deposed primary (a newer incarnation exists):
                    # reject the reply — the split-brain fence
                    _REG.counter(
                        "coordinator_client_stale_replies_total").inc()
                    last = ConnectionError(
                        f"stale coordinator incarnation {inc} < "
                        f"{self.last_incarnation}")
                    self._rotate()
                    continue
                if out.get("standby"):
                    # an unpromoted follower holds no authority yet
                    last = ConnectionError(
                        "coordinator endpoint is an unpromoted standby")
                    self._rotate()
                    continue
                if inc > self.last_incarnation:
                    if self.last_incarnation:
                        # the coordinator restarted or a standby took
                        # over: re-introduce ourselves on the next renew
                        self.grace = True
                        _REG.counter(
                            "coordinator_client_incarnation_bumps_total"
                        ).inc()
                    self.last_incarnation = inc
                try:
                    self.last_epoch = max(self.last_epoch,
                                          int(out.get("epoch") or 0))
                except (TypeError, ValueError):
                    pass
            return out
        raise last if last is not None else ConnectionError(
            "coordinator unreachable")

    def register(self, payload: Optional[dict] = None) -> dict:
        if payload is not None:
            self._buffered_payload = dict(payload)
        out = self.call(
            "register", tag=self.tag, kind=self.kind,
            endpoint=self.self_endpoint, payload=payload,
            epoch=membership_epoch_from_env(), **self._id_kwargs())
        self.grace = False
        return out

    def renew(self, payload: Optional[dict] = None) -> dict:
        from . import faults

        inj = faults.injector()
        if inj is not None and inj.on_lease_renew():
            # swallowed client-side: the coordinator never sees it, the
            # lease runs out — exactly what a silently-dead host does
            _REG.counter("coordinator_client_renewals_suppressed_total").inc()
            return {"suppressed": True}
        if payload is not None:
            self._buffered_payload = dict(payload)
        try:
            if self.grace:
                # grace-mode reconnect: re-register idempotently (with
                # the last buffered payload) so a recovered/promoted
                # coordinator re-learns this member BEFORE the renewal
                self.call(
                    "register", tag=self.tag, kind=self.kind,
                    endpoint=self.self_endpoint,
                    payload=payload if payload is not None
                    else self._buffered_payload,
                    epoch=membership_epoch_from_env(),
                    **self._id_kwargs())
                self.grace = False
                _REG.counter(
                    "coordinator_client_reconnects_total").inc()
            out = self.call(
                "renew", tag=self.tag, payload=payload,
                epoch=membership_epoch_from_env(), **self._id_kwargs())
        except ConnectionError:
            # GRACE MODE: training/serving continue; the renewal is
            # buffered and replayed as a re-register on reconnect. The
            # error still propagates — LeaseWorker/HeartBeatWorker
            # swallow it, and the netsplit drill asserts it raises.
            if not self.grace:
                self.grace = True
                _REG.counter(
                    "coordinator_client_grace_entries_total").inc()
            _REG.counter(
                "coordinator_client_grace_renewals_total").inc()
            raise
        if isinstance(out, dict) and out.get("evicted"):
            # lease-expiry eviction: this member is out of the job —
            # dump the flight record NOW, while the spans that led here
            # are still in the ring (no-op unless tracing is armed)
            from ..telemetry import tracing

            tracing.flight_dump("lease_evicted")
        return out

    def membership(self) -> dict:
        return self.call("membership")

    def numerics_report(self, step: int, fingerprint: dict,
                        world_size: int = 0) -> dict:
        """Publish one SDC fingerprint (telemetry/numerics.SDCReporter
        drives this on the PADDLE_SDC_CHECK_EVERY cadence)."""
        return self.call(
            "numerics_report", tag=self.tag, step=step,
            fingerprint=fingerprint, world_size=world_size)

    def numerics_status(self) -> dict:
        return self.call("numerics_status")

    def fleet_status(self) -> dict:
        return self.call("fleet_status")

    def fleet_metrics(self) -> str:
        return self.call("fleet_metrics")

    def note_incident(self, incident: dict) -> dict:
        return self.call("note_incident", incident=incident)

    def close(self) -> None:
        self._conn.close()


class LeaseWorker:
    """Daemon renewal thread for processes without a heartbeat worker
    cadence of their own (pservers; lease-only trainers). Registration
    + renewals never raise — a flapping coordinator must not take a
    healthy member down."""

    def __init__(self, client: CoordinatorClient, interval: float,
                 payload_fn: Optional[Callable[[], dict]] = None):
        self.client = client
        self.interval = max(0.05, float(interval))
        self.payload_fn = payload_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _payload(self) -> Optional[dict]:
        out = None
        if self.payload_fn is not None:
            try:
                out = self.payload_fn()
            except Exception:  # noqa: BLE001
                out = None
        try:
            # fleet aggregation (ISSUE 15): pservers and serving
            # replicas ship the same bounded snapshot + ledger summary
            # trainers ride on heartbeat renewals; off = unchanged
            from ..telemetry import goodput as _goodput

            extra = _goodput.fleet_payload()
            if extra:
                out = dict(out or {})
                out.update(extra)
        except Exception:  # noqa: BLE001 — accounting only
            pass
        return out

    def start(self) -> "LeaseWorker":
        if self._thread is not None:
            return self
        try:
            self.client.register(payload=self._payload())
        except Exception:  # noqa: BLE001 — renewals retry registration
            pass

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.client.renew(payload=self._payload())
                except Exception:  # noqa: BLE001 — keep renewing
                    continue

        self._thread = threading.Thread(
            target=loop, daemon=True,
            name=f"paddle-tpu-lease-{self.client.tag}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self.client.close()


def maybe_start_lease_worker(kind: str, tag: Optional[str] = None,
                             self_endpoint: Optional[str] = None,
                             payload_fn: Optional[Callable[[], dict]] = None,
                             ) -> Optional[LeaseWorker]:
    """Start lease renewals when the launcher armed the control plane
    (PADDLE_COORDINATOR_ENDPOINT + PADDLE_LEASE_SECS); no-op (two env
    reads) otherwise. Renewal cadence is lease_secs/3 so a healthy
    member always lands well inside the expiry window."""
    endpoint = os.environ.get(ENV_ENDPOINT)
    lease = lease_secs_from_env()
    if not endpoint or lease <= 0:
        return None
    client = CoordinatorClient(endpoint, tag=tag, kind=kind,
                               self_endpoint=self_endpoint)
    return LeaseWorker(client, interval=lease / 3.0,
                       payload_fn=payload_fn).start()


def query_membership(timeout: float = 2.0) -> Optional[dict]:
    """The coordinator's membership table, or None when no control
    plane is armed / reachable (status pages must never crash)."""
    return _query("membership", timeout)


def query_fleet(timeout: float = 2.0) -> Optional[dict]:
    """The coordinator's fleet rollup (debugz /fleetz), or None when no
    control plane is armed / reachable."""
    return _query("fleet_status", timeout)


def query_fleet_metrics(timeout: float = 2.0) -> Optional[str]:
    """The fleet-wide Prometheus exposition (debugz /fleetz/metrics),
    or None when no control plane is armed / reachable."""
    return _query("fleet_metrics", timeout)


def query_coord_status(timeout: float = 2.0) -> Optional[dict]:
    """The coordinator's control-plane self-description — incarnation,
    role, snapshot age (debugz /statusz row) — or None when no control
    plane is armed / reachable."""
    return _query("coord_status", timeout)


def _query(verb: str, timeout: float):
    endpoint = os.environ.get(ENV_ENDPOINT)
    if not endpoint:
        return None
    try:
        client = CoordinatorClient(endpoint, deadline=timeout)
        try:
            return client.call(verb)
        finally:
            client.close()
    except Exception:  # noqa: BLE001
        return None


# ---------------------------------------------------------------------------
# warm standby follower + launcher-side proxy (ISSUE 18)
# ---------------------------------------------------------------------------


class CoordinatorFollower:
    """Standby-side replication: poll the primary's `repl_pull` stream
    on the renewal cadence, mirror snapshot+WAL into the local
    Coordinator, and SELF-PROMOTE once the primary's own incarnation
    lease lapses — the same expiry rule members live under
    (expire_periods lease periods with no successful contact)."""

    def __init__(self, coord: Coordinator, primary_endpoint: str,
                 interval: Optional[float] = None):
        self.coord = coord
        self.endpoint = primary_endpoint
        self.interval = (max(0.05, coord.lease_secs / 3.0)
                         if interval is None else max(0.05, interval))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._have = (-1, 0)

    def start(self) -> "CoordinatorFollower":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="paddle-tpu-coord-follower")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _loop(self) -> None:
        from .ps_server import _Conn

        deadline = call_deadline_from_env()
        lapse = self.coord.lease_secs * self.coord.expire_periods
        last_ok = time.time()
        conn = None
        while not self._stop.wait(self.interval):
            if self.coord.role == "primary":
                return  # promoted (possibly by a test) — stop following
            try:
                if conn is None:
                    conn = _Conn(self.endpoint, deadline=deadline,
                                 io_timeout=deadline + 10.0)
                out = conn.call("repl_pull", have_seq=self._have[0],
                                have_off=self._have[1])
                self.coord.repl_apply(out)
                self._have = (int(out["seq"]), int(out["off"]))
                last_ok = time.time()
                _REG.counter("coordinator_repl_pulls_total").inc()
            except Exception:  # noqa: BLE001 — the primary is flapping
                if conn is not None:
                    try:
                        conn.close()
                    except Exception:  # noqa: BLE001
                        pass
                    conn = None  # fresh socket on the next attempt
                if time.time() - last_ok > lapse:
                    # the primary's incarnation lease lapsed: take over
                    print("[coordinator] primary unreachable for "
                          f"{round(time.time() - last_ok, 1)}s — "
                          "standby promoting itself", file=sys.stderr,
                          flush=True)
                    self.coord.promote()
                    return


class CoordinatorProxy:
    """Launcher-side handle on a PROCESS-hosted coordinator (durable
    mode): the same surface the launcher uses on the in-process object
    (register / report_failure / sweep / note_incident / fleet_* /
    epoch), backed by CoordinatorClient with endpoint failover. Every
    verb degrades gracefully on an outage — training must continue
    while the supervisor respawns the coordinator — and the proxy
    timestamps outage windows so recovery lands one `coord_outage`
    incident in both the fleet ledger and the coordinator's incident
    ring (the goodtop/goodput badput-visibility trail)."""

    def __init__(self, endpoint: str, lease_secs: float,
                 retries_per_rank: int, ledger=None):
        self.lease_secs = float(lease_secs)
        self.retries_per_rank = int(retries_per_rank)
        self.ledger = ledger
        # a short deadline: the watch loop must keep reaping trainers
        # while the control plane is down
        self.client = CoordinatorClient(
            endpoint, tag="launcher", kind="launcher",
            deadline=min(call_deadline_from_env(),
                         max(0.3, self.lease_secs / 2.0)))
        self.unreachable_since: Optional[float] = None
        self._pending_failures: List[Tuple[str, str]] = []
        self._last_sweep = 0.0
        # sweep over RPC rides the renewal cadence, not the launcher's
        # 0.2s watch tick — expiry granularity stays well inside the
        # expire_periods window
        self._sweep_interval = min(2.0, max(0.1, self.lease_secs / 3.0))

    @property
    def epoch(self) -> int:
        return self.client.last_epoch

    def _down(self) -> None:
        if self.unreachable_since is None:
            self.unreachable_since = time.time()
            _REG.counter("coordinator_outages_total").inc()

    def _recovered(self) -> None:
        """First successful verb after an outage: record ONE
        coord_outage incident (ledger + incident ring)."""
        if self.unreachable_since is None:
            return
        now = time.time()
        ev = {"event": "coord_outage",
              "detect_ts": round(self.unreachable_since, 6),
              "respawn_ts": round(now, 6),
              "gap_s": round(now - self.unreachable_since, 3),
              "incarnation": self.client.last_incarnation}
        self.unreachable_since = None
        print(f"[launch] coordinator reachable again after "
              f"{ev['gap_s']}s outage (incarnation "
              f"{ev['incarnation']})", file=sys.stderr, flush=True)
        if self.ledger is not None:
            try:
                self.ledger.event(**ev)
            except Exception:  # noqa: BLE001 — accounting only
                pass
        try:
            self.client.note_incident(dict(ev))
        except ConnectionError:
            self._down()

    def _flush_pending(self) -> None:
        # failure reports observed during an outage: charge the budgets
        # now, in order (raises out to the caller's handler if the
        # coordinator dropped again — the queue survives)
        while self._pending_failures:
            tag, reason = self._pending_failures[0]
            self.client.call("report_failure", tag=tag, reason=reason)
            self._pending_failures.pop(0)

    def register(self, tag: str, kind: str = "trainer",
                 endpoint: Optional[str] = None,
                 payload: Optional[dict] = None) -> dict:
        try:
            out = self.client.call(
                "register", tag=tag, kind=kind, endpoint=endpoint,
                payload=payload, **self.client._id_kwargs())
            self._recovered()
            return out
        except ConnectionError:
            self._down()
            return {"epoch": self.epoch, "evicted": False,
                    "lease_secs": self.lease_secs, "deferred": True}

    def report_failure(self, tag: str, reason: str = "") -> dict:
        try:
            self._flush_pending()
            out = self.client.call("report_failure", tag=tag,
                                   reason=reason)
            self._recovered()
            return out
        except ConnectionError:
            self._down()
            self._pending_failures.append((tag, reason))
            # optimistic verdict: never evict blind — the report is
            # queued and the budget charged on reconnect
            return {"evicted": False, "epoch": self.epoch,
                    "failures": -1,
                    "retries_left": self.retries_per_rank,
                    "deferred": True}

    def sweep(self) -> List[dict]:
        now = time.time()
        if now - self._last_sweep < self._sweep_interval:
            return []
        self._last_sweep = now
        try:
            self._flush_pending()
            out = self.client.call("sweep")
            self._recovered()
        except ConnectionError:
            self._down()
            return []
        if not isinstance(out, list):
            return []
        for ev in out:
            if isinstance(ev, dict) and ev.get("epoch"):
                try:
                    self.client.last_epoch = max(
                        self.client.last_epoch, int(ev["epoch"]))
                except (TypeError, ValueError):
                    pass
        return out

    def note_incident(self, ev: dict) -> dict:
        try:
            out = self.client.note_incident(dict(ev))
            self._recovered()
            return out
        except ConnectionError:
            self._down()
            return {"ok": False, "deferred": True}

    def drain_events(self) -> List[dict]:
        try:
            out = self.client.call("events")
            self._recovered()
            return out if isinstance(out, list) else []
        except ConnectionError:
            self._down()
            return []

    def fleet_status(self) -> dict:
        return self.client.fleet_status()

    def fleet_metrics(self) -> str:
        return self.client.fleet_metrics()

    def coord_status(self) -> Optional[dict]:
        try:
            return self.client.call("coord_status")
        except ConnectionError:
            return None

    def close(self) -> None:
        self.client.close()


# ---------------------------------------------------------------------------
# process entrypoint: the durable / standby coordinator the launcher
# spawns and supervises (python -m paddle_tpu.distributed.coordinator)
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse
    import signal

    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.coordinator",
        description="process-hosted durable job coordinator")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--state_dir", default="")
    p.add_argument("--lease_secs", type=float, default=5.0)
    p.add_argument("--retries_per_rank", type=int, default=0)
    p.add_argument("--expire_periods", type=float, default=EXPIRE_PERIODS)
    p.add_argument("--snapshot_secs", type=float, default=None)
    p.add_argument("--startup_grace", type=float, default=None)
    p.add_argument("--standby_of", default="",
                   help="primary endpoint to follow (warm standby mode)")
    args = p.parse_args(argv)
    # fault tag-scoping identity (the coordinator kill drills target
    # PADDLE_PS_FAULT_TAGS=coord); the launcher sets this at spawn, the
    # default covers hand-run coordinators
    os.environ.setdefault(
        "PADDLE_PS_RANK_TAG",
        "coord-standby" if args.standby_of else "coord")
    from .ps_server import _Handler, _TCPServer

    role = "standby" if args.standby_of else "primary"
    coord = Coordinator(lease_secs=args.lease_secs,
                        retries_per_rank=args.retries_per_rank,
                        expire_periods=args.expire_periods,
                        startup_grace=args.startup_grace,
                        state_dir=args.state_dir or None,
                        snapshot_secs=args.snapshot_secs,
                        role=role)
    srv = _TCPServer((args.host, args.port), _Handler)
    srv.ps = coord  # type: ignore[attr-defined] — _Handler contract
    # the launcher reads this first stdout line to learn the bound port
    # (the _spawn_pserver banner protocol)
    print(f"[coordinator] listening on "
          f"{args.host}:{srv.server_address[1]}", flush=True)
    follower = None
    if args.standby_of:
        follower = CoordinatorFollower(coord, args.standby_of).start()

    def _graceful(signum, frame):  # noqa: ARG001
        coord.shutdown_event.set()

    try:
        signal.signal(signal.SIGTERM, _graceful)
    except ValueError:  # non-main thread (tests)
        pass
    threading.Thread(target=srv.serve_forever,
                     kwargs={"poll_interval": 0.1}, daemon=True,
                     name="paddle-tpu-coordinator-rpc").start()
    try:
        while not coord.shutdown_event.wait(0.2):
            pass
    finally:
        if follower is not None:
            follower.stop()
        srv.shutdown()
        srv.close_all_connections()
        srv.server_close()
        if coord.state_dir and coord.role == "primary":
            try:
                # clean exit = lossless restart (same discipline as the
                # pserver's final snapshot)
                coord.snapshot(force=True)
            except Exception as e:  # noqa: BLE001
                print(f"[coordinator] final snapshot failed: {e}",
                      file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
