"""Parameter-server embedding: host-resident sharded tables.

Parity surface: reference operators/distributed/large_scale_kv.h,
distributed_lookup_table op (operators/distributed_ops/), the pserver
optimizer blocks run by listen_and_serv (distribute_transpiler.py:545),
and the Downpour-style async update flow (distributed/communicator.h).

This is the one capability XLA does not subsume (SURVEY.md §7): an
embedding table larger than chip HBM. The table lives in HOST memory,
row-sharded across `num_shards` shard stores. Single-process runs keep
the shards in-process; under the launcher's PS mode
(PADDLE_PSERVERS_IP_PORT_LIST) the same table lives in a dedicated
pserver PROCESS and create_table hands back a ps_server.RemoteTable
client with the identical gather/push surface, so N trainer processes
share one table over TCP (ps_server.py — the listen_and_serv/gRPC
data-plane analog). The device step interacts with it through two
callbacks:

  gather  — forward: jax.pure_callback pulls just the looked-up rows to
            the device ([batch, dim], never the full table)
  update  — backward: jax.experimental.io_callback pushes the rows'
            gradients back; the SERVER applies the optimizer (sgd or
            adagrad per row, like the reference's pserver optimizer
            blocks), deduplicating repeated ids within a batch

Under async dispatch, step N+1's gather may observe state before step
N's update lands — the reference's async-SGD (Downpour) semantics;
fetch-synchronized loops (the default Executor.run) behave like sync PS.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

_tables: Dict[str, "ShardedHostTable"] = {}
_lock = threading.Lock()


class ShardedHostTable:
    """Row-sharded host KV: shard s owns rows with row % num_shards == s
    (the reference's round-robin block placement, ps_dispatcher.py)."""

    def __init__(
        self,
        name: str,
        shape,
        dtype: str = "float32",
        num_shards: int = 4,
        optimizer: str = "sgd",
        learning_rate: float = 0.1,
        initializer_std: Optional[float] = None,
        seed: int = 0,
    ):
        self.name = name
        self.rows, self.dim = int(shape[0]), int(shape[1])
        self.dtype = np.dtype(dtype)
        self.num_shards = int(num_shards)
        self.optimizer = optimizer
        self.learning_rate = float(learning_rate)
        if optimizer not in ("sgd", "adagrad"):
            raise ValueError(f"unsupported server optimizer {optimizer!r}")
        # server-traffic accounting (tests + ops dashboards): every push
        # RPC-equivalent bumps these, so sync-mode vs geo-mode traffic is
        # directly comparable
        self.push_calls = 0
        self.pushed_bytes = 0
        rng = np.random.RandomState(seed)
        std = initializer_std if initializer_std is not None else 1.0 / np.sqrt(self.dim)
        self._shards: List[np.ndarray] = []
        self._accum: List[Optional[np.ndarray]] = []
        self._locks = [threading.Lock() for _ in range(self.num_shards)]
        # dirty-row tracking (incremental snapshots): per-shard LOCAL row
        # indices touched by push_* since the last drain_dirty(). Always
        # on — a set-update per push is noise next to the scatter itself,
        # and the snapshotter decides whether to use it
        self._dirty: List[set] = [set() for _ in range(self.num_shards)]
        for s in range(self.num_shards):
            n = (self.rows - s + self.num_shards - 1) // self.num_shards
            self._shards.append(rng.normal(0.0, std, (n, self.dim)).astype(self.dtype))
            self._accum.append(
                np.zeros((n, self.dim), np.float32) if optimizer == "adagrad" else None
            )

    # -- addressing ------------------------------------------------------
    def _locate(self, ids: np.ndarray):
        if ids.size and (ids.min() < 0 or ids.max() >= self.rows):
            bad = ids[(ids < 0) | (ids >= self.rows)][0]
            raise IndexError(
                f"table {self.name!r}: id {int(bad)} out of range "
                f"[0, {self.rows}) — pad ids must be remapped before lookup"
            )
        return ids % self.num_shards, ids // self.num_shards

    # -- serving ---------------------------------------------------------
    def gather(self, ids) -> np.ndarray:
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        shard, local = self._locate(ids)
        out = np.empty((ids.shape[0], self.dim), self.dtype)
        for s in range(self.num_shards):
            m = shard == s
            if m.any():
                with self._locks[s]:
                    out[m] = self._shards[s][local[m]]
        return out

    def push_delta(self, ids, deltas) -> None:
        """Geo-SGD server half (reference GeoCommunicator,
        operators/distributed/communicator.h:396): trainers push
        accumulated parameter DELTAS every K steps; the server just adds
        them (no server-side optimizer — the trainer already applied
        its own). Repeated ids accumulate."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        deltas = np.asarray(deltas, np.float32).reshape(ids.shape[0], self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        acc = np.zeros((uniq.shape[0], self.dim), np.float32)
        np.add.at(acc, inv, deltas)
        shard, local = self._locate(uniq)
        self.push_calls += 1
        self.pushed_bytes += int(deltas.nbytes + ids.nbytes)
        for s in range(self.num_shards):
            m = shard == s
            if not m.any():
                continue
            with self._locks[s]:
                self._shards[s][local[m]] = (
                    self._shards[s][local[m]].astype(np.float32) + acc[m]
                ).astype(self.dtype)
                self._dirty[s].update(local[m].tolist())

    def push_gradients(self, ids, grads) -> None:
        """Apply the server-side optimizer for the touched rows. Repeated
        ids in one batch are accumulated first (SelectedRows merge-add
        semantics) so the update matches a dense scatter-add gradient."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        grads = np.asarray(grads, np.float32).reshape(ids.shape[0], self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        acc = np.zeros((uniq.shape[0], self.dim), np.float32)
        np.add.at(acc, inv, grads)
        shard, local = self._locate(uniq)
        # count only validated pushes (push_delta counts after _locate too)
        self.push_calls += 1
        self.pushed_bytes += int(grads.nbytes + ids.nbytes)
        lr = self.learning_rate
        for s in range(self.num_shards):
            m = shard == s
            if not m.any():
                continue
            rows = local[m]
            g = acc[m]
            with self._locks[s]:
                if self.optimizer == "adagrad":
                    self._accum[s][rows] += g * g
                    g = g / (np.sqrt(self._accum[s][rows]) + 1e-6)
                self._shards[s][rows] = (
                    self._shards[s][rows].astype(np.float32) - lr * g
                ).astype(self.dtype)
                self._dirty[s].update(rows.tolist())

    # -- introspection / checkpoint --------------------------------------
    def nbytes(self) -> int:
        return sum(sh.nbytes for sh in self._shards)

    def memory_stats(self) -> dict:
        """Resident-memory accounting (ISSUE 11 satellite): the
        capacity-planning row behind `stats`/`fleet.ps_stats()` and the
        debugz /statusz ps_memory section. rows x row-width for the
        value shards, plus the adagrad accumulator when present; the
        dirty-row set is the incremental-snapshot overhead."""
        shard_bytes = int(sum(sh.nbytes for sh in self._shards))
        accum_bytes = int(sum(a.nbytes for a in self._accum
                              if a is not None))
        # CPython set-of-int overhead is roughly 32B/entry + the ints;
        # an estimate is all capacity planning needs
        dirty = sum(len(d) for d in self._dirty)
        return {
            "rows": self.rows,
            "dim": self.dim,
            "dtype": str(self.dtype),
            "num_shards": self.num_shards,
            "shard_bytes": shard_bytes,
            "accum_bytes": accum_bytes,
            "dirty_rows": dirty,
            "dirty_overhead_bytes": dirty * 64,
            "resident_bytes": shard_bytes + accum_bytes + dirty * 64,
        }

    def to_dense(self) -> np.ndarray:
        """Materialize the full table (tests/checkpoints only — defeats
        the purpose in a real run)."""
        out = np.empty((self.rows, self.dim), self.dtype)
        for s in range(self.num_shards):
            out[s::self.num_shards] = self._shards[s]
        return out

    def state_dict(self):
        # deep copies: a checkpoint must be a snapshot, not an alias of
        # the live shards (np.asarray with a matching dtype is a no-op).
        # Copies happen UNDER the shard locks: the pserver's periodic
        # snapshotter (ps_server.PSServer.snapshot) runs concurrently
        # with pushes, and an unlocked copy could capture a half-updated
        # row (torn between the optimizer's read and write)
        shards, accum = [], []
        for s in range(self.num_shards):
            with self._locks[s]:
                shards.append(self._shards[s].copy())
                accum.append(
                    None if self._accum[s] is None else self._accum[s].copy())
        return {
            "shards": shards,
            "accum": accum,
            "optimizer": self.optimizer,
            "learning_rate": self.learning_rate,
        }

    def load_state_dict(self, state):
        self._shards = [np.array(s, self.dtype) for s in state["shards"]]
        self._accum = [
            None if a is None else np.array(a, np.float32) for a in state["accum"]
        ]
        self.optimizer = state.get("optimizer", self.optimizer)
        self.learning_rate = float(state.get("learning_rate", self.learning_rate))
        # the loaded state IS the new baseline: nothing is dirty vs it
        self._dirty = [set() for _ in range(self.num_shards)]

    # -- incremental snapshots (dirty-row deltas) -------------------------
    def dirty_rows(self) -> int:
        """Rows touched since the last drain (across shards, local idx)."""
        return sum(len(d) for d in self._dirty)

    def drain_dirty(self) -> dict:
        """Capture-and-clear the dirty rows as a VALUE delta: per shard,
        the touched local row indices with their current values (+ the
        adagrad accumulator rows when present). Copies happen under the
        shard locks, like state_dict, so no torn row is ever captured.
        Value deltas are idempotent — replaying one on top of a newer
        base is last-write-wins, which makes the base/delta race in the
        snapshotter safe by construction."""
        out = {"shards": {}, "rows": 0}
        for s in range(self.num_shards):
            with self._locks[s]:
                if not self._dirty[s]:
                    continue
                rows = np.fromiter(sorted(self._dirty[s]), np.int64,
                                   len(self._dirty[s]))
                out["shards"][s] = {
                    "rows": rows,
                    "values": self._shards[s][rows].copy(),
                    "accum": (None if self._accum[s] is None
                              else self._accum[s][rows].copy()),
                }
                out["rows"] += int(rows.shape[0])
                self._dirty[s].clear()
        return out

    def apply_dirty_delta(self, delta: dict) -> int:
        """Scatter a drain_dirty() delta back into the shards (restore
        path: base snapshot + delta chain). Does NOT re-dirty the rows —
        a restored state is the new clean baseline."""
        n = 0
        for s, ent in delta.get("shards", {}).items():
            s = int(s)
            rows = np.asarray(ent["rows"], np.int64)
            with self._locks[s]:
                self._shards[s][rows] = np.asarray(
                    ent["values"], self.dtype)
                if ent.get("accum") is not None and self._accum[s] is not None:
                    self._accum[s][rows] = np.asarray(
                        ent["accum"], np.float32)
            n += int(rows.shape[0])
        return n


class GeoSGDClient:
    """Geo-SGD trainer half (reference geo_sgd_transpiler.py + the
    GeoCommunicator send thread): the trainer optimizes a LOCAL copy of
    the touched rows every step and pushes accumulated parameter deltas
    (cur - at_last_sync, scaled 1/num_trainers) to the server every
    `sync_steps` steps — K× less server traffic than per-step gradient
    push, at the cost of staleness bounded by K.

    API-compatible with ShardedHostTable for the lookup op (gather /
    push_gradients), so `mode="geo"` is transparent to programs. Rows
    are cached lazily: only touched rows live trainer-side."""

    def __init__(self, server: ShardedHostTable, sync_steps: int = 100,
                 num_trainers: int = 1):
        self.server = server
        self.name = server.name
        self.dim = server.dim
        self.rows = server.rows
        self.dtype = server.dtype
        self.sync_steps = int(sync_steps)
        self.num_trainers = int(num_trainers)
        self._local: Dict[int, np.ndarray] = {}
        self._old: Dict[int, np.ndarray] = {}
        self._touched: set = set()
        self._step = 0
        self._lock = threading.Lock()

    def _ensure_rows(self, uniq):
        missing = [r for r in uniq if r not in self._local]
        if missing:
            pulled = self.server.gather(np.asarray(missing, np.int64))
            for r, row in zip(missing, pulled):
                self._local[r] = row.astype(np.float32).copy()
                self._old[r] = row.astype(np.float32).copy()

    def gather(self, ids) -> np.ndarray:
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        with self._lock:
            self._ensure_rows(np.unique(ids).tolist())
            return np.stack([self._local[int(r)] for r in ids]).astype(
                self.dtype)

    def push_gradients(self, ids, grads) -> None:
        """LOCAL optimizer step on the touched rows; every sync_steps
        pushes, the accumulated deltas go to the server."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        grads = np.asarray(grads, np.float32).reshape(ids.shape[0], self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        acc = np.zeros((uniq.shape[0], self.dim), np.float32)
        np.add.at(acc, inv, grads)
        lr = self.server.learning_rate
        with self._lock:
            self._ensure_rows(uniq.tolist())
            for r, g in zip(uniq.tolist(), acc):
                self._local[r] = self._local[r] - lr * g
                self._touched.add(r)
            self._step += 1
            if self._step % self.sync_steps == 0:
                self._sync_locked()

    def _sync_locked(self):
        if not self._touched:
            return
        rows = np.asarray(sorted(self._touched), np.int64)
        delta = np.stack([
            (self._local[int(r)] - self._old[int(r)]) / self.num_trainers
            for r in rows
        ])
        self.server.push_delta(rows, delta)
        fresh = self.server.gather(rows)
        for r, row in zip(rows.tolist(), fresh):
            self._local[r] = row.astype(np.float32).copy()
            self._old[r] = self._local[r].copy()
        self._touched.clear()

    def flush(self) -> None:
        """Push any pending deltas now (checkpoint / shutdown barrier)."""
        with self._lock:
            self._sync_locked()

    # -- delegation so geo tables checkpoint like plain ones --------------
    def nbytes(self) -> int:
        return self.server.nbytes()

    def to_dense(self) -> np.ndarray:
        self.flush()
        return self.server.to_dense()

    def state_dict(self):
        self.flush()
        return self.server.state_dict()

    def load_state_dict(self, state):
        with self._lock:
            self._local.clear()
            self._old.clear()
            self._touched.clear()
        self.server.load_state_dict(state)


def create_table(name, shape, mode: str = "sync", geo_sync_steps: int = 100,
                 num_trainers: Optional[int] = None, endpoints=None,
                 replication: Optional[int] = None, **kw):
    """mode: "sync" — per-step gradient push with a server-side barrier
    across trainers (reference DistributeTranspiler sync_mode); "async"
    — per-step push applied on arrival (Downpour); "geo" — local
    optimizer + K-step delta push (Geo-SGD, geo_sgd_transpiler.py).

    When `endpoints` is given, or the launcher exports
    PADDLE_PSERVERS_IP_PORT_LIST (launch.py --server_num), the table is
    HOSTED: this process gets a RemoteTable client and the rows live in
    the pserver process(es), shared by every trainer (ps_server.py).
    Without either, the table is in-process (single trainer / tests).
    In-process "sync" and "async" behave identically (there is no peer
    to barrier with).

    replication (default PADDLE_PS_REPLICATION, else 1): hosted tables
    only — each row partition gets a primary pserver plus R-1 backup
    replicas on distinct pservers (ps_server.RemoteTable docs: fast
    failover, hedged pulls). 1 = today's unreplicated behavior;
    in-process tables ignore it (there is no second process to hold a
    replica)."""
    import os as _os

    from . import ps_server as _net

    if num_trainers is None:
        num_trainers = int(_os.environ.get("PADDLE_TRAINERS_NUM", 1))
    if replication is None:
        replication = int(_os.environ.get("PADDLE_PS_REPLICATION", 1) or 1)
    with _lock:
        if name in _tables:
            raise ValueError(f"table {name!r} already exists")
        if endpoints is None and _net.training_role() == "TRAINER":
            endpoints = _net.pserver_endpoints()
        if endpoints:
            if mode not in ("sync", "async", "geo"):
                raise ValueError(f"unknown PS mode {mode!r}")
            t = _net.RemoteTable(
                name, shape, endpoints,
                sync_trainers=num_trainers if mode == "sync" else 0,
                trainer_id=int(_os.environ.get("PADDLE_TRAINER_ID", 0)),
                replication=replication,
                **kw)
        else:
            t = ShardedHostTable(name, shape, **kw)
        if mode == "geo":
            if t.optimizer != "sgd":
                raise ValueError(
                    "geo mode applies SGD trainer-side (reference "
                    "geo_sgd_transpiler.py restriction); use optimizer='sgd'")
            t = GeoSGDClient(t, sync_steps=geo_sync_steps,
                             num_trainers=num_trainers)
        _tables[name] = t
        return t


def get_table(name) -> ShardedHostTable:
    t = _tables.get(name)
    if t is None:
        raise KeyError(
            f"host embedding table {name!r} not registered; call "
            f"distributed.ps.create_table first"
        )
    return t


def drop_table(name) -> None:
    with _lock:
        _tables.pop(name, None)
