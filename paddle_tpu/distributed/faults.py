"""Deterministic fault injection for the PS data plane and checkpoints.

Parity surface: the reference hardens its distributed runtime against
real faults (grpc_client.h retries, HeartBeatMonitor timeouts,
checkpoint_notify recovery) but tests them with sleeps and luck; here
faults are INJECTED on a deterministic schedule so the chaos tests in
tests/test_ps_faults.py and tests/test_checkpoint.py assert exact
recovery behavior instead of probabilistic survival.

Gate: the layer is active only when BOTH the FLAGS_ps_fault_injection
flag is on AND PADDLE_PS_FAULT_SPEC is non-empty. Flag-off behavior is
bit-identical to a build without this module: ps_server consults
`injector()` once per RPC and gets None.

Spec grammar (PADDLE_PS_FAULT_SPEC) — semicolon-separated rules:

    <action>:<method>:<nth>[:<arg>]

    action  one of
            drop    client side: close the connection AFTER sending the
                    request, before reading the reply — the server has
                    (usually) applied it, the client cannot know:
                    exercises the retry + dedup path
            refuse  client side: raise ConnectionError BEFORE sending —
                    the request never reaches the server: exercises the
                    plain retry path
            delay   client side: sleep <arg> seconds before sending
            stall   client side, REPEATING: every <nth>-th outgoing RPC
                    matching the verb sleeps <arg> MILLISECONDS before
                    sending — the client-side sibling of `slow`, used
                    with PADDLE_PS_FAULT_TAGS to make ONE trainer's
                    verb deterministically late (the step-tracing
                    critical-path drill: the stalled rank must be the
                    one the merged trace blames). The <method> field
                    may also name a stall_point phase ("gen_decode_
                    step" in the serving decode loop) — phase names and
                    RPC verbs never collide
            kill    server side: os._exit(1) the pserver process once it
                    has handled <nth> RPCs in total (method filter still
                    applies): exercises supervision + snapshot recovery.
                    The durable job coordinator serves its verbs through
                    the same on_server_call hook, so a kill rule scoped
                    with PADDLE_PS_FAULT_TAGS=coord kills the
                    coordinator after N handled verbs instead
            slow    server side, REPEATING: every <nth>-th handled RPC
                    matching the verb sleeps <arg> MILLISECONDS before
                    being served — deterministic tail-latency injection
                    (the hedged-read drill: a slow primary must lose the
                    race to a backup hedge)
            partition  server side, LATCHING: once this server has
                    handled <nth> RPCs it enters a partitioned state —
                    still reachable (reads, pings) but REJECTING
                    replication traffic (`replicate` forwards), so a
                    backup replica goes stale exactly the way a
                    primary<->backup network partition makes it. The
                    <method> field names the pserver tag to partition
                    ("ps1") or "*" for any
            crash   phase side: os._exit(1) at the Nth arrival at a
                    named code phase (crash_point(phase) call sites; the
                    <method> field names the phase). Checkpoint commit
                    phases: "ckpt_tmp_written" (content files written,
                    step dir not yet renamed into place),
                    "ckpt_before_commit" (step dir in place, manifest —
                    the commit point — not yet written),
                    "ckpt_manifest_tmp_written" (manifest tmp file
                    written, os.replace — the rename — not yet issued),
                    "ckpt_writer" (inside the async background writer
                    thread, before it touches the disk),
                    "ckpt_shard_committed" (a rank's shard manifest
                    landed, its commit-barrier report not yet sent) and
                    "ckpt_before_global_commit" (every shard confirmed,
                    global manifest not yet written): exercises the
                    torn-checkpoint fallback and the sharded
                    global-commit protocol in fluid/checkpoint.py.
                    Serving phase: "gen_decode_step" (between decode
                    steps in the generation engine's loop) kills a
                    replica mid-decode — the crash-tolerant-generation
                    drill's deterministic mid-stream death.
                    Control-plane phase: "coord_verb" (entry of every
                    coordinator verb dispatch) kills the job
                    coordinator process after handling N verbs — the
                    coordinator kill-and-respawn drill. Scope the rule
                    with PADDLE_PS_FAULT_TAGS=coord so only the
                    durable coordinator process (tag "coord"; a
                    standby is "coord-standby") arms it: the launcher
                    and every trainer/pserver share the same spec env
                    but match different tags
            bitflip phase side, DATA-corrupting: at the Nth arrival at a
                    named data phase (bitflip_point(phase, array) call
                    sites: "push_grad" in the PS client push path,
                    "sdc_apply" in the dp merged-grad apply path of the
                    SDC drill worker) flip ONE BIT of one element of
                    the array flowing through — the deterministic
                    stand-in for a silent data corruption (cosmic ray,
                    bad DIMM, wrong FMA). The optional <arg> is the
                    flat element index to corrupt (default 0). Combine
                    with PADDLE_PS_FAULT_TAGS to corrupt exactly one
                    dp rank: the cross-replica SDC detector
                    (telemetry/numerics.py) must name that rank
            oom     phase side: raise a simulated RESOURCE_EXHAUSTED at
                    the Nth arrival at a named executor memory phase
                    ("compile", "run" — oom_point() call sites in
                    fluid/executor.py), driving the OOM-doctor drill
                    (telemetry/memory.py) deterministically on backends
                    that never genuinely exhaust HBM
            io_err  phase side: raise OSError(EIO) at the Nth arrival at
                    a named WRITE phase (io_point(phase) call sites:
                    "ckpt_content", "ckpt_manifest",
                    "ckpt_global_manifest") — a disk I/O error at that
                    exact write; the save fails loudly and the commit
                    protocol must leave the previous checkpoint intact
            short_write  phase side: the Nth write at the matching phase
                    lands TRUNCATED (half the intended bytes) while the
                    writer believes it succeeded — the silent partial
                    write a power loss or a lying disk produces. A short
                    content file makes checksum verification fail
                    (corrupt, restore falls back); a short manifest is
                    unparseable (torn by definition)
            diskfull  phase side, LATCHING: from the Nth arrival at the
                    matching phase on, EVERY io_point write phase in
                    this process raises OSError(ENOSPC) — the disk is
                    full for everyone, not just one file. Saves keep
                    failing until the process restarts (or the operator
                    frees space, e.g. `ckpt_doctor --gc`)
            lease_expire  member side, LATCHING: once this process has
                    attempted <nth> coordinator lease renewals, ALL
                    further renewals are swallowed client-side (the
                    coordinator never sees them and the lease runs out
                    exactly like a silently-dead host's). The <method>
                    field names the process tag to starve ("trainer1",
                    "ps0") or "*"; the process itself keeps running —
                    that is the point: lease expiry, not process death
            netsplit  member side, WINDOWED: once this process has
                    issued <nth> outgoing RPCs, ALL outgoing RPCs are
                    dropped (FaultError before send) for <arg>
                    MILLISECONDS, then the split heals — one side of a
                    network partition, deterministically. Lease
                    renewals ride the same client path, so a long
                    enough window also expires the member's lease. The
                    <method> field names the process tag or "*"
    method  an RPC verb name (gather, push_gradients, ...), a phase
            name (crash rules), or "*"
    nth     1-based index of the matching call AT THE INJECTION SITE;
            each rule fires exactly once, on its Nth match

Example: "drop:push_gradients:3;kill:*:40" drops the third push RPC the
client issues and kills the pserver after it has handled 40 RPCs.

Counting is per-process and per-rule, so the schedule is a pure function
of the RPC sequence — reruns inject the same faults at the same points.
Supervised pserver RESPAWNS get PADDLE_PS_FAULT_SPEC cleared by the
launcher: a kill rule means "kill this server once", not "kill every
incarnation from its own RPC-count zero".

Process scoping: PADDLE_PS_FAULT_TAGS (comma-separated) arms the layer
only in processes whose PADDLE_PS_RANK_TAG ("ps0") or trainer id
("trainer1") is listed — so a replication drill can kill ONE pserver of
a replicated pair instead of every process that shares the env.
"""
from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

ENV_SPEC = "PADDLE_PS_FAULT_SPEC"
ENV_TAGS = "PADDLE_PS_FAULT_TAGS"

_CLIENT_ACTIONS = ("drop", "refuse", "delay", "stall")
_SERVER_ACTIONS = ("kill", "slow", "partition")
_PHASE_ACTIONS = ("crash", "oom")
# data-corruption rules: fire at named DATA phases (bitflip_point call
# sites) and perturb the array flowing through instead of failing
_DATA_ACTIONS = ("bitflip",)
# disk-fault rules: fire at named WRITE phases (io_point call sites in
# the checkpoint commit protocol)
_IO_ACTIONS = ("io_err", "short_write", "diskfull")
# rules whose <method> field names a PROCESS TAG, not an RPC verb
_TAG_ACTIONS = ("lease_expire", "netsplit")


def _process_tags() -> set:
    """The identities this process answers to for tag-matched rules:
    its pserver tag ("ps0"), its launcher-stable trainer tag
    ("trainer2", PADDLE_TRAINER_TAG), and the rank-derived fallback."""
    tags = {os.environ.get("PADDLE_PS_RANK_TAG") or "",
            os.environ.get("PADDLE_TRAINER_TAG") or "",
            "trainer" + os.environ.get("PADDLE_TRAINER_ID", "")}
    tags.discard("")
    tags.discard("trainer")
    return tags


class FaultError(ConnectionError):
    """Raised by client-side `refuse`/`drop` rules; a subclass of
    ConnectionError so it flows through the exact retry path a real
    transport fault would take."""


class SimulatedOOM(RuntimeError):
    """Raised by `oom:<phase>:<nth>` rules: a deterministic stand-in
    for the allocator's RESOURCE_EXHAUSTED (the message carries the
    marker, so telemetry.memory.is_oom routes it through the exact OOM-
    doctor path a real out-of-memory would take)."""


class _Rule:
    __slots__ = ("action", "method", "nth", "arg", "count", "fired")

    def __init__(self, action: str, method: str, nth: int, arg: float):
        self.action = action
        self.method = method
        self.nth = nth
        self.arg = arg
        self.count = 0
        self.fired = False

    def matches(self, method: str) -> bool:
        return self.method in ("*", method)

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"_Rule({self.action}:{self.method}:{self.nth}"
                f"{':' + str(self.arg) if self.arg else ''})")


def parse_spec(spec: str) -> List[_Rule]:
    rules = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"bad fault rule {raw!r}: want action:method:nth[:arg]")
        action, method, nth = parts[0], parts[1], parts[2]
        known = (_CLIENT_ACTIONS + _SERVER_ACTIONS + _PHASE_ACTIONS
                 + _IO_ACTIONS + _TAG_ACTIONS + _DATA_ACTIONS)
        if action not in known:
            raise ValueError(
                f"bad fault rule {raw!r}: unknown action {action!r} "
                f"(want one of {known})")
        try:
            n = int(nth)
        except ValueError:
            raise ValueError(f"bad fault rule {raw!r}: nth must be an int")
        if n < 1:
            raise ValueError(f"bad fault rule {raw!r}: nth is 1-based")
        arg = float(parts[3]) if len(parts) == 4 else 0.0
        if action == "netsplit" and arg <= 0:
            raise ValueError(
                f"bad fault rule {raw!r}: netsplit needs a window — "
                f"netsplit:<tag>:<nth>:<ms>")
        if action == "stall" and arg <= 0:
            raise ValueError(
                f"bad fault rule {raw!r}: stall needs a duration — "
                f"stall:<verb>:<nth>:<ms>")
        rules.append(_Rule(action, method, n, arg))
    return rules


class FaultInjector:
    """One injection schedule, shared by every connection in a process.

    Client hooks (called by ps_server._Conn.call):
      before_send(method)  — fires refuse (raises FaultError) and delay
      drop_after_send(method) -> bool — True: close the socket now

    Server hook (called by ps_server.PSServer.handle):
      on_server_call(method) — fires kill (os._exit) once the counter
      reaches the rule's nth

    Phase hook (called through crash_point() at named code phases, e.g.
    fluid/checkpoint.py's commit protocol):
      at_phase(phase) — fires crash (os._exit) on the Nth arrival at
      the matching phase
    """

    def __init__(self, spec: str):
        self.spec = spec
        self._rules = parse_spec(spec)
        self._lock = threading.Lock()
        self._server_calls = 0
        self.partitioned = False  # latched by a fired `partition` rule
        self.lease_blocked = False  # latched by a fired `lease_expire`
        self.netsplit_until = 0.0  # wall time the split heals
        self.disk_full = False  # latched by a fired `diskfull` rule

    def _take(self, site_actions, method: str) -> List[_Rule]:
        """Advance matching rules' counters; return the rules firing NOW."""
        firing = []
        with self._lock:
            for r in self._rules:
                if r.action not in site_actions or r.fired:
                    continue
                if not r.matches(method):
                    continue
                r.count += 1
                if r.count == r.nth:
                    r.fired = True
                    firing.append(r)
        return firing

    def _take_every(self, site_actions, method: str) -> List[_Rule]:
        """REPEATING variant (`slow`): fires on every nth-th match —
        count % nth == 0 — and never spends the rule, so 1/nth of the
        matching calls see the fault (a deterministic latency tail)."""
        firing = []
        with self._lock:
            for r in self._rules:
                if r.action not in site_actions:
                    continue
                if not r.matches(method):
                    continue
                r.count += 1
                if r.count % r.nth == 0:
                    firing.append(r)
        return firing

    def _take_tagged(self, action: str) -> List[_Rule]:
        """Advance rules whose <method> field names one of THIS
        process's tags (or "*") — each rule counted at most once per
        arrival even when several tags match."""
        tags = _process_tags()
        firing = []
        with self._lock:
            for r in self._rules:
                if r.action != action or r.fired:
                    continue
                if not (r.method == "*" or r.method in tags):
                    continue
                r.count += 1
                if r.count == r.nth:
                    r.fired = True
                    firing.append(r)
        return firing

    # -- client side -----------------------------------------------------
    def before_send(self, method: str) -> None:
        # netsplit rules count every outgoing RPC from a tagged process;
        # firing opens a drop window during which ALL sends fail the way
        # a severed link fails them (the renewal path included)
        now = time.time()
        for r in self._take_tagged("netsplit"):
            with self._lock:
                self.netsplit_until = max(self.netsplit_until,
                                          now + r.arg / 1000.0)
            os.write(2, (f"[faults] netsplit: pid {os.getpid()} dropping "
                         f"all RPCs for {r.arg:.0f}ms (rule netsplit:"
                         f"{r.method}:{r.nth})\n").encode())
        if now < self.netsplit_until:
            raise FaultError(
                f"fault injection: netsplit — {method!r} RPC dropped "
                f"({self.netsplit_until - now:.3f}s until the window "
                f"heals)")
        for r in self._take_every(("stall",), method):
            time.sleep(r.arg / 1000.0)  # arg is MILLISECONDS, repeating
        for r in self._take(("refuse", "delay"), method):
            if r.action == "delay":
                time.sleep(r.arg)
            else:
                raise FaultError(
                    f"fault injection: refused {method!r} RPC "
                    f"(rule {r.action}:{r.method}:{r.nth})")

    def drop_after_send(self, method: str) -> bool:
        return bool(self._take(("drop",), method))

    @staticmethod
    def _flight(reason: str) -> None:
        """Best-effort flight-recorder dump before an os._exit — the
        atexit/excepthook triggers never run for a hard death, so the
        kill/crash rules dump the span ring themselves. No-op unless
        PADDLE_TRACING + PADDLE_TRACE_DIR are armed."""
        try:
            from ..telemetry import tracing

            tracing.flight_dump(reason)
        except Exception:  # noqa: BLE001 — the death must still happen
            pass

    # -- server side -----------------------------------------------------
    def on_server_call(self, method: str) -> None:
        for r in self._take(("kill",), method):
            # hard death, no cleanup: the supervision + snapshot story
            # must recover from exactly this
            os.write(2, (f"[faults] killing pserver pid {os.getpid()} "
                         f"(rule kill:{r.method}:{r.nth})\n").encode())
            self._flight("kill")
            os._exit(1)
        for r in self._take_every(("slow",), method):
            time.sleep(r.arg / 1000.0)  # arg is MILLISECONDS
        # partition rules match the server's TAG, not the RPC verb, and
        # count every handled RPC; once fired the injector latches
        tag = os.environ.get("PADDLE_PS_RANK_TAG", "")
        for r in self._take(("partition",), tag):
            os.write(2, (f"[faults] partitioning pserver {tag or '?'} pid "
                         f"{os.getpid()} (rule partition:{r.method}:"
                         f"{r.nth}): reachable but rejecting replication"
                         f"\n").encode())
            with self._lock:
                self.partitioned = True

    def blocks_replication(self) -> bool:
        """True once a `partition` rule fired: this server must reject
        `replicate` forwards (reachable-but-stale backup)."""
        return self.partitioned

    # -- lease side ------------------------------------------------------
    def on_lease_renew(self) -> bool:
        """Counts one coordinator lease-renewal ATTEMPT from this
        process; True once a matching `lease_expire` rule has latched —
        the caller (CoordinatorClient.renew) then swallows the renewal
        so the lease expires while the process stays alive."""
        for r in self._take_tagged("lease_expire"):
            os.write(2, (f"[faults] lease_expire: pid {os.getpid()} "
                         f"swallowing all lease renewals from now on "
                         f"(rule lease_expire:{r.method}:{r.nth})\n"
                         ).encode())
            with self._lock:
                self.lease_blocked = True
        return self.lease_blocked

    # -- disk-fault side -------------------------------------------------
    def at_io_phase(self, phase: str) -> bool:
        """Consulted at named checkpoint WRITE phases (io_point call
        sites). Raises OSError for `io_err` (one EIO at the Nth match)
        and `diskfull` (ENOSPC from the Nth match ON — latched: a full
        disk fails every later write too); returns True when a
        `short_write` rule fired and the caller must truncate the bytes
        it is about to write."""
        import errno

        for r in self._take(("diskfull",), phase):
            os.write(2, (f"[faults] disk full from phase {phase!r} on "
                         f"(rule diskfull:{r.method}:{r.nth})\n").encode())
            with self._lock:
                self.disk_full = True
        if self.disk_full:
            raise OSError(errno.ENOSPC,
                          f"fault injection: no space left on device "
                          f"(phase {phase!r})")
        for r in self._take(("io_err",), phase):
            raise OSError(errno.EIO,
                          f"fault injection: I/O error at phase "
                          f"{phase!r} (rule io_err:{r.method}:{r.nth})")
        short = bool(self._take(("short_write",), phase))
        if short:
            os.write(2, (f"[faults] short write at phase {phase!r}\n"
                         ).encode())
        return short

    # -- data-corruption side ----------------------------------------------
    def at_bitflip_phase(self, phase: str, array):
        """Consulted at named DATA phases (bitflip_point call sites):
        a `bitflip:<phase>:<nth>[:<elem>]` rule returns a COPY of the
        array with one bit of one element flipped (float32/float64: the
        high exponent bit, so the corruption is loud in any norm;
        integer dtypes: the low bit). No matching rule: the array is
        returned untouched, same object."""
        rules = self._take(("bitflip",), phase)
        if not rules:
            return array
        import numpy as np

        a = np.array(array, copy=True)
        flat = a.reshape(-1)
        for r in rules:
            if flat.size == 0:
                continue
            idx = int(r.arg) % flat.size
            if a.dtype == np.float32:
                u = flat.view(np.uint32)
                u[idx] ^= np.uint32(1 << 30)
            elif a.dtype == np.float64:
                u = flat.view(np.uint64)
                u[idx] ^= np.uint64(1 << 62)
            else:
                # any other dtype: flip the low bit of the element's
                # first byte through the raw view
                b = a.view(np.uint8).reshape(a.size, a.itemsize)
                b[idx, 0] ^= np.uint8(1)
            os.write(2, (f"[faults] bitflip at phase {phase!r}: "
                         f"element {idx} corrupted in pid "
                         f"{os.getpid()} (rule bitflip:{r.method}:"
                         f"{r.nth})\n").encode())
        return a

    # -- memory side -----------------------------------------------------
    def at_oom_phase(self, phase: str) -> None:
        """Consulted at the executor's named memory phases ("compile",
        "run"): an `oom:<phase>:<nth>` rule raises a SimulatedOOM — a
        message-compatible stand-in for the allocator's
        RESOURCE_EXHAUSTED, so the OOM-doctor drill is deterministic on
        backends (CPU) that never actually run out."""
        for r in self._take(("oom",), phase):
            os.write(2, (f"[faults] simulated OOM at phase {phase!r} "
                         f"(rule oom:{r.method}:{r.nth})\n").encode())
            raise SimulatedOOM(
                f"RESOURCE_EXHAUSTED: fault injection — simulated HBM "
                f"out of memory at phase {phase!r} "
                f"(rule oom:{r.method}:{r.nth})")

    # -- phase side ------------------------------------------------------
    def at_phase(self, phase: str) -> None:
        for r in self._take(("crash",), phase):
            # same hard death as kill: the atomic-commit protocol must
            # leave a recoverable state at EVERY phase boundary
            os.write(2, (f"[faults] crashing pid {os.getpid()} at phase "
                         f"{phase!r} (rule crash:{r.method}:{r.nth})\n"
                         ).encode())
            self._flight(f"crash:{phase}")
            os._exit(1)

    def at_stall_phase(self, phase: str) -> None:
        """REPEATING delay at a named code phase (stall_point call
        sites) — the phase-site sibling of the client-RPC `stall`
        action: every nth-th arrival sleeps <arg> milliseconds. Phase
        names and RPC verbs never collide, so one spec can stall a verb
        and a phase independently."""
        for r in self._take_every(("stall",), phase):
            time.sleep((r.arg or 0) / 1000.0)


_injector: Optional[FaultInjector] = None
_injector_lock = threading.Lock()


def injector() -> Optional[FaultInjector]:
    """The process-wide injector, or None when the layer is off (the
    common case: one flag read + one env read, no state)."""
    from ..fluid import flags

    if not flags.flag("FLAGS_ps_fault_injection"):
        return None
    spec = os.environ.get(ENV_SPEC, "")
    if not spec.strip():
        return None
    tags = os.environ.get(ENV_TAGS, "").strip()
    if tags:
        # scoped arming: only processes named in PADDLE_PS_FAULT_TAGS
        # ("ps0", "trainer1") see the schedule — a replicated drill can
        # fault ONE replica of a pair
        mine = {os.environ.get("PADDLE_PS_RANK_TAG") or "",
                "trainer" + os.environ.get("PADDLE_TRAINER_ID", "")}
        wanted = {t.strip() for t in tags.split(",") if t.strip()}
        if not (wanted & mine):
            return None
    global _injector
    with _injector_lock:
        if _injector is None or _injector.spec != spec:
            _injector = FaultInjector(spec)
        return _injector


def crash_point(phase: str) -> None:
    """Deterministic kill site: os._exit(1) if an armed crash rule
    matches this phase on this arrival. One flag read when the layer is
    off — callers (checkpoint commit protocol) pay nothing in
    production."""
    inj = injector()
    if inj is not None:
        inj.at_phase(phase)


def stall_point(phase: str) -> None:
    """Deterministic mid-phase delay site: a REPEATING
    `stall:<phase>:<nth>:<ms>` rule sleeps at every nth-th arrival at
    this phase — e.g. "gen_decode_step" in the serving decode loop
    slows one replica's generation without killing it. One flag read
    when the layer is off."""
    inj = injector()
    if inj is not None:
        inj.at_stall_phase(phase)


def oom_point(phase: str) -> None:
    """Deterministic simulated-OOM site at the executor's named memory
    phases ("compile", "run"): raises SimulatedOOM when an armed
    `oom:<phase>:<nth>` rule matches — the OOM-doctor drill's trigger
    on backends that never genuinely exhaust memory. One flag read when
    the layer is off."""
    inj = injector()
    if inj is not None:
        inj.at_oom_phase(phase)


def bitflip_point(phase: str, array):
    """Deterministic data-corruption site at a named data phase: a
    matching `bitflip:<phase>:<nth>[:<elem>]` rule returns a copy of
    `array` with one bit of one element flipped; otherwise the array
    passes through untouched. One flag read when the layer is off —
    the data plane pays nothing in production."""
    inj = injector()
    if inj is None:
        return array
    return inj.at_bitflip_phase(phase, array)


def io_point(phase: str) -> bool:
    """Deterministic disk-fault site at a named write phase: may raise
    OSError (`io_err`, `diskfull`); returns True when the caller must
    simulate a short write (truncate the bytes). One flag read when the
    layer is off."""
    inj = injector()
    if inj is None:
        return False
    return inj.at_io_phase(phase)


def reset() -> None:
    """Drop the cached injector (tests: fresh counters per case)."""
    global _injector
    with _injector_lock:
        _injector = None
