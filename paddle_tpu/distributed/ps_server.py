"""Networked parameter-server data plane: tables in a server PROCESS.

Parity surface: the reference's cross-process PS runtime —
operators/distributed_ops/listen_and_serv_op.cc (server event loop),
operators/distributed/grpc/grpc_client.h:176 (async client),
operators/distributed/communicator.h:180-396 (send queues, Geo), and the
PADDLE_PSERVERS_IP_PORT_LIST / PADDLE_TRAINING_ROLE / PADDLE_PORT env
contract (fleet/base/role_maker.py:497).

TPU-native redesign: the device step only ever sees [batch, dim] row
slices through the existing gather/push callbacks (ops/ps_ops.py), so
the wire protocol is four verbs over TCP — create / gather / push /
admin — not a full RPC graph executor. One server process (or several,
round-robin row-sharded like the reference ps_dispatcher) owns
ShardedHostTable instances; N launcher-spawned trainer processes talk to
it through RemoteTable, which is duck-type identical to the in-process
table, so ops/ps_ops.py and GeoSGDClient run unchanged on top.

Sync semantics (reference DistributeTranspiler sync_mode): in
`sync` mode the server BARRIERS each push round — it accumulates one
push per trainer, merges them (concat + dedup scatter-add, scaled
1/num_trainers: dp-mean convention, same as the framework's allreduce
mean), applies the optimizer ONCE, then releases every waiter. Two
trainers each pushing d(mean loss over their half-batch) therefore
produce exactly the single-process full-batch update — the loss-parity
contract tests/test_ps_dist.py asserts. `async` skips the barrier
(Downpour: apply on arrival); `geo` trainers push deltas (additive,
no barrier) through GeoSGDClient wrapping a RemoteTable.

Fault tolerance (tests/test_ps_faults.py):

  client   — every RPC runs in a retry loop: per-attempt socket, exp
             backoff with jitter, transparent reconnect on
             ConnectionError/EOF/timeout. Idempotent verbs retry freely;
             push_gradients / push_delta carry a (trainer_id, step|seq)
             dedup key and a `retry` marker so a replayed push that
             already LANDED (reply lost) is applied exactly once.
  server   — `generation` rides the create_table handshake
             (PADDLE_ELASTIC_RESTART): a restarted trainer group bumps
             it and the server RESETS the table's push barrier, so the
             half-filled round a crashed group left behind can never
             merge with — or deadlock — the new group's pushes.
  state    — periodic atomic snapshots (state_dict -> tmp + os.replace,
             PADDLE_PS_SNAPSHOT_SECS / PADDLE_PS_SNAPSHOT_DIR); a
             supervised restart (launch.py) preloads them, and a client
             that finds its table missing after a server restart
             re-issues the idempotent create_table (which restores the
             snapshot) and replays the verb — a pserver crash costs at
             most one snapshot interval of updates (Downpour
             bounded-staleness), not the job.
  faults   — distributed/faults.py injects drop/refuse/delay/kill on a
             deterministic schedule (FLAGS_ps_fault_injection +
             PADDLE_PS_FAULT_SPEC); flag-off is bit-identical.

Framing: 8-byte big-endian length + pickle (trusted cluster transport,
like the reference's protobuf-over-gRPC — auth/encryption is deployment
infra, not the data plane's job).
"""
from __future__ import annotations

import argparse
import json
import os
import pickle
import random
import socket
import socketserver
import struct
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from . import faults
from .ps import ShardedHostTable
from ..telemetry import BYTE_BUCKETS, get_registry
from ..telemetry import sink as _metrics_sink

_LEN = struct.Struct(">Q")

# process metrics registry (paddle_tpu.telemetry): client- and server-
# side series use disjoint name prefixes (ps_client_* / ps_server_*) so
# in-process test servers sharing the registry stay distinguishable
_REG = get_registry()

def _arm_metrics_sink() -> None:
    """Pserver-side JSONL records on the SAME env var trainers use
    (PADDLE_METRICS_PATH, ROADMAP telemetry follow-on): the path gets a
    per-process `ps` tag (launch.py's PADDLE_PS_RANK_TAG, pid fallback)
    so a co-located trainer's rank-0 file is never interleaved. Unset =
    sink stays off and every emit below is a no-op."""
    path = os.environ.get(_metrics_sink.ENV_PATH)
    if not path:
        return
    tag = os.environ.get("PADDLE_PS_RANK_TAG") or f"ps{os.getpid()}"
    root, ext = os.path.splitext(path)
    _metrics_sink.enable(f"{root}.{tag}{ext or '.jsonl'}")


def _emit_ps_step(table: str, mode: str, step: int, rows: int,
                  apply_ms: float) -> None:
    """One kind="ps_step" JSONL record per APPLIED update — the pserver's
    analog of the trainer's kind="step" record (a sync round merges once;
    async/delta pushes apply on arrival)."""
    _metrics_sink.emit({
        "kind": "ps_step", "table": table, "mode": mode,
        "step": int(step), "rows": int(rows),
        "apply_ms": round(apply_ms, 3),
    })


# a barrier that outlives this window means a peer trainer died mid-round:
# fail fast so the launcher's watcher can abort/restart the group
SYNC_TIMEOUT = float(os.environ.get("PADDLE_PS_SYNC_TIMEOUT", 120.0))

# client retry envelope: total in-band wait ~= sum of capped backoffs,
# sized to ride out a supervised pserver restart (launch.py respawn:
# poll interval + python startup, a few seconds) with room to spare
RPC_MAX_RETRIES = int(os.environ.get("PADDLE_PS_RPC_RETRIES", 10))
RPC_BACKOFF_BASE = float(os.environ.get("PADDLE_PS_RPC_BACKOFF", 0.05))
RPC_BACKOFF_CAP = float(os.environ.get("PADDLE_PS_RPC_BACKOFF_CAP", 2.0))


class TableMissingError(RuntimeError):
    """Server says the table does not exist — after a pserver restart the
    client re-creates it (idempotent; the server's preload_dir restores
    the latest snapshot) and replays the verb (RemoteTable._call)."""


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def _send_msg(sock: socket.socket, obj) -> int:
    """Returns wire bytes written (framing + payload) for telemetry."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)
    return _LEN.size + len(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed the PS connection")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


def _recv_msg_sized(sock: socket.socket):
    """(message, wire bytes read) — the telemetry-aware receive."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n)), _LEN.size + n


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


def _atomic_write(path: str, blob: bytes) -> None:
    """tmp + os.replace (the fluid/io.py contract): a crash mid-write
    can never leave a torn file at `path`."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def read_snapshot_manifest(dirname: str) -> Optional[dict]:
    """Parsed `<dirname>/manifest.json` of a PS snapshot dir, or None
    when absent/unreadable (pre-manifest snapshot dirs stay loadable —
    the per-table .pkl files are the state; the manifest is metadata)."""
    try:
        with open(os.path.join(dirname, "manifest.json")) as f:
            m = json.load(f)
        return m if isinstance(m, dict) else None
    except (OSError, ValueError):
        return None


def _validated_state(state, table, name):
    """Preload checkpoints fail LOUDLY instead of silently corrupting
    the table: a RemoteTable.state_dict() ({"servers": [...]}) unwraps
    only in the 1-server case, and the shard geometry must match the
    table this server actually hosts (a full-table checkpoint loaded
    into a multi-server PARTITION would misalign every row)."""
    if isinstance(state, dict) and "servers" in state:
        if len(state["servers"]) != 1:
            raise ValueError(
                f"preload {name!r}: checkpoint was saved from "
                f"{len(state['servers'])} pservers; restore it into the "
                f"same server count (per-server .pkl files)")
        state = state["servers"][0]
    shards = state.get("shards") if isinstance(state, dict) else None
    if shards is None:
        raise ValueError(
            f"preload {name!r}: not a table state_dict (expected a "
            f"'shards' key; got {type(state).__name__})")
    rows = sum(int(s.shape[0]) for s in shards)
    dims = {int(s.shape[1]) for s in shards}
    if rows != table.rows or dims != {table.dim}:
        raise ValueError(
            f"preload {name!r}: checkpoint geometry [{rows}, {dims}] "
            f"does not match this server's table "
            f"[{table.rows}, {table.dim}] — on multi-server deployments "
            f"each server needs ITS OWN partition checkpoint")
    if len(shards) != table.num_shards:
        raise ValueError(
            f"preload {name!r}: checkpoint has {len(shards)} shards, "
            f"table expects {table.num_shards}")
    return state


class _SyncState:
    """Per-table push barrier (sync mode): round r applies once all
    `num_trainers` contributions for r have arrived.

    Completion is tracked per-CONTRIBUTION (a token each waiter removes
    after waking) AND by an applied-round high-water mark used ONLY for
    replay dedup: `last_applied` is consulted when a push arrives with
    the `retry` marker (its first send may have landed before the
    connection died), never for first sends. Within one trainer-group
    GENERATION the mark is exact — sync rounds complete in lockstep, so
    a retried round number is either still pending (join the barrier) or
    <= last_applied (already merged: return without re-applying).

    A restarted trainer group restarts its step counter at 0, which
    would poison the high-water mark and leave half-filled rounds from
    the dead group in `rounds` — so the create_table handshake carries a
    `generation` (launch.py PADDLE_ELASTIC_RESTART) and the server swaps
    in a FRESH _SyncState when it bumps, marking the old one `reset` and
    waking its stale waiters to fail fast instead of timing out.

    `async_seen` / `delta_seen` are the barrier-less analogs: per-trainer
    high-water marks that dedup RETRIED async pushes / geo deltas.
    Downpour semantics make the high-water approximation safe: within one
    client, pushes are issued in step order, and async mode tolerates
    bounded reordering/loss by design."""

    def __init__(self, num_trainers: int):
        self.cond = threading.Condition()
        self.num = int(num_trainers)
        self.rounds: Dict[int, Dict[int, tuple]] = {}
        self.done: set = set()
        self.last_applied = -1
        self.async_seen: Dict[int, int] = {}
        self.delta_seen: Dict[int, int] = {}
        self.reset = False  # generation bumped: stale waiters fail fast


class PSServer:
    """Event loop owning the host tables (listen_and_serv analog).

    preload_dir (fleet.init_server(model_dir)): when a table is first
    created, `<preload_dir>/<name>.pkl` — a `table.state_dict()` pickle
    saved by a previous run — is loaded into it, the reference's
    init_server checkpoint-restore contract. Snapshots
    (snapshot_dir/snapshot_secs) write the SAME format, so a supervised
    restart preloads the latest snapshot through this path."""

    def __init__(self, preload_dir: Optional[str] = None,
                 snapshot_dir: Optional[str] = None,
                 snapshot_secs: float = 0.0):
        self.tables: Dict[str, ShardedHostTable] = {}
        self.specs: Dict[str, dict] = {}
        self.sync: Dict[str, _SyncState] = {}
        self.gens: Dict[str, int] = {}
        self.lock = threading.Lock()
        self.shutdown_event = threading.Event()
        self.preload_dir = preload_dir
        self.snapshot_dir = snapshot_dir or None
        self.snapshot_secs = float(snapshot_secs or 0.0)
        self._snap_thread: Optional[threading.Thread] = None
        # cross-job adoption: a stable snapshot dir carries a manifest
        # (snapshot epoch + trainer-group generation); a new job's
        # server picks up the epoch counter where the old job left it,
        # and serve() reports what was adopted
        self._snapshot_epoch = 0
        self.adopted_manifest: Optional[dict] = None
        if preload_dir:
            m = read_snapshot_manifest(preload_dir)
            if m is not None:
                self.adopted_manifest = m
                self._snapshot_epoch = int(m.get("snapshot_epoch", 0))

    # -- verbs -----------------------------------------------------------

    def create_table(self, spec: dict):
        """Idempotent across trainers: the first create wins; later
        creates with an IDENTICAL spec are no-ops, mismatches error.
        `generation` (not part of the identity spec) is the trainer
        group's restart attempt: a bump resets the sync barrier."""
        spec = dict(spec)
        gen = int(spec.pop("generation", 0))
        name = spec["name"]
        with self.lock:
            if name in self.tables:
                if spec != self.specs[name]:
                    raise ValueError(
                        f"table {name!r} already exists with a different "
                        f"spec: {self.specs[name]} vs {spec}")
                if gen > self.gens.get(name, 0):
                    # elastic restart: the new group must never share
                    # barrier state (half-filled rounds, applied marks,
                    # step high-water) with the dead one
                    old = self.sync[name]
                    self.sync[name] = _SyncState(old.num)
                    self.gens[name] = gen
                    with old.cond:
                        old.reset = True
                        old.cond.notify_all()
                return {"rows": self.tables[name].rows,
                        "dim": self.tables[name].dim}
            kw = {k: v for k, v in spec.items()
                  if k not in ("name", "shape", "sync_trainers")}
            t = ShardedHostTable(name, spec["shape"], **kw)
            if self.preload_dir:
                path = os.path.join(self.preload_dir, f"{name}.pkl")
                if os.path.exists(path):
                    with open(path, "rb") as f:
                        t.load_state_dict(
                            _validated_state(pickle.load(f), t, name))
            self.tables[name] = t
            self.specs[name] = dict(spec)
            self.sync[name] = _SyncState(int(spec.get("sync_trainers", 0)))
            self.gens[name] = gen
            return {"rows": t.rows, "dim": t.dim}

    def _table(self, name: str) -> ShardedHostTable:
        t = self.tables.get(name)
        if t is None:
            raise KeyError(f"no table {name!r} on this pserver")
        return t

    def gather(self, name, ids):
        return self._table(name).gather(ids)

    def push_gradients(self, name, ids, grads, trainer_id=0, step=0,
                       retry=False):
        table = self._table(name)
        st = self.sync[name]
        if st.num <= 1:
            # async / single trainer: apply on arrival (Downpour). A
            # RETRIED push whose first send already landed is skipped.
            with st.cond:
                if retry and st.async_seen.get(trainer_id, -1) >= step:
                    _REG.counter("ps_server_replay_dedup_total",
                                 help="retried pushes whose first send "
                                      "already landed (applied once)",
                                 verb="push_gradients").inc()
                    return 0
                st.async_seen[trainer_id] = max(
                    st.async_seen.get(trainer_id, -1), step)
            t0 = time.perf_counter()
            table.push_gradients(ids, grads)
            _emit_ps_step(name, "async", step, len(np.asarray(ids)),
                          (time.perf_counter() - t0) * 1e3)
            return 0
        token = object()
        merged = None  # (rows, apply_ms) when THIS call merged the round
        with st.cond:
            if retry and step <= st.last_applied:
                # replay of a round that merged before the reply was
                # lost: the update already landed exactly once
                _REG.counter("ps_server_replay_dedup_total",
                             verb="push_gradients").inc()
                return 0
            buf = st.rounds.setdefault(step, {})
            # overwrite-not-raise: a pre-existing same-trainer entry is a
            # dropped connection's orphan (its server thread still waits
            # on a token that will never complete and times out) — the
            # retry's token supersedes it
            buf[trainer_id] = (np.asarray(ids), np.asarray(grads), token)
            if len(buf) == st.num:
                # trainer-id order, not arrival order: the merged batch
                # is then exactly the single-process batch layout, so
                # duplicate-id float accumulation is order-identical
                ids_m = np.concatenate([buf[t][0] for t in sorted(buf)])
                g_m = np.concatenate([buf[t][1] for t in sorted(buf)])
                t0 = time.perf_counter()
                table.push_gradients(ids_m, g_m / st.num)
                merged = (len(ids_m), (time.perf_counter() - t0) * 1e3)
                for t in buf:
                    st.done.add(buf[t][2])
                st.done.discard(token)  # the merger does not wait
                st.last_applied = max(st.last_applied, step)
                del st.rounds[step]
                st.cond.notify_all()
            elif st.cond.wait_for(lambda: token in st.done or st.reset,
                                  timeout=SYNC_TIMEOUT):
                if token in st.done:
                    st.done.discard(token)  # each waiter prunes its own
                else:
                    # generation bump while we waited: our group is dead
                    raise RuntimeError(
                        f"sync-PS round abandoned: the trainer group "
                        f"restarted while table {name!r} round {step} "
                        f"was waiting for peers")
            else:
                # drop our contribution so the round can't half-fire if
                # this trainer is restarted and retries
                if step in st.rounds:
                    st.rounds[step].pop(trainer_id, None)
                raise RuntimeError(
                    f"sync-PS barrier timed out after {SYNC_TIMEOUT}s: "
                    f"only {len(st.rounds.get(step, {}))}/{st.num} "
                    f"trainers pushed table {name!r} round {step} — a "
                    f"peer trainer likely died")
        if merged is not None:
            # emitted outside the barrier lock: sink I/O must never
            # extend the round's critical section
            _emit_ps_step(name, "sync", step, merged[0], merged[1])
        return 0

    def push_delta(self, name, ids, deltas, trainer_id=0, seq=-1,
                   retry=False):
        table = self._table(name)
        if seq >= 0:
            st = self.sync[name]
            with st.cond:
                if retry and st.delta_seen.get(trainer_id, -1) >= seq:
                    _REG.counter("ps_server_replay_dedup_total",
                                 verb="push_delta").inc()
                    return 0  # replayed delta already accumulated
                st.delta_seen[trainer_id] = max(
                    st.delta_seen.get(trainer_id, -1), seq)
        t0 = time.perf_counter()
        table.push_delta(ids, deltas)
        _emit_ps_step(name, "delta", seq, len(np.asarray(ids)),
                      (time.perf_counter() - t0) * 1e3)
        return 0

    def handle(self, method: str, kwargs: dict):
        inj = faults.injector()
        if inj is not None:
            inj.on_server_call(method)  # may os._exit (kill rule)
        if kwargs.get("retry"):
            # the client marked this a replay attempt (its first send may
            # have landed); dedup hits are counted separately above
            _REG.counter("ps_server_retry_received_total",
                         help="RPCs arriving with the retry marker",
                         verb=method).inc()
        if method == "ping":
            return "pong"
        if method == "create_table":
            return self.create_table(kwargs["spec"])
        if method == "gather":
            return self.gather(kwargs["name"], kwargs["ids"])
        if method == "push_gradients":
            return self.push_gradients(
                kwargs["name"], kwargs["ids"], kwargs["grads"],
                kwargs.get("trainer_id", 0), kwargs.get("step", 0),
                kwargs.get("retry", False))
        if method == "push_delta":
            return self.push_delta(
                kwargs["name"], kwargs["ids"], kwargs["deltas"],
                kwargs.get("trainer_id", 0), kwargs.get("seq", -1),
                kwargs.get("retry", False))
        if method == "to_dense":
            return self._table(kwargs["name"]).to_dense()
        if method == "nbytes":
            return self._table(kwargs["name"]).nbytes()
        if method == "stats":
            # idempotent observability verb: per-table traffic counters
            # (when a name is given) + this server process's telemetry
            # registry slice — per-verb latency histogram summaries,
            # retry/replay-dedup counters, bytes in/out
            out = {"server": server_telemetry()}
            name = kwargs.get("name")
            if name:
                t = self._table(name)
                out["push_calls"] = t.push_calls
                out["pushed_bytes"] = t.pushed_bytes
            return out
        if method == "state_dict":
            return self._table(kwargs["name"]).state_dict()
        if method == "load_state_dict":
            self._table(kwargs["name"]).load_state_dict(kwargs["state"])
            return 0
        if method == "snapshot":
            return self.snapshot()
        if method == "drop_table":
            with self.lock:
                self.tables.pop(kwargs["name"], None)
                self.specs.pop(kwargs["name"], None)
                self.sync.pop(kwargs["name"], None)
                self.gens.pop(kwargs["name"], None)
            return 0
        if method == "shutdown":
            self.shutdown_event.set()
            return 0
        raise ValueError(f"unknown PS method {method!r}")

    # -- snapshots --------------------------------------------------------

    def snapshot(self) -> int:
        """Atomically checkpoint every hosted table to
        `<snapshot_dir>/<name>.pkl` (tmp + os.replace: a crash mid-write
        can never leave a torn file, so the newest snapshot on disk is
        always loadable). Same format as preload_dir, so a supervised
        restart restores it through the existing create_table path.
        A manifest.json (snapshot epoch, trainer-group generation, table
        geometries) is committed LAST, so a stable cross-job snapshot
        dir is self-describing: the next job's servers adopt the tables
        and the manifest tells operators what they adopted. Returns the
        number of tables written."""
        if not self.snapshot_dir:
            return 0
        os.makedirs(self.snapshot_dir, exist_ok=True)
        with self.lock:
            items = list(self.tables.items())
            gens = dict(self.gens)
        n = 0
        for name, t in items:
            _atomic_write(os.path.join(self.snapshot_dir, f"{name}.pkl"),
                          pickle.dumps(t.state_dict(),
                                       protocol=pickle.HIGHEST_PROTOCOL))
            n += 1
        if n:
            self._snapshot_epoch += 1
            manifest = {
                "format": 1,
                "snapshot_epoch": self._snapshot_epoch,
                "generation": max(gens.values(), default=0),
                "unix_time": time.time(),
                "tables": {
                    name: {"rows": t.rows, "dim": t.dim}
                    for name, t in items
                },
            }
            _atomic_write(os.path.join(self.snapshot_dir, "manifest.json"),
                          json.dumps(manifest, indent=1).encode())
        return n

    def start_snapshotter(self) -> None:
        if not (self.snapshot_dir and self.snapshot_secs > 0):
            return
        if self._snap_thread is not None:
            return

        def loop():
            while not self.shutdown_event.wait(self.snapshot_secs):
                try:
                    self.snapshot()
                except Exception as e:  # keep serving; snapshots degrade
                    print(f"[ps_server] snapshot failed: {e}",
                          file=sys.stderr, flush=True)

        self._snap_thread = threading.Thread(target=loop, daemon=True)
        self._snap_thread.start()


def server_telemetry() -> dict:
    """This process's ps_server_* registry slice, JSON-ready — the
    payload of the `stats` verb. Histograms dump as summaries
    (count/sum/min/max/avg); the Prometheus exposition carries full
    buckets for scrapers."""
    snap = _REG.snapshot()
    return {k: v for k, v in snap.items() if k.startswith("ps_server_")}


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        srv: PSServer = self.server.ps  # type: ignore[attr-defined]
        while True:
            try:
                (method, kwargs), n_in = _recv_msg_sized(self.request)
            except (ConnectionError, EOFError):
                return
            # counted at ARRIVAL, not after the reply: an RPC whose
            # client vanished mid-round-trip was still handled and must
            # show in the books deterministically
            _REG.counter("ps_server_rpc_total", verb=method).inc()
            _REG.counter("ps_server_bytes_in_total", verb=method).inc(n_in)
            t0 = time.perf_counter()
            try:
                result = srv.handle(method, kwargs)
                reply = (True, result)
            except BaseException as e:  # noqa: BLE001 — ship to client
                _REG.counter("ps_server_errors_total", verb=method).inc()
                reply = (False, f"{type(e).__name__}: {e}")
            _REG.histogram("ps_server_rpc_ms",
                           help="server-side verb handling latency "
                                "(sync pushes include the barrier wait)",
                           verb=method).observe(
                (time.perf_counter() - t0) * 1e3)
            try:
                n_out = _send_msg(self.request, reply)
            except OSError:
                return  # peer gone; the retry path owns recovery
            _REG.counter("ps_server_bytes_out_total", verb=method).inc(n_out)
            if srv.shutdown_event.is_set():
                threading.Thread(
                    target=self.server.shutdown, daemon=True).start()
                return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def serve(port: int = 0, host: str = "0.0.0.0", ready_cb=None,
          preload_dir: Optional[str] = None,
          snapshot_dir: Optional[str] = None,
          snapshot_secs: Optional[float] = None):
    """Run the pserver event loop (blocks). port=0 picks a free port;
    ready_cb (tests) receives the bound (host, port). Snapshot knobs
    default from PADDLE_PS_SNAPSHOT_DIR / PADDLE_PS_SNAPSHOT_SECS; a
    clean shutdown writes one final snapshot so a graceful restart is
    lossless (a crash loses at most one interval)."""
    if snapshot_dir is None:
        snapshot_dir = os.environ.get("PADDLE_PS_SNAPSHOT_DIR") or None
    if snapshot_secs is None:
        snapshot_secs = float(
            os.environ.get("PADDLE_PS_SNAPSHOT_SECS", 0) or 0)
    _arm_metrics_sink()
    srv = _TCPServer((host, port), _Handler)
    srv.ps = PSServer(preload_dir=preload_dir,  # type: ignore[attr-defined]
                      snapshot_dir=snapshot_dir,
                      snapshot_secs=snapshot_secs)
    srv.ps.start_snapshotter()
    # stamp liveness for the launcher's supervisor when heartbeats are on
    # (same channel trainers use; catches a HUNG pserver, not just death)
    hb = None
    hb_dir = os.environ.get("PADDLE_HEARTBEAT_DIR")
    hb_tag = os.environ.get("PADDLE_PS_RANK_TAG")
    if hb_dir and hb_tag:
        from .heartbeat import HeartBeatWorker

        hb = HeartBeatWorker(hb_dir, hb_tag).start()
    if ready_cb is not None:
        ready_cb(srv.server_address)
    if srv.ps.adopted_manifest is not None:
        # printed AFTER the ready banner: the launcher reads the first
        # stdout line to learn the bound port
        m = srv.ps.adopted_manifest
        print(f"[ps_server] adopting snapshot dir {preload_dir!r} "
              f"(epoch {m.get('snapshot_epoch')}, generation "
              f"{m.get('generation')}, tables "
              f"{sorted(m.get('tables', {}))})", flush=True)
    try:
        srv.serve_forever(poll_interval=0.1)
    finally:
        if hb is not None:
            hb.stop()
        srv.server_close()
        try:
            srv.ps.snapshot()
        except Exception as e:
            print(f"[ps_server] final snapshot failed: {e}",
                  file=sys.stderr, flush=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.ps_server")
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("PADDLE_PORT", 0)))
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--preload_dir", default=os.environ.get(
        "PADDLE_PS_PRELOAD_DIR", ""))
    p.add_argument("--snapshot_dir", default=os.environ.get(
        "PADDLE_PS_SNAPSHOT_DIR", ""))
    p.add_argument("--snapshot_secs", type=float, default=float(
        os.environ.get("PADDLE_PS_SNAPSHOT_SECS", 0) or 0))
    args = p.parse_args(argv)

    def ready(addr):
        # the launcher reads this line to learn the bound port
        print(f"[ps_server] listening on {addr[0]}:{addr[1]}", flush=True)

    serve(args.port, args.host, ready_cb=ready,
          preload_dir=args.preload_dir or None,
          snapshot_dir=args.snapshot_dir or None,
          snapshot_secs=args.snapshot_secs)
    return 0


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class _Conn:
    """Pooled client connections to ONE endpoint. Pooling (not one shared
    socket) matters: a sync-mode push BLOCKS in the server barrier, and a
    second table's push or a gather from another runtime thread must not
    queue behind it — the cross-table ordering deadlock the reference
    avoids with per-request gRPC calls (grpc_client.h AsyncSendVar).

    call() retries transport faults (ConnectionError / EOF / timeout /
    refused connect) with exponential backoff + jitter and a fresh
    socket per attempt, so a pserver restart is invisible to the caller.
    Replay-sensitive verbs (push_gradients, push_delta) are marked
    `retry=True` from the second attempt on; the server's dedup keys
    make the replay apply-once. Application errors the server REPLIED
    with are never retried — the RPC itself succeeded."""

    # verbs whose replay the server dedups via (trainer_id, step|seq)
    _MARK_RETRY = ("push_gradients", "push_delta")

    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self.addr = (host, int(port))
        self._free: List[socket.socket] = []
        self._lock = threading.Lock()

    def _checkout(self) -> socket.socket:
        with self._lock:
            if self._free:
                return self._free.pop()
        s = socket.create_connection(self.addr, timeout=SYNC_TIMEOUT + 30)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def call(self, method: str, **kwargs):
        inj = faults.injector()
        last_err: Optional[BaseException] = None
        t_rpc = time.perf_counter()
        sent_bytes = rcvd_bytes = 0
        for attempt in range(RPC_MAX_RETRIES + 1):
            if attempt:
                if method in self._MARK_RETRY:
                    kwargs["retry"] = True
                back = min(RPC_BACKOFF_CAP,
                           RPC_BACKOFF_BASE * (2 ** (attempt - 1)))
                time.sleep(back * (0.5 + random.random()))  # jittered
            s = None
            try:
                s = self._checkout()
                if inj is not None:
                    inj.before_send(method)  # refuse/delay rules
                sent_bytes += _send_msg(s, (method, kwargs))
                if inj is not None and inj.drop_after_send(method):
                    raise faults.FaultError(
                        f"fault injection: dropped connection after "
                        f"sending {method!r}")
                (ok, result), n_in = _recv_msg_sized(s)
                rcvd_bytes += n_in
            except (OSError, EOFError) as e:
                # includes ConnectionError, socket.timeout, refused
                # connects while a supervised pserver restarts
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                last_err = e
                continue
            except BaseException:
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                raise
            with self._lock:
                self._free.append(s)
            # per-verb client telemetry: wall latency INCLUDING backoff
            # (what the training step actually waited), retries, bytes
            _REG.histogram("ps_client_rpc_ms",
                           help="client RPC wall latency incl. retries",
                           verb=method).observe(
                (time.perf_counter() - t_rpc) * 1e3)
            _REG.counter("ps_client_rpc_total", verb=method).inc()
            if attempt:
                _REG.counter("ps_client_retries_total",
                             help="retried RPC attempts",
                             verb=method).inc(attempt)
            _REG.counter("ps_client_bytes_sent_total",
                         verb=method).inc(sent_bytes)
            _REG.counter("ps_client_bytes_received_total",
                         verb=method).inc(rcvd_bytes)
            if not ok:
                _REG.counter("ps_client_app_errors_total",
                             verb=method).inc()
                if isinstance(result, str) and result.startswith(
                        "KeyError") and "no table" in result:
                    raise TableMissingError(f"pserver {self.addr}: {result}")
                raise RuntimeError(f"pserver {self.addr}: {result}")
            return result
        _REG.counter("ps_client_rpc_failed_total", verb=method).inc()
        raise ConnectionError(
            f"pserver {self.addr}: RPC {method!r} still failing after "
            f"{RPC_MAX_RETRIES + 1} attempts: {last_err}") from last_err

    def close(self):
        with self._lock:
            for s in self._free:
                try:
                    s.close()
                except OSError:
                    pass
            self._free.clear()


class RemoteTable:
    """Client shim: the ShardedHostTable duck type over N pservers.

    Rows are round-robin sharded across servers (global row r lives on
    server r % n at local row r // n — the reference ps_dispatcher
    RoundRobin placement), so with one server the hosted table is
    byte-identical (same seed, same shape) to the in-process one.

    generation (default PADDLE_ELASTIC_RESTART): the trainer group's
    restart attempt, carried in the create_table handshake so a server
    that outlived the previous group resets its sync barrier. Every verb
    goes through _call, which re-creates the table (idempotent; the
    server preloads its latest snapshot) if a restarted pserver lost it.
    """

    def __init__(self, name, shape, endpoints: List[str],
                 dtype: str = "float32", num_shards: int = 4,
                 optimizer: str = "sgd", learning_rate: float = 0.1,
                 initializer_std: Optional[float] = None, seed: int = 0,
                 sync_trainers: int = 0, trainer_id: int = 0,
                 generation: Optional[int] = None):
        self.name = name
        self.rows, self.dim = int(shape[0]), int(shape[1])
        self.dtype = np.dtype(dtype)
        self.learning_rate = float(learning_rate)
        self.optimizer = optimizer
        self.endpoints = list(endpoints)
        self.trainer_id = int(trainer_id)
        self.generation = int(
            os.environ.get("PADDLE_ELASTIC_RESTART", 0)
            if generation is None else generation)
        self._n = len(self.endpoints)
        self._conns = [_Conn(e) for e in self.endpoints]
        self._step = 0
        self._delta_seq = 0
        self._step_lock = threading.Lock()
        # multi-server fan-out pool: per-server RPCs overlap instead of
        # serializing N round-trips (the reference's async gRPC client
        # model, grpc_client.h AsyncSendVar); connections are pooled per
        # endpoint so concurrent calls never share a socket
        self._pool = None
        if self._n > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(max_workers=self._n)
        self._specs: List[dict] = []
        for s in range(self._n):
            n_rows = (self.rows - s + self._n - 1) // self._n
            self._specs.append({
                "name": name, "shape": (n_rows, self.dim),
                "dtype": str(self.dtype), "num_shards": num_shards,
                "optimizer": optimizer, "learning_rate": learning_rate,
                "initializer_std": initializer_std,
                # distinct per-server streams when sharded; the single-
                # server layout reproduces the local table bit-for-bit
                "seed": seed if self._n == 1 else seed + s,
                "sync_trainers": sync_trainers,
                "generation": self.generation,
            })
        for s, conn in enumerate(self._conns):
            conn.call("create_table", spec=self._specs[s])

    # -- addressing ------------------------------------------------------
    def _locate(self, ids: np.ndarray):
        if ids.size and (ids.min() < 0 or ids.max() >= self.rows):
            bad = ids[(ids < 0) | (ids >= self.rows)][0]
            raise IndexError(
                f"table {self.name!r}: id {int(bad)} out of range "
                f"[0, {self.rows})")
        return ids % self._n, ids // self._n

    def _call(self, s: int, method: str, **kwargs):
        """One server's RPC with restart recovery: a pserver that came
        back empty (supervised respawn) gets the idempotent create —
        which preloads its latest snapshot — and the verb is replayed."""
        try:
            return self._conns[s].call(method, **kwargs)
        except TableMissingError:
            self._conns[s].call("create_table", spec=self._specs[s])
            return self._conns[s].call(method, **kwargs)

    def _fanout(self, thunks):
        """Run one thunk per server, overlapped when a pool exists."""
        if self._pool is None:
            return [t() for t in thunks]
        return [f.result() for f in
                [self._pool.submit(t) for t in thunks]]

    # -- serving ---------------------------------------------------------
    def gather(self, ids) -> np.ndarray:
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        srv, local = self._locate(ids)
        out = np.empty((ids.shape[0], self.dim), self.dtype)
        masks = [srv == s for s in range(self._n)]
        rows = self._fanout([
            (lambda s=s, m=m: self._call(
                s, "gather", name=self.name, ids=local[m]))
            if m.any() else (lambda: None)
            for s, m in enumerate(masks)
        ])
        for m, r in zip(masks, rows):
            if r is not None:
                out[m] = r
        return out

    def push_gradients(self, ids, grads) -> None:
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        grads = np.asarray(grads, np.float32).reshape(ids.shape[0], self.dim)
        with self._step_lock:
            step = self._step
            self._step += 1
        srv, local = self._locate(ids)
        # every server participates in every sync round (even with zero
        # rows) so its barrier bookkeeping sees all trainers each step;
        # overlapped: in sync mode each call blocks on the barrier
        self._fanout([
            lambda s=s: self._call(
                s, "push_gradients", name=self.name, ids=local[srv == s],
                grads=grads[srv == s], trainer_id=self.trainer_id,
                step=step)
            for s in range(self._n)
        ])

    def push_delta(self, ids, deltas) -> None:
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        deltas = np.asarray(deltas, np.float32).reshape(
            ids.shape[0], self.dim)
        with self._step_lock:
            seq = self._delta_seq
            self._delta_seq += 1
        srv, local = self._locate(ids)
        masks = [srv == s for s in range(self._n)]
        self._fanout([
            (lambda s=s, m=m: self._call(
                s, "push_delta", name=self.name, ids=local[m],
                deltas=deltas[m], trainer_id=self.trainer_id, seq=seq))
            if m.any() else (lambda: None)
            for s, m in enumerate(masks)
        ])

    # -- introspection / checkpoint --------------------------------------
    def nbytes(self) -> int:
        return sum(self._call(s, "nbytes", name=self.name)
                   for s in range(self._n))

    def stats(self) -> dict:
        """Aggregated table traffic counters + each pserver's telemetry
        slice under "servers" (the idempotent `stats` verb)."""
        agg = {"push_calls": 0, "pushed_bytes": 0, "servers": []}
        for s in range(self._n):
            st = self._call(s, "stats", name=self.name)
            agg["push_calls"] += st["push_calls"]
            agg["pushed_bytes"] += st["pushed_bytes"]
            agg["servers"].append(st.get("server", {}))
        return agg

    def server_stats(self) -> List[dict]:
        """Per-pserver telemetry snapshots (no table counters) — verb
        latencies, retry/replay-dedup counters, bytes in/out."""
        return [self._conns[s].call("stats").get("server", {})
                for s in range(self._n)]

    def to_dense(self) -> np.ndarray:
        out = np.empty((self.rows, self.dim), self.dtype)
        for s in range(self._n):
            out[s::self._n] = self._call(s, "to_dense", name=self.name)
        return out

    def state_dict(self):
        return {"servers": [self._call(s, "state_dict", name=self.name)
                            for s in range(self._n)]}

    def load_state_dict(self, state):
        if "servers" in state:
            for s, st in enumerate(state["servers"]):
                self._call(s, "load_state_dict", name=self.name, state=st)
        else:  # a local-table checkpoint restored into a hosted run
            if self._n != 1:
                raise ValueError(
                    "single-table checkpoint needs exactly 1 pserver")
            self._call(0, "load_state_dict", name=self.name, state=state)

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        for c in self._conns:
            c.close()


# ---------------------------------------------------------------------------
# env contract
# ---------------------------------------------------------------------------


def pserver_endpoints() -> List[str]:
    """PADDLE_PSERVERS_IP_PORT_LIST (reference role_maker.py:497)."""
    raw = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
    return [e.strip() for e in raw.split(",") if e.strip()]


def training_role() -> str:
    return os.environ.get("PADDLE_TRAINING_ROLE", "TRAINER").upper()


if __name__ == "__main__":
    sys.exit(main())
