"""Networked parameter-server data plane: tables in a server PROCESS.

Parity surface: the reference's cross-process PS runtime —
operators/distributed_ops/listen_and_serv_op.cc (server event loop),
operators/distributed/grpc/grpc_client.h:176 (async client),
operators/distributed/communicator.h:180-396 (send queues, Geo), and the
PADDLE_PSERVERS_IP_PORT_LIST / PADDLE_TRAINING_ROLE / PADDLE_PORT env
contract (fleet/base/role_maker.py:497).

TPU-native redesign: the device step only ever sees [batch, dim] row
slices through the existing gather/push callbacks (ops/ps_ops.py), so
the wire protocol is four verbs over TCP — create / gather / push /
admin — not a full RPC graph executor. One server process (or several,
round-robin row-sharded like the reference ps_dispatcher) owns
ShardedHostTable instances; N launcher-spawned trainer processes talk to
it through RemoteTable, which is duck-type identical to the in-process
table, so ops/ps_ops.py and GeoSGDClient run unchanged on top.

Sync semantics (reference DistributeTranspiler sync_mode): in
`sync` mode the server BARRIERS each push round — it accumulates one
push per trainer, merges them (concat + dedup scatter-add, scaled
1/num_trainers: dp-mean convention, same as the framework's allreduce
mean), applies the optimizer ONCE, then releases every waiter. Two
trainers each pushing d(mean loss over their half-batch) therefore
produce exactly the single-process full-batch update — the loss-parity
contract tests/test_ps_dist.py asserts. `async` skips the barrier
(Downpour: apply on arrival); `geo` trainers push deltas (additive,
no barrier) through GeoSGDClient wrapping a RemoteTable.

Framing: 8-byte big-endian length + pickle (trusted cluster transport,
like the reference's protobuf-over-gRPC — auth/encryption is deployment
infra, not the data plane's job).
"""
from __future__ import annotations

import argparse
import os
import pickle
import socket
import socketserver
import struct
import sys
import threading
from typing import Dict, List, Optional

import numpy as np

from .ps import ShardedHostTable

_LEN = struct.Struct(">Q")

# a barrier that outlives this window means a peer trainer died mid-step:
# fail fast so the launcher's watcher can abort/restart the group
SYNC_TIMEOUT = float(os.environ.get("PADDLE_PS_SYNC_TIMEOUT", 120.0))


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed the PS connection")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


def _validated_state(state, table, name):
    """Preload checkpoints fail LOUDLY instead of silently corrupting
    the table: a RemoteTable.state_dict() ({"servers": [...]}) unwraps
    only in the 1-server case, and the shard geometry must match the
    table this server actually hosts (a full-table checkpoint loaded
    into a multi-server PARTITION would misalign every row)."""
    if isinstance(state, dict) and "servers" in state:
        if len(state["servers"]) != 1:
            raise ValueError(
                f"preload {name!r}: checkpoint was saved from "
                f"{len(state['servers'])} pservers; restore it into the "
                f"same server count (per-server .pkl files)")
        state = state["servers"][0]
    shards = state.get("shards") if isinstance(state, dict) else None
    if shards is None:
        raise ValueError(
            f"preload {name!r}: not a table state_dict (expected a "
            f"'shards' key; got {type(state).__name__})")
    rows = sum(int(s.shape[0]) for s in shards)
    dims = {int(s.shape[1]) for s in shards}
    if rows != table.rows or dims != {table.dim}:
        raise ValueError(
            f"preload {name!r}: checkpoint geometry [{rows}, {dims}] "
            f"does not match this server's table "
            f"[{table.rows}, {table.dim}] — on multi-server deployments "
            f"each server needs ITS OWN partition checkpoint")
    if len(shards) != table.num_shards:
        raise ValueError(
            f"preload {name!r}: checkpoint has {len(shards)} shards, "
            f"table expects {table.num_shards}")
    return state


class _SyncState:
    """Per-table push barrier (sync mode): round r applies once all
    `num_trainers` contributions for r have arrived.

    Completion is tracked per-CONTRIBUTION (a token each waiter removes
    after waking), not by an applied-step high-water mark — a restarted
    trainer group (launch.py --elastic_retries; the server process
    deliberately outlives restarts so hosted tables survive) restarts
    its step counter at 0, and a high-water mark would let its pushes
    return before the merge. A push that finds a stale same-trainer
    entry in its round (left by a crashed group) simply overwrites it:
    the dead process no longer waits, and a live trainer never pushes
    the same (table, round) twice by construction (the client's step
    counter increments per push)."""

    def __init__(self, num_trainers: int):
        self.cond = threading.Condition()
        self.num = int(num_trainers)
        self.rounds: Dict[int, Dict[int, tuple]] = {}
        self.done: set = set()


class PSServer:
    """Event loop owning the host tables (listen_and_serv analog).

    preload_dir (fleet.init_server(model_dir)): when a table is first
    created, `<preload_dir>/<name>.pkl` — a `table.state_dict()` pickle
    saved by a previous run — is loaded into it, the reference's
    init_server checkpoint-restore contract."""

    def __init__(self, preload_dir: Optional[str] = None):
        self.tables: Dict[str, ShardedHostTable] = {}
        self.specs: Dict[str, dict] = {}
        self.sync: Dict[str, _SyncState] = {}
        self.lock = threading.Lock()
        self.shutdown_event = threading.Event()
        self.preload_dir = preload_dir

    # -- verbs -----------------------------------------------------------

    def create_table(self, spec: dict):
        """Idempotent across trainers: the first create wins; later
        creates with an IDENTICAL spec are no-ops, mismatches error."""
        name = spec["name"]
        with self.lock:
            if name in self.tables:
                if spec != self.specs[name]:
                    raise ValueError(
                        f"table {name!r} already exists with a different "
                        f"spec: {self.specs[name]} vs {spec}")
                return {"rows": self.tables[name].rows,
                        "dim": self.tables[name].dim}
            kw = {k: v for k, v in spec.items()
                  if k not in ("name", "shape", "sync_trainers")}
            t = ShardedHostTable(name, spec["shape"], **kw)
            if self.preload_dir:
                path = os.path.join(self.preload_dir, f"{name}.pkl")
                if os.path.exists(path):
                    with open(path, "rb") as f:
                        t.load_state_dict(
                            _validated_state(pickle.load(f), t, name))
            self.tables[name] = t
            self.specs[name] = dict(spec)
            self.sync[name] = _SyncState(int(spec.get("sync_trainers", 0)))
            return {"rows": t.rows, "dim": t.dim}

    def _table(self, name: str) -> ShardedHostTable:
        t = self.tables.get(name)
        if t is None:
            raise KeyError(f"no table {name!r} on this pserver")
        return t

    def gather(self, name, ids):
        return self._table(name).gather(ids)

    def push_gradients(self, name, ids, grads, trainer_id=0, step=0):
        table = self._table(name)
        st = self.sync[name]
        if st.num <= 1:
            table.push_gradients(ids, grads)  # async / single trainer
            return 0
        token = object()
        with st.cond:
            buf = st.rounds.setdefault(step, {})
            # overwrite-not-raise: a pre-existing entry can only be a
            # crashed group's leftover (see _SyncState docstring)
            buf[trainer_id] = (np.asarray(ids), np.asarray(grads), token)
            if len(buf) == st.num:
                # trainer-id order, not arrival order: the merged batch
                # is then exactly the single-process batch layout, so
                # duplicate-id float accumulation is order-identical
                ids_m = np.concatenate([buf[t][0] for t in sorted(buf)])
                g_m = np.concatenate([buf[t][1] for t in sorted(buf)])
                table.push_gradients(ids_m, g_m / st.num)
                for t in buf:
                    st.done.add(buf[t][2])
                st.done.discard(token)  # the merger does not wait
                del st.rounds[step]
                st.cond.notify_all()
            elif st.cond.wait_for(lambda: token in st.done,
                                  timeout=SYNC_TIMEOUT):
                st.done.discard(token)  # each waiter prunes its own
            else:
                # drop our contribution so the round can't half-fire if
                # this trainer is restarted and retries
                if step in st.rounds:
                    st.rounds[step].pop(trainer_id, None)
                raise RuntimeError(
                    f"sync-PS barrier timed out after {SYNC_TIMEOUT}s: "
                    f"only {len(st.rounds.get(step, {}))}/{st.num} "
                    f"trainers pushed table {name!r} round {step} — a "
                    f"peer trainer likely died")
        return 0

    def push_delta(self, name, ids, deltas):
        self._table(name).push_delta(ids, deltas)
        return 0

    def handle(self, method: str, kwargs: dict):
        if method == "ping":
            return "pong"
        if method == "create_table":
            return self.create_table(kwargs["spec"])
        if method == "gather":
            return self.gather(kwargs["name"], kwargs["ids"])
        if method == "push_gradients":
            return self.push_gradients(
                kwargs["name"], kwargs["ids"], kwargs["grads"],
                kwargs.get("trainer_id", 0), kwargs.get("step", 0))
        if method == "push_delta":
            return self.push_delta(
                kwargs["name"], kwargs["ids"], kwargs["deltas"])
        if method == "to_dense":
            return self._table(kwargs["name"]).to_dense()
        if method == "nbytes":
            return self._table(kwargs["name"]).nbytes()
        if method == "stats":
            t = self._table(kwargs["name"])
            return {"push_calls": t.push_calls,
                    "pushed_bytes": t.pushed_bytes}
        if method == "state_dict":
            return self._table(kwargs["name"]).state_dict()
        if method == "load_state_dict":
            self._table(kwargs["name"]).load_state_dict(kwargs["state"])
            return 0
        if method == "drop_table":
            with self.lock:
                self.tables.pop(kwargs["name"], None)
                self.specs.pop(kwargs["name"], None)
                self.sync.pop(kwargs["name"], None)
            return 0
        if method == "shutdown":
            self.shutdown_event.set()
            return 0
        raise ValueError(f"unknown PS method {method!r}")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        srv: PSServer = self.server.ps  # type: ignore[attr-defined]
        while True:
            try:
                method, kwargs = _recv_msg(self.request)
            except (ConnectionError, EOFError):
                return
            try:
                result = srv.handle(method, kwargs)
                _send_msg(self.request, (True, result))
            except BaseException as e:  # noqa: BLE001 — ship to client
                try:
                    _send_msg(self.request, (False, f"{type(e).__name__}: {e}"))
                except OSError:
                    return
            if srv.shutdown_event.is_set():
                threading.Thread(
                    target=self.server.shutdown, daemon=True).start()
                return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def serve(port: int = 0, host: str = "0.0.0.0", ready_cb=None,
          preload_dir: Optional[str] = None):
    """Run the pserver event loop (blocks). port=0 picks a free port;
    ready_cb (tests) receives the bound (host, port)."""
    srv = _TCPServer((host, port), _Handler)
    srv.ps = PSServer(preload_dir=preload_dir)  # type: ignore[attr-defined]
    if ready_cb is not None:
        ready_cb(srv.server_address)
    try:
        srv.serve_forever(poll_interval=0.1)
    finally:
        srv.server_close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.ps_server")
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("PADDLE_PORT", 0)))
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--preload_dir", default=os.environ.get(
        "PADDLE_PS_PRELOAD_DIR", ""))
    args = p.parse_args(argv)

    def ready(addr):
        # the launcher reads this line to learn the bound port
        print(f"[ps_server] listening on {addr[0]}:{addr[1]}", flush=True)

    serve(args.port, args.host, ready_cb=ready,
          preload_dir=args.preload_dir or None)
    return 0


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class _Conn:
    """Pooled client connections to ONE endpoint. Pooling (not one shared
    socket) matters: a sync-mode push BLOCKS in the server barrier, and a
    second table's push or a gather from another runtime thread must not
    queue behind it — the cross-table ordering deadlock the reference
    avoids with per-request gRPC calls (grpc_client.h AsyncSendVar)."""

    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self.addr = (host, int(port))
        self._free: List[socket.socket] = []
        self._lock = threading.Lock()

    def _checkout(self) -> socket.socket:
        with self._lock:
            if self._free:
                return self._free.pop()
        s = socket.create_connection(self.addr, timeout=SYNC_TIMEOUT + 30)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def call(self, method: str, **kwargs):
        s = self._checkout()
        try:
            _send_msg(s, (method, kwargs))
            ok, result = _recv_msg(s)
        except BaseException:
            try:
                s.close()
            finally:
                pass
            raise
        with self._lock:
            self._free.append(s)
        if not ok:
            raise RuntimeError(f"pserver {self.addr}: {result}")
        return result

    def close(self):
        with self._lock:
            for s in self._free:
                try:
                    s.close()
                except OSError:
                    pass
            self._free.clear()


class RemoteTable:
    """Client shim: the ShardedHostTable duck type over N pservers.

    Rows are round-robin sharded across servers (global row r lives on
    server r % n at local row r // n — the reference ps_dispatcher
    RoundRobin placement), so with one server the hosted table is
    byte-identical (same seed, same shape) to the in-process one.
    """

    def __init__(self, name, shape, endpoints: List[str],
                 dtype: str = "float32", num_shards: int = 4,
                 optimizer: str = "sgd", learning_rate: float = 0.1,
                 initializer_std: Optional[float] = None, seed: int = 0,
                 sync_trainers: int = 0, trainer_id: int = 0):
        self.name = name
        self.rows, self.dim = int(shape[0]), int(shape[1])
        self.dtype = np.dtype(dtype)
        self.learning_rate = float(learning_rate)
        self.optimizer = optimizer
        self.endpoints = list(endpoints)
        self.trainer_id = int(trainer_id)
        self._n = len(self.endpoints)
        self._conns = [_Conn(e) for e in self.endpoints]
        self._step = 0
        self._step_lock = threading.Lock()
        # multi-server fan-out pool: per-server RPCs overlap instead of
        # serializing N round-trips (the reference's async gRPC client
        # model, grpc_client.h AsyncSendVar); connections are pooled per
        # endpoint so concurrent calls never share a socket
        self._pool = None
        if self._n > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(max_workers=self._n)
        for s, conn in enumerate(self._conns):
            n_rows = (self.rows - s + self._n - 1) // self._n
            conn.call("create_table", spec={
                "name": name, "shape": (n_rows, self.dim),
                "dtype": str(self.dtype), "num_shards": num_shards,
                "optimizer": optimizer, "learning_rate": learning_rate,
                "initializer_std": initializer_std,
                # distinct per-server streams when sharded; the single-
                # server layout reproduces the local table bit-for-bit
                "seed": seed if self._n == 1 else seed + s,
                "sync_trainers": sync_trainers,
            })

    # -- addressing ------------------------------------------------------
    def _locate(self, ids: np.ndarray):
        if ids.size and (ids.min() < 0 or ids.max() >= self.rows):
            bad = ids[(ids < 0) | (ids >= self.rows)][0]
            raise IndexError(
                f"table {self.name!r}: id {int(bad)} out of range "
                f"[0, {self.rows})")
        return ids % self._n, ids // self._n

    def _fanout(self, thunks):
        """Run one thunk per server, overlapped when a pool exists."""
        if self._pool is None:
            return [t() for t in thunks]
        return [f.result() for f in
                [self._pool.submit(t) for t in thunks]]

    # -- serving ---------------------------------------------------------
    def gather(self, ids) -> np.ndarray:
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        srv, local = self._locate(ids)
        out = np.empty((ids.shape[0], self.dim), self.dtype)
        masks = [srv == s for s in range(self._n)]
        rows = self._fanout([
            (lambda s=s, m=m: self._conns[s].call(
                "gather", name=self.name, ids=local[m]))
            if m.any() else (lambda: None)
            for s, m in enumerate(masks)
        ])
        for m, r in zip(masks, rows):
            if r is not None:
                out[m] = r
        return out

    def push_gradients(self, ids, grads) -> None:
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        grads = np.asarray(grads, np.float32).reshape(ids.shape[0], self.dim)
        with self._step_lock:
            step = self._step
            self._step += 1
        srv, local = self._locate(ids)
        # every server participates in every sync round (even with zero
        # rows) so its barrier bookkeeping sees all trainers each step;
        # overlapped: in sync mode each call blocks on the barrier
        self._fanout([
            lambda s=s: self._conns[s].call(
                "push_gradients", name=self.name, ids=local[srv == s],
                grads=grads[srv == s], trainer_id=self.trainer_id,
                step=step)
            for s in range(self._n)
        ])

    def push_delta(self, ids, deltas) -> None:
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        deltas = np.asarray(deltas, np.float32).reshape(
            ids.shape[0], self.dim)
        srv, local = self._locate(ids)
        masks = [srv == s for s in range(self._n)]
        self._fanout([
            (lambda s=s, m=m: self._conns[s].call(
                "push_delta", name=self.name, ids=local[m],
                deltas=deltas[m]))
            if m.any() else (lambda: None)
            for s, m in enumerate(masks)
        ])

    # -- introspection / checkpoint --------------------------------------
    def nbytes(self) -> int:
        return sum(c.call("nbytes", name=self.name) for c in self._conns)

    def stats(self) -> dict:
        agg = {"push_calls": 0, "pushed_bytes": 0}
        for c in self._conns:
            st = c.call("stats", name=self.name)
            for k in agg:
                agg[k] += st[k]
        return agg

    def to_dense(self) -> np.ndarray:
        out = np.empty((self.rows, self.dim), self.dtype)
        for s in range(self._n):
            out[s::self._n] = self._conns[s].call(
                "to_dense", name=self.name)
        return out

    def state_dict(self):
        return {"servers": [c.call("state_dict", name=self.name)
                            for c in self._conns]}

    def load_state_dict(self, state):
        if "servers" in state:
            for c, st in zip(self._conns, state["servers"]):
                c.call("load_state_dict", name=self.name, state=st)
        else:  # a local-table checkpoint restored into a hosted run
            if self._n != 1:
                raise ValueError(
                    "single-table checkpoint needs exactly 1 pserver")
            self._conns[0].call(
                "load_state_dict", name=self.name, state=state)

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        for c in self._conns:
            c.close()


# ---------------------------------------------------------------------------
# env contract
# ---------------------------------------------------------------------------


def pserver_endpoints() -> List[str]:
    """PADDLE_PSERVERS_IP_PORT_LIST (reference role_maker.py:497)."""
    raw = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
    return [e.strip() for e in raw.split(",") if e.strip()]


def training_role() -> str:
    return os.environ.get("PADDLE_TRAINING_ROLE", "TRAINER").upper()


if __name__ == "__main__":
    sys.exit(main())
