"""Networked parameter-server data plane: tables in a server PROCESS.

Parity surface: the reference's cross-process PS runtime —
operators/distributed_ops/listen_and_serv_op.cc (server event loop),
operators/distributed/grpc/grpc_client.h:176 (async client),
operators/distributed/communicator.h:180-396 (send queues, Geo), and the
PADDLE_PSERVERS_IP_PORT_LIST / PADDLE_TRAINING_ROLE / PADDLE_PORT env
contract (fleet/base/role_maker.py:497).

TPU-native redesign: the device step only ever sees [batch, dim] row
slices through the existing gather/push callbacks (ops/ps_ops.py), so
the wire protocol is four verbs over TCP — create / gather / push /
admin — not a full RPC graph executor. One server process (or several,
round-robin row-sharded like the reference ps_dispatcher) owns
ShardedHostTable instances; N launcher-spawned trainer processes talk to
it through RemoteTable, which is duck-type identical to the in-process
table, so ops/ps_ops.py and GeoSGDClient run unchanged on top.

Sync semantics (reference DistributeTranspiler sync_mode): in
`sync` mode the server BARRIERS each push round — it accumulates one
push per trainer, merges them (concat + dedup scatter-add, scaled
1/num_trainers: dp-mean convention, same as the framework's allreduce
mean), applies the optimizer ONCE, then releases every waiter. Two
trainers each pushing d(mean loss over their half-batch) therefore
produce exactly the single-process full-batch update — the loss-parity
contract tests/test_ps_dist.py asserts. `async` skips the barrier
(Downpour: apply on arrival); `geo` trainers push deltas (additive,
no barrier) through GeoSGDClient wrapping a RemoteTable.

Fault tolerance (tests/test_ps_faults.py):

  client   — every RPC runs in a retry loop: per-attempt socket, exp
             backoff with jitter, transparent reconnect on
             ConnectionError/EOF/timeout. Idempotent verbs retry freely;
             push_gradients / push_delta carry a (trainer_id, step|seq)
             dedup key and a `retry` marker so a replayed push that
             already LANDED (reply lost) is applied exactly once.
  server   — `generation` rides the create_table handshake
             (PADDLE_ELASTIC_RESTART): a restarted trainer group bumps
             it and the server RESETS the table's push barrier, so the
             half-filled round a crashed group left behind can never
             merge with — or deadlock — the new group's pushes.
  state    — periodic atomic snapshots (state_dict -> tmp + os.replace,
             PADDLE_PS_SNAPSHOT_SECS / PADDLE_PS_SNAPSHOT_DIR); a
             supervised restart (launch.py) preloads them, and a client
             that finds its table missing after a server restart
             re-issues the idempotent create_table (which restores the
             snapshot) and replays the verb — a pserver crash costs at
             most one snapshot interval of updates (Downpour
             bounded-staleness), not the job.
  faults   — distributed/faults.py injects drop/refuse/delay/kill on a
             deterministic schedule (FLAGS_ps_fault_injection +
             PADDLE_PS_FAULT_SPEC); flag-off is bit-identical.

Framing: 8-byte big-endian length + pickle (trusted cluster transport,
like the reference's protobuf-over-gRPC — auth/encryption is deployment
infra, not the data plane's job).
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import pickle
import random
import socket
import socketserver
import struct
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from . import faults
from .ps import ShardedHostTable
from ..telemetry import BYTE_BUCKETS, get_registry
from ..telemetry import sink as _metrics_sink
from ..telemetry import tracing as _tracing

_LEN = struct.Struct(">Q")

# process metrics registry (paddle_tpu.telemetry): client- and server-
# side series use disjoint name prefixes (ps_client_* / ps_server_*) so
# in-process test servers sharing the registry stay distinguishable
_REG = get_registry()

def _arm_metrics_sink() -> None:
    """Pserver-side JSONL records on the SAME env var trainers use
    (PADDLE_METRICS_PATH, ROADMAP telemetry follow-on): the path gets a
    per-process `ps` tag (launch.py's PADDLE_PS_RANK_TAG, pid fallback)
    so a co-located trainer's rank-0 file is never interleaved. Unset =
    sink stays off and every emit below is a no-op."""
    path = os.environ.get(_metrics_sink.ENV_PATH)
    if not path:
        return
    tag = os.environ.get("PADDLE_PS_RANK_TAG") or f"ps{os.getpid()}"
    root, ext = os.path.splitext(path)
    _metrics_sink.enable(f"{root}.{tag}{ext or '.jsonl'}")


def _emit_ps_step(table: str, mode: str, step: int, rows: int,
                  apply_ms: float) -> None:
    """One kind="ps_step" JSONL record per APPLIED update — the pserver's
    analog of the trainer's kind="step" record (a sync round merges once;
    async/delta pushes apply on arrival)."""
    _metrics_sink.emit({
        "kind": "ps_step", "table": table, "mode": mode,
        "step": int(step), "rows": int(rows),
        "apply_ms": round(apply_ms, 3),
    })


# a barrier that outlives this window means a peer trainer died mid-round:
# fail fast so the launcher's watcher can abort/restart the group
SYNC_TIMEOUT = float(os.environ.get("PADDLE_PS_SYNC_TIMEOUT", 120.0))

# client retry envelope: total in-band wait ~= sum of capped backoffs,
# sized to ride out a supervised pserver restart (launch.py respawn:
# poll interval + python startup, a few seconds) with room to spare
RPC_MAX_RETRIES = int(os.environ.get("PADDLE_PS_RPC_RETRIES", 10))
RPC_BACKOFF_BASE = float(os.environ.get("PADDLE_PS_RPC_BACKOFF", 0.05))
RPC_BACKOFF_CAP = float(os.environ.get("PADDLE_PS_RPC_BACKOFF_CAP", 2.0))

# overall per-RPC deadline (seconds): when > 0 the retry LOOP is bounded
# by wall time, not attempt count — the knob that makes replicated
# failover trigger in bounded time instead of riding the backoff ladder.
# 0 (default) keeps the attempt-count bound exactly as before; a
# replicated RemoteTable defaults its connections to
# REPLICATED_DEADLINE_DEFAULT when the env is unset
RPC_DEADLINE = float(os.environ.get("PADDLE_PS_CALL_DEADLINE_SECS", 0) or 0)
REPLICATED_DEADLINE_DEFAULT = 10.0

# replication knobs (all inert at R=1):
#   hedge quantile    — read-only verbs hedge to a backup after the
#                       verb's observed latency quantile (0 disables)
#   hedge min samples — don't trust the histogram before this many obs
#   forward deadline  — how long a primary waits on a backup ack before
#                       dropping it from the forward set (it resyncs on
#                       rejoin)
#   replog keep       — per-partition ring of recent applied writes for
#                       seq-tail catch-up (anti-entropy without a full
#                       state transfer)
#   rejoin secs       — how long a client keeps trying to re-enroll a
#                       dead replica after failing over away from it
HEDGE_QUANTILE = float(os.environ.get("PADDLE_PS_HEDGE_QUANTILE", 0.95) or 0)
HEDGE_MIN_SAMPLES = int(os.environ.get("PADDLE_PS_HEDGE_MIN_SAMPLES", 16))
FORWARD_DEADLINE = float(
    os.environ.get("PADDLE_PS_FORWARD_DEADLINE_SECS", 5.0))
REPLOG_KEEP = int(os.environ.get("PADDLE_PS_REPLOG_KEEP", 256))
REJOIN_SECS = float(os.environ.get("PADDLE_PS_REJOIN_SECS", 120.0))

# incremental snapshots: compact the delta chain into a fresh base every
# N deltas (and implicitly on load — a restored chain forces a new base)
SNAPSHOT_COMPACT_EVERY = int(
    os.environ.get("PADDLE_PS_SNAPSHOT_COMPACT_EVERY", 8))


class TableMissingError(RuntimeError):
    """Server says the table does not exist — after a pserver restart the
    client re-creates it (idempotent; the server's preload_dir restores
    the latest snapshot) and replays the verb (RemoteTable._call)."""


class NotPrimaryError(RuntimeError):
    """A write verb reached a backup (or unpromoted) replica — the
    client re-resolves the partition's primary and replays."""


class StalePrimaryError(RuntimeError):
    """This replica was deposed (a newer epoch exists) or is awaiting
    resync; it must not serve until anti-entropy catches it up. Raised
    both at a deposed primary (its forward was epoch-rejected) and to
    clients that reach a stale replica."""


def _table_key(name: str, partition=None) -> str:
    """Server-side table identity. Unreplicated tables keep the bare
    name (R=1 wire + snapshot filenames byte-identical); replicated
    partitions get a `@p<idx>` suffix because one server hosts its own
    primary partition AND backup copies of its neighbours' under the
    same logical table name."""
    return name if partition is None else f"{name}@p{int(partition)}"


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def _send_msg(sock: socket.socket, obj) -> int:
    """Returns wire bytes written (framing + payload) for telemetry."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)
    return _LEN.size + len(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed the PS connection")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


def _recv_msg_sized(sock: socket.socket):
    """(message, wire bytes read) — the telemetry-aware receive."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n)), _LEN.size + n


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


def _atomic_write(path: str, blob: bytes) -> None:
    """tmp + os.replace (the fluid/io.py contract): a crash mid-write
    can never leave a torn file at `path`."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def read_snapshot_manifest(dirname: str) -> Optional[dict]:
    """Parsed `<dirname>/manifest.json` of a PS snapshot dir, or None
    when absent/unreadable (pre-manifest snapshot dirs stay loadable —
    the per-table .pkl files are the state; the manifest is metadata)."""
    try:
        with open(os.path.join(dirname, "manifest.json")) as f:
            m = json.load(f)
        return m if isinstance(m, dict) else None
    except (OSError, ValueError):
        return None


def _validated_state(state, table, name):
    """Preload checkpoints fail LOUDLY instead of silently corrupting
    the table: a RemoteTable.state_dict() ({"servers": [...]}) unwraps
    only in the 1-server case, and the shard geometry must match the
    table this server actually hosts (a full-table checkpoint loaded
    into a multi-server PARTITION would misalign every row)."""
    if isinstance(state, dict) and "servers" in state:
        if len(state["servers"]) != 1:
            raise ValueError(
                f"preload {name!r}: checkpoint was saved from "
                f"{len(state['servers'])} pservers; restore it into the "
                f"same server count (per-server .pkl files)")
        state = state["servers"][0]
    shards = state.get("shards") if isinstance(state, dict) else None
    if shards is None:
        raise ValueError(
            f"preload {name!r}: not a table state_dict (expected a "
            f"'shards' key; got {type(state).__name__})")
    rows = sum(int(s.shape[0]) for s in shards)
    dims = {int(s.shape[1]) for s in shards}
    if rows != table.rows or dims != {table.dim}:
        raise ValueError(
            f"preload {name!r}: checkpoint geometry [{rows}, {dims}] "
            f"does not match this server's table "
            f"[{table.rows}, {table.dim}] — on multi-server deployments "
            f"each server needs ITS OWN partition checkpoint")
    if len(shards) != table.num_shards:
        raise ValueError(
            f"preload {name!r}: checkpoint has {len(shards)} shards, "
            f"table expects {table.num_shards}")
    return state


class _SyncState:
    """Per-table push barrier (sync mode): round r applies once all
    `num_trainers` contributions for r have arrived.

    Completion is tracked per-CONTRIBUTION (a token each waiter removes
    after waking) AND by an applied-round high-water mark used ONLY for
    replay dedup: `last_applied` is consulted when a push arrives with
    the `retry` marker (its first send may have landed before the
    connection died), never for first sends. Within one trainer-group
    GENERATION the mark is exact — sync rounds complete in lockstep, so
    a retried round number is either still pending (join the barrier) or
    <= last_applied (already merged: return without re-applying).

    A restarted trainer group restarts its step counter at 0, which
    would poison the high-water mark and leave half-filled rounds from
    the dead group in `rounds` — so the create_table handshake carries a
    `generation` (launch.py PADDLE_ELASTIC_RESTART) and the server swaps
    in a FRESH _SyncState when it bumps, marking the old one `reset` and
    waking its stale waiters to fail fast instead of timing out.

    `async_seen` / `delta_seen` are the barrier-less analogs: per-trainer
    high-water marks that dedup RETRIED async pushes / geo deltas.
    Downpour semantics make the high-water approximation safe: within one
    client, pushes are issued in step order, and async mode tolerates
    bounded reordering/loss by design."""

    def __init__(self, num_trainers: int):
        self.cond = threading.Condition()
        self.num = int(num_trainers)
        self.rounds: Dict[int, Dict[int, tuple]] = {}
        self.done: set = set()
        self.last_applied = -1
        self.async_seen: Dict[int, int] = {}
        self.delta_seen: Dict[int, int] = {}
        self.reset = False  # generation bumped: stale waiters fail fast


def _payload_nbytes(obj) -> int:
    """Recursive resident-byte estimate for RPC payload shapes (numpy
    arrays dominate; containers add their members). Used by the replog
    ring and table memory accounting — an estimate, not an audit."""
    if obj is None:
        return 0
    nb = getattr(obj, "nbytes", None)
    if nb is not None:
        return int(nb)
    if isinstance(obj, (list, tuple, set)):
        return sum(_payload_nbytes(x) for x in obj)
    if isinstance(obj, dict):
        return sum(_payload_nbytes(k) + _payload_nbytes(v)
                   for k, v in obj.items())
    if isinstance(obj, (bytes, bytearray, str)):
        return len(obj)
    return 8  # ints/floats/bools: pointer-ish


class _ReplicaState:
    """Per-hosted-partition replication state (only exists for tables
    created with a `partition` in their spec, i.e. R>1).

    Roles: None (created, not yet promoted — serves reads, rejects
    writes), "primary" (applies client writes, forwards each applied
    write to `backups` with a monotone per-partition `seq` under `lock`
    so every replica applies the identical prefix), "backup" (applies
    only `replicate` forwards in seq order; serves hedged reads).

    `epoch` is the promotion generation: a failover promotes a backup at
    epoch+1, and any forward carrying an older epoch is rejected — the
    deposed-primary fence. `log` is a bounded ring of recent applied
    writes for seq-tail catch-up (anti-entropy): a respawned replica
    that preloaded a snapshot at seq S only replays (S, seq] when the
    ring still covers it, else takes a full state transfer."""

    def __init__(self):
        self.role: Optional[str] = None
        self.epoch = 0
        self.seq = 0  # last applied replicated write
        self.backups: List[str] = []  # endpoints (primary only)
        self.conns: Dict[str, "_Conn"] = {}
        self.dropped: Dict[str, str] = {}  # endpoint -> reason
        self.log: deque = deque(maxlen=max(1, REPLOG_KEEP))
        self.lock = threading.RLock()
        self.stale = False  # deposed / awaiting resync

    def status(self) -> dict:
        with self.lock:
            return {
                "role": self.role, "epoch": self.epoch, "seq": self.seq,
                "stale": self.stale,
                "backups": list(self.backups),
                "dropped": dict(self.dropped),
            }

    def log_bytes(self) -> int:
        """Estimated resident bytes of the replication log ring — part
        of the table's memory accounting (ISSUE 11): each entry holds
        the applied write's ids + payload arrays until the ring evicts
        it, which on a hot table is REPLOG_KEEP rounds of traffic."""
        with self.lock:
            return sum(_payload_nbytes(e) for e in self.log)


class PSServer:
    """Event loop owning the host tables (listen_and_serv analog).

    preload_dir (fleet.init_server(model_dir)): when a table is first
    created, `<preload_dir>/<name>.pkl` — a `table.state_dict()` pickle
    saved by a previous run — is loaded into it, the reference's
    init_server checkpoint-restore contract. Snapshots
    (snapshot_dir/snapshot_secs) write the SAME format, so a supervised
    restart preloads the latest snapshot through this path."""

    def __init__(self, preload_dir: Optional[str] = None,
                 snapshot_dir: Optional[str] = None,
                 snapshot_secs: float = 0.0,
                 snapshot_mode: Optional[str] = None):
        self.tables: Dict[str, ShardedHostTable] = {}
        self.specs: Dict[str, dict] = {}
        self.sync: Dict[str, _SyncState] = {}
        self.gens: Dict[str, int] = {}
        self.replicas: Dict[str, _ReplicaState] = {}
        self.lock = threading.Lock()
        self.shutdown_event = threading.Event()
        self.preload_dir = preload_dir
        self.snapshot_dir = snapshot_dir or None
        self.snapshot_secs = float(snapshot_secs or 0.0)
        self.snapshot_mode = (snapshot_mode or os.environ.get(
            "PADDLE_PS_SNAPSHOT_MODE", "full") or "full").lower()
        if self.snapshot_mode not in ("full", "incremental"):
            raise ValueError(
                f"PADDLE_PS_SNAPSHOT_MODE must be 'full' or "
                f"'incremental', got {self.snapshot_mode!r}")
        # incremental mode: per-table-key chain bookkeeping
        # {key: {"serial": int, "base": fname, "base_sha256": hex,
        #        "deltas": [{"file","sha256","rows"}]}}
        self._snap_chain: Dict[str, dict] = {}
        self._snap_thread: Optional[threading.Thread] = None
        # cross-job adoption: a stable snapshot dir carries a manifest
        # (snapshot epoch + trainer-group generation); a new job's
        # server picks up the epoch counter where the old job left it,
        # and serve() reports what was adopted
        self._snapshot_epoch = 0
        self.adopted_manifest: Optional[dict] = None
        if preload_dir:
            m = read_snapshot_manifest(preload_dir)
            if m is not None:
                self.adopted_manifest = m
                self._snapshot_epoch = int(m.get("snapshot_epoch", 0))

    # -- verbs -----------------------------------------------------------

    def create_table(self, spec: dict):
        """Idempotent across trainers: the first create wins; later
        creates with an IDENTICAL spec are no-ops, mismatches error.
        `generation` (not part of the identity spec) is the trainer
        group's restart attempt: a bump resets the sync barrier.
        Replicated partitions (spec carries `partition` + `replicas`)
        key the table as name@p<idx> and get a _ReplicaState; role
        assignment happens through the separate `promote` verb."""
        spec = dict(spec)
        gen = int(spec.pop("generation", 0))
        name = spec["name"]
        key = _table_key(name, spec.get("partition"))

        def identity(s: dict) -> dict:
            # sync_trainers (and the replica endpoint list) are
            # MEMBERSHIP state, not table identity: an elastic resize
            # re-creates the table at a new world size under a bumped
            # generation, and the rows must carry over
            return {k: v for k, v in s.items()
                    if k not in ("sync_trainers", "replicas")}

        with self.lock:
            if key in self.tables:
                if gen > self.gens.get(key, 0):
                    if identity(spec) != identity(self.specs[key]):
                        raise ValueError(
                            f"table {key!r} already exists with a "
                            f"different spec: {self.specs[key]} vs {spec}")
                    # elastic restart: the new group must never share
                    # barrier state (half-filled rounds, applied marks,
                    # step high-water) with the dead one; its
                    # sync_trainers is the NEW world size, so the merge
                    # denominator (dp-mean) tracks the resize
                    old = self.sync[key]
                    self.sync[key] = _SyncState(
                        int(spec.get("sync_trainers", old.num)))
                    self.specs[key] = dict(spec)
                    self.gens[key] = gen
                    with old.cond:
                        old.reset = True
                        old.cond.notify_all()
                elif spec != self.specs[key]:
                    raise ValueError(
                        f"table {key!r} already exists with a different "
                        f"spec: {self.specs[key]} vs {spec} (a changed "
                        f"sync_trainers needs a bumped generation — the "
                        f"elastic-resize handshake)")
                return {"rows": self.tables[key].rows,
                        "dim": self.tables[key].dim}
            kw = {k: v for k, v in spec.items()
                  if k not in ("name", "shape", "sync_trainers",
                               "partition", "replicas")}
            t = ShardedHostTable(name, spec["shape"], **kw)
            replica_meta = None
            if self.preload_dir:
                replica_meta = self._preload_table(t, key)
            self.tables[key] = t
            self.specs[key] = dict(spec)
            self.sync[key] = _SyncState(int(spec.get("sync_trainers", 0)))
            self.gens[key] = gen
            if "partition" in spec:
                rs = _ReplicaState()
                if replica_meta:
                    rs.seq = int(replica_meta.get("seq", 0))
                    rs.epoch = int(replica_meta.get("epoch", 0))
                self.replicas[key] = rs
            return {"rows": t.rows, "dim": t.dim}

    def _preload_table(self, t: ShardedHostTable, key: str):
        """Restore `key` from preload_dir — an incremental base+delta
        chain when the dir's manifest describes one, else the legacy
        full `<key>.pkl`. Returns the replica_meta dict ({seq, epoch})
        recorded in the newest restored file, or None."""
        m = read_snapshot_manifest(self.preload_dir)
        if m and m.get("mode") == "incremental" and \
                key in m.get("chains", {}):
            return self._restore_chain(t, key, m["chains"][key])
        path = os.path.join(self.preload_dir, f"{key}.pkl")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            state = _validated_state(pickle.load(f), t, key)
        meta = state.pop("replica_meta", None)
        t.load_state_dict(state)
        return meta

    def _restore_chain(self, t: ShardedHostTable, key: str, chain: dict):
        """base + ordered deltas, each sha256-verified; the chain stops
        LOUDLY at the first corrupt file (everything before it is intact
        thanks to atomic per-file writes) instead of silently skipping.
        The in-memory chain bookkeeping is NOT seeded, so the next
        snapshot writes a fresh base — compaction-on-load."""
        def read_verified(fname, want_sha):
            path = os.path.join(self.preload_dir, fname)
            with open(path, "rb") as f:
                blob = f.read()
            if want_sha and hashlib.sha256(blob).hexdigest() != want_sha:
                raise ValueError(f"checksum mismatch in {fname}")
            return pickle.loads(blob)

        state = _validated_state(
            read_verified(chain["base"], chain.get("base_sha256")), t, key)
        meta = state.pop("replica_meta", None)
        t.load_state_dict(state)
        for ent in chain.get("deltas", []):
            try:
                delta = read_verified(ent["file"], ent.get("sha256"))
            except (OSError, ValueError) as e:
                print(f"[ps_server] delta chain for {key!r} broken at "
                      f"{ent.get('file')}: {e}; restored up to the last "
                      f"intact delta", file=sys.stderr, flush=True)
                break
            t.apply_dirty_delta(delta)
            meta = delta.get("replica_meta", meta)
        return meta

    def _table(self, name: str, partition=None) -> ShardedHostTable:
        key = _table_key(name, partition)
        t = self.tables.get(key)
        if t is None:
            raise KeyError(f"no table {key!r} on this pserver")
        return t

    # -- replication core -------------------------------------------------

    def _check_writable(self, key: str) -> Optional[_ReplicaState]:
        """Client writes only land on the partition's current primary;
        a backup or a deposed/stale replica bounces them with a typed
        error the client resolves by re-routing."""
        rs = self.replicas.get(key)
        if rs is None:
            return None
        with rs.lock:
            if rs.stale:
                raise StalePrimaryError(
                    f"replica {key!r} was deposed (epoch {rs.epoch}) and "
                    f"awaits resync")
            if rs.role != "primary":
                raise NotPrimaryError(
                    f"replica {key!r} is {rs.role or 'unpromoted'} at "
                    f"epoch {rs.epoch}; writes go to the primary")
        return rs

    def _check_readable(self, key: str) -> None:
        """Reads are served by primaries AND backups (hedged pulls) —
        but never by a deposed replica whose copy may have diverged,
        and never by an UNPROMOTED one: a respawned server re-created
        from its (possibly stale) snapshot has role None until it
        resyncs, and serving a gather from that copy would leak stale
        rows into an otherwise bit-exact training trace."""
        rs = self.replicas.get(key)
        if rs is None:
            return
        if rs.stale:
            raise StalePrimaryError(
                f"replica {key!r} was deposed and awaits resync")
        if rs.role is None:
            raise NotPrimaryError(
                f"replica {key!r} is unpromoted (respawned, not yet "
                f"resynced); reads go to the primary or a backup")

    def _apply_replicated(self, key: str, apply_fn, op: str, ids, payload,
                          dedup: dict):
        """Apply a write and, when `key` is a replicated primary,
        forward the APPLIED form to every enrolled backup under the
        partition lock — the lock serializes (apply, seq++, forward) so
        all replicas see the identical apply prefix. Unreplicated
        tables take the bare-apply fast path (R=1 untouched)."""
        rs = self.replicas.get(key)
        if rs is None:
            apply_fn()
            return
        with rs.lock:
            apply_fn()
            if rs.role != "primary":
                return
            # seq advances and the write ring records EVERY primary
            # apply — even with zero live backups — so a replica that
            # rejoins later can catch up from the exact point it missed
            rs.seq += 1
            entry = (rs.seq, op, ids, payload, dedup)
            rs.log.append(entry)
            if rs.backups:
                self._forward(key, rs, entry)

    def _forward(self, key: str, rs: _ReplicaState, entry) -> None:
        """Synchronous fan-out of one applied write to the live backups
        (caller holds rs.lock). A backup that cannot ack within the
        forward deadline is DROPPED from the set (it will resync when it
        rejoins) so a dead replica costs bounded latency, not the job; a
        stale-epoch rejection means WE were deposed — fail the client
        write loudly so it re-routes to the real primary."""
        seq, op, ids, payload, dedup = entry
        for ep in list(rs.backups):
            if ep in rs.dropped:
                continue
            conn = rs.conns.get(ep)
            if conn is None:
                conn = rs.conns[ep] = _Conn(
                    ep, deadline=FORWARD_DEADLINE, max_attempts=3,
                    io_timeout=FORWARD_DEADLINE + 5.0)
            try:
                conn.call("replicate", key=key, epoch=rs.epoch, seq=seq,
                          op=op, ids=ids, payload=payload, dedup=dedup)
                _REG.counter("ps_server_replicate_forwarded_total",
                             help="applied writes forwarded to backups",
                             verb=op).inc()
            except ConnectionError as e:
                rs.dropped[ep] = f"unreachable: {e}"
                _REG.counter("ps_server_replica_dropped_total",
                             reason="unreachable").inc()
                print(f"[ps_server] dropping backup {ep} for {key!r}: "
                      f"unreachable ({type(e).__name__})",
                      file=sys.stderr, flush=True)
            except RuntimeError as e:
                msg = str(e)
                if "StaleEpoch" in msg:
                    # a newer primary exists: we are deposed
                    rs.stale = True
                    _REG.counter("ps_server_deposed_total").inc()
                    raise StalePrimaryError(
                        f"primary for {key!r} at epoch {rs.epoch} was "
                        f"deposed: {msg}")
                rs.dropped[ep] = f"lagging: {msg}"
                _REG.counter("ps_server_replica_dropped_total",
                             reason="lagging").inc()
                print(f"[ps_server] dropping backup {ep} for {key!r}: "
                      f"{msg}", file=sys.stderr, flush=True)

    def replicate(self, key, epoch, seq, op, ids, payload, dedup=None):
        """Backup-side apply of one forwarded write. Epoch fences a
        deposed primary (StaleEpoch → it stops serving); seq must be
        exactly last+1 — a duplicate (primary died between forward and
        client-reply; the round re-merged elsewhere) is acked without
        re-applying, a gap means we missed forwards and must resync."""
        table = self._table_by_key(key)
        rs = self.replicas.get(key)
        if rs is None:
            raise KeyError(f"no replica state for {key!r}")
        inj = faults.injector()
        if inj is not None and inj.blocks_replication():
            raise faults.FaultError(
                f"fault injection: partitioned — replicate {key!r} "
                f"seq {seq} rejected")
        with rs.lock:
            if epoch < rs.epoch:
                _REG.counter("ps_server_stale_epoch_rejected_total").inc()
                raise RuntimeError(
                    f"StaleEpoch: replicate for {key!r} carries epoch "
                    f"{epoch} < current {rs.epoch} (deposed primary)")
            if rs.stale:
                # a deposed replica's content and seq are untrustworthy;
                # acking against the inflated seq would silently skip
                # real entries — refuse until resync repairs it (the
                # forwarding primary drops us; rejoin drives resync)
                raise RuntimeError(
                    f"ReplicaGap: {key!r} was deposed and awaits "
                    f"resync; forward {seq} refused")
            if epoch > rs.epoch:
                rs.epoch = int(epoch)
                if rs.role != "backup":
                    rs.role = "backup"
                    rs.backups, rs.dropped = [], {}
            elif rs.role is None:
                rs.role = "backup"
            if seq <= rs.seq:
                _REG.counter("ps_server_replicate_dedup_total",
                             verb=op).inc()
                return {"seq": rs.seq}
            if seq != rs.seq + 1:
                raise RuntimeError(
                    f"ReplicaGap: {key!r} has seq {rs.seq}, got forward "
                    f"{seq}; resync required")
            self._apply_forward(key, table, op, ids, payload)
            rs.seq = int(seq)
            rs.log.append((rs.seq, op, ids, payload, dedup))
            self._absorb_dedup(key, dedup)
            _REG.counter("ps_server_replicate_applied_total",
                         verb=op).inc()
            return {"seq": rs.seq}

    def _apply_forward(self, key, table, op, ids, payload):
        if op == "push_gradients":
            table.push_gradients(ids, payload)
        elif op == "push_delta":
            table.push_delta(ids, payload)
        elif op == "load_state":
            table.load_state_dict(payload)
        else:
            raise ValueError(f"unknown replicated op {op!r}")

    def _absorb_dedup(self, key: str, dedup) -> None:
        """Mirror the primary's replay-dedup high-water marks onto this
        backup, so a promotion preserves exactly-once semantics for
        client retries that straddle the failover."""
        if not dedup:
            return
        st = self.sync.get(key)
        if st is None:
            return
        with st.cond:
            if "sync_step" in dedup:
                st.last_applied = max(st.last_applied,
                                      int(dedup["sync_step"]))
            if "async" in dedup:
                tid, step = dedup["async"]
                st.async_seen[tid] = max(st.async_seen.get(tid, -1),
                                         int(step))
            if "delta" in dedup:
                tid, seq = dedup["delta"]
                st.delta_seen[tid] = max(st.delta_seen.get(tid, -1),
                                         int(seq))

    def _dedup_snapshot(self, key: str) -> dict:
        st = self.sync.get(key)
        if st is None:
            return {}
        with st.cond:
            return {"last_applied": st.last_applied,
                    "async_seen": dict(st.async_seen),
                    "delta_seen": dict(st.delta_seen)}

    def _install_dedup(self, key: str, dd: dict) -> None:
        st = self.sync.get(key)
        if st is None or not dd:
            return
        with st.cond:
            st.last_applied = max(st.last_applied,
                                  int(dd.get("last_applied", -1)))
            for tid, v in (dd.get("async_seen") or {}).items():
                st.async_seen[tid] = max(st.async_seen.get(tid, -1), v)
            for tid, v in (dd.get("delta_seen") or {}).items():
                st.delta_seen[tid] = max(st.delta_seen.get(tid, -1), v)

    def _table_by_key(self, key: str) -> ShardedHostTable:
        t = self.tables.get(key)
        if t is None:
            raise KeyError(f"no table {key!r} on this pserver")
        return t

    def promote(self, key, epoch, backups):
        """Make this replica the partition's primary at `epoch`.
        Idempotent per epoch; older epochs are rejected (a client racing
        a finished failover just refreshes its routing)."""
        rs = self.replicas.get(key)
        if rs is None:
            raise KeyError(f"no replica state for {key!r}")
        with rs.lock:
            if epoch < rs.epoch or (
                    epoch == rs.epoch and rs.role == "backup"
                    and epoch > 0):
                raise RuntimeError(
                    f"StalePromote: {key!r} is {rs.role} at epoch "
                    f"{rs.epoch}; promote({epoch}) is stale")
            if epoch == rs.epoch and rs.role == "primary":
                return {"epoch": rs.epoch, "seq": rs.seq}  # idempotent
            rs.role = "primary"
            rs.epoch = int(epoch)
            rs.backups = [str(e) for e in (backups or [])]
            rs.dropped = {}
            rs.stale = False
            _REG.counter("ps_server_promotions_total").inc()
            print(f"[ps_server] promoted to PRIMARY for {key!r} "
                  f"(epoch {rs.epoch}, seq {rs.seq}, backups "
                  f"{rs.backups})", file=sys.stderr, flush=True)
            return {"epoch": rs.epoch, "seq": rs.seq}

    def fetch_replica_state(self, key, backup=None, have_seq=0):
        """Primary-side anti-entropy source: under the partition lock
        (no forward can interleave), hand back either the seq TAIL the
        requester is missing (ring still covers it) or a full state
        transfer, and enroll the requester in the forward set from this
        exact point — nothing applied after the snapshot can be missed."""
        table = self._table_by_key(key)
        rs = self.replicas.get(key)
        if rs is None:
            raise KeyError(f"no replica state for {key!r}")
        with rs.lock:
            if rs.role != "primary":
                raise NotPrimaryError(
                    f"fetch_replica_state: {key!r} is {rs.role}, not "
                    f"primary")
            have_seq = int(have_seq)
            # have_seq < 0 is an explicit full-transfer demand: a
            # deposed replica's local seq counts writes the cluster
            # never accepted, so "covered" computed from it would hand
            # back an empty tail and leave its divergence in place
            covered = have_seq >= 0 and (
                (have_seq >= rs.seq)
                or (rs.log and rs.log[0][0] <= have_seq + 1))
            if covered:
                out = {"tail": [e for e in rs.log if e[0] > have_seq]}
                _REG.counter("ps_server_resyncs_total", mode="tail").inc()
            else:
                out = {"state": table.state_dict()}
                _REG.counter("ps_server_resyncs_total", mode="full").inc()
            out.update(seq=rs.seq, epoch=rs.epoch,
                       dedup=self._dedup_snapshot(key))
            if backup:
                backup = str(backup)
                rs.dropped.pop(backup, None)
                if backup not in rs.backups:
                    rs.backups.append(backup)
            return out

    def resync(self, key, primary, self_endpoint=None):
        """Backup-side anti-entropy driver (runs on the REJOINING
        replica): pull the missing state from the current primary —
        which atomically enrolls us in its forward set — and install
        it. Called by the client's rejoin thread after a supervised
        respawn, or for a deposed replica."""
        table = self._table_by_key(key)
        rs = self.replicas.get(key)
        if rs is None:
            raise KeyError(f"no replica state for {key!r}")
        with rs.lock:
            # short io_timeout: bounds the (rare) resync-vs-forward lock
            # cycle between two replicas to seconds, not the barrier
            # envelope — the loser retries and converges
            conn = _Conn(primary, deadline=max(FORWARD_DEADLINE, 5.0),
                         io_timeout=max(FORWARD_DEADLINE, 5.0) + 5.0)
            try:
                # a deposed replica applied writes the cluster never
                # accepted: its seq is inflated and its same-numbered
                # log entries may DIFFER from the new primary's, so the
                # seq must not seed anti-entropy — demand a full state
                # transfer (have_seq=-1) instead of a tail
                have = -1 if rs.stale else rs.seq
                out = conn.call("fetch_replica_state", key=key,
                                backup=self_endpoint, have_seq=have)
            finally:
                conn.close()
            if "state" in out:
                table.load_state_dict(out["state"])
                # entries from the deposed incarnation must not survive
                # into a future promotion's tail service
                rs.log.clear()
                mode = "full"
            else:
                for seq, op, ids, payload, dedup in out["tail"]:
                    self._apply_forward(key, table, op, ids, payload)
                    self._absorb_dedup(key, dedup)
                    # keep the ring contiguous through rs.seq, so a
                    # later promotion serves gap-free tails
                    rs.log.append((seq, op, ids, payload, dedup))
                mode = "tail"
            rs.seq = int(out["seq"])
            rs.epoch = int(out["epoch"])
            rs.role = "backup"
            rs.stale = False
            self._install_dedup(key, out.get("dedup") or {})
        print(f"[ps_server] resynced {key!r} from {primary} "
              f"({mode}, seq {rs.seq}, epoch {rs.epoch}); rejoined as "
              f"backup", file=sys.stderr, flush=True)
        return {"seq": rs.seq, "epoch": rs.epoch, "mode": mode}

    def adopt_role(self, key, epoch, role):
        """Explicit role assignment for a fresh backup (the client sets
        it right after promoting the partition's first primary, so
        status pages and promotion ranking see a real backup instead of
        an unpromoted husk). Only ever an upgrade: an existing role or
        a newer epoch is left alone."""
        rs = self.replicas.get(key)
        if rs is None:
            raise KeyError(f"no replica state for {key!r}")
        with rs.lock:
            if rs.role is None and epoch >= rs.epoch:
                rs.role = str(role)
                rs.epoch = int(epoch)
            return {"role": rs.role, "epoch": rs.epoch}

    def replica_status(self, key):
        rs = self.replicas.get(key)
        if rs is None:
            # table may exist unreplicated, or not at all
            self._table_by_key(key)
            return {"role": None, "epoch": 0, "seq": 0, "stale": False}
        return rs.status()

    def replica_summary(self) -> Dict[str, dict]:
        """Compact {partition_key: {role, epoch, seq, stale}} across
        every hosted replicated partition — the payload this server's
        coordinator lease renewals carry, so the control plane can
        elect a caught-up backup when a primary's lease expires."""
        out = {}
        for key, rs in list(self.replicas.items()):
            with rs.lock:
                out[key] = {"role": rs.role, "epoch": rs.epoch,
                            "seq": rs.seq, "stale": rs.stale}
        return out

    # -- data verbs -------------------------------------------------------

    def gather(self, name, ids, partition=None):
        key = _table_key(name, partition)
        self._check_readable(key)
        return self._table(name, partition).gather(ids)

    def push_gradients(self, name, ids, grads, trainer_id=0, step=0,
                       retry=False, partition=None):
        key = _table_key(name, partition)
        self._check_writable(key)
        table = self._table(name, partition)
        st = self.sync[key]
        if st.num <= 1:
            # async / single trainer: apply on arrival (Downpour). A
            # RETRIED push whose first send already landed is skipped.
            with st.cond:
                if retry and st.async_seen.get(trainer_id, -1) >= step:
                    _REG.counter("ps_server_replay_dedup_total",
                                 help="retried pushes whose first send "
                                      "already landed (applied once)",
                                 verb="push_gradients").inc()
                    return 0
                st.async_seen[trainer_id] = max(
                    st.async_seen.get(trainer_id, -1), step)
            t0 = time.perf_counter()
            self._apply_replicated(
                key, lambda: table.push_gradients(ids, grads),
                "push_gradients", ids, grads,
                {"async": (trainer_id, step)})
            _emit_ps_step(name, "async", step, len(np.asarray(ids)),
                          (time.perf_counter() - t0) * 1e3)
            return 0
        token = object()
        merged = None  # (ids, grads, peer tokens) when THIS call merges
        with st.cond:
            if retry and step <= st.last_applied:
                # replay of a round that merged before the reply was
                # lost: the update already landed exactly once
                _REG.counter("ps_server_replay_dedup_total",
                             verb="push_gradients").inc()
                return 0
            buf = st.rounds.setdefault(step, {})
            # overwrite-not-raise: a pre-existing same-trainer entry is a
            # dropped connection's orphan (its server thread still waits
            # on a token that will never complete and times out) — the
            # retry's token supersedes it
            buf[trainer_id] = (np.asarray(ids), np.asarray(grads), token)
            if len(buf) == st.num:
                # trainer-id order, not arrival order: the merged batch
                # is then exactly the single-process batch layout, so
                # duplicate-id float accumulation is order-identical
                ids_m = np.concatenate([buf[t][0] for t in sorted(buf)])
                g_m = np.concatenate([buf[t][1] for t in sorted(buf)])
                # claim the round (dedup high-water + buffer removal)
                # BEFORE applying below, so a racing replay can never
                # trigger a second merge; peers are released only AFTER
                # the apply lands
                peers = [v[2] for v in buf.values() if v[2] is not token]
                st.last_applied = max(st.last_applied, step)
                del st.rounds[step]
                merged = (ids_m, g_m / st.num, peers)
                # this arrival RELEASED the barrier: the causal evidence
                # tracetop's critical path cites for the round
                _tracing.annotate(released_round=step)
            else:
                with _tracing.span("barrier_wait",
                                   attrs={"table": name, "round": step,
                                          "trainer": trainer_id}):
                    woke = st.cond.wait_for(
                        lambda: token in st.done or st.reset,
                        timeout=SYNC_TIMEOUT)
                if woke:
                    if token in st.done:
                        st.done.discard(token)  # each waiter prunes its own
                    else:
                        # generation bump while we waited: group is dead
                        raise RuntimeError(
                            f"sync-PS round abandoned: the trainer group "
                            f"restarted while table {name!r} round {step} "
                            f"was waiting for peers")
                else:
                    # drop our contribution so the round can't half-fire
                    # if this trainer is restarted and retries
                    if step in st.rounds:
                        st.rounds[step].pop(trainer_id, None)
                    raise RuntimeError(
                        f"sync-PS barrier timed out after {SYNC_TIMEOUT}s: "
                        f"only {len(st.rounds.get(step, {}))}/{st.num} "
                        f"trainers pushed table {name!r} round {step} — a "
                        f"peer trainer likely died")
        if merged is not None:
            ids_m, g_scaled, peers = merged
            t0 = time.perf_counter()
            # applied OUTSIDE st.cond: _apply_replicated takes rs.lock,
            # and the replication paths (replicate, resync,
            # fetch_replica_state) take rs.lock THEN st.cond — holding
            # st.cond across the apply inverts that order and can
            # deadlock a primary that is merging a round while a peer
            # forwards to it during a role-transition race. On apply
            # failure (e.g. this primary was deposed mid-forward) the
            # peers are NOT released: they time out, surface the error,
            # and the clients re-drive the round at the new primary.
            with _tracing.span("apply", attrs={"table": name,
                                               "round": step,
                                               "rows": int(len(ids_m))}):
                self._apply_replicated(
                    key, lambda: table.push_gradients(ids_m, g_scaled),
                    "push_gradients", ids_m, g_scaled, {"sync_step": step})
            apply_ms = (time.perf_counter() - t0) * 1e3
            with st.cond:
                st.done.update(peers)
                st.cond.notify_all()
            # emitted outside the barrier lock: sink I/O must never
            # extend the round's critical section
            _emit_ps_step(name, "sync", step, len(ids_m), apply_ms)
        return 0

    def push_delta(self, name, ids, deltas, trainer_id=0, seq=-1,
                   retry=False, partition=None):
        key = _table_key(name, partition)
        self._check_writable(key)
        table = self._table(name, partition)
        if seq >= 0:
            st = self.sync[key]
            with st.cond:
                if retry and st.delta_seen.get(trainer_id, -1) >= seq:
                    _REG.counter("ps_server_replay_dedup_total",
                                 verb="push_delta").inc()
                    return 0  # replayed delta already accumulated
                st.delta_seen[trainer_id] = max(
                    st.delta_seen.get(trainer_id, -1), seq)
        t0 = time.perf_counter()
        self._apply_replicated(
            key, lambda: table.push_delta(ids, deltas),
            "push_delta", ids, deltas, {"delta": (trainer_id, seq)})
        _emit_ps_step(name, "delta", seq, len(np.asarray(ids)),
                      (time.perf_counter() - t0) * 1e3)
        return 0

    def handle(self, method: str, kwargs: dict):
        inj = faults.injector()
        if inj is not None:
            inj.on_server_call(method)  # may os._exit (kill rule)
        if kwargs.get("retry"):
            # the client marked this a replay attempt (its first send may
            # have landed); dedup hits are counted separately above
            _REG.counter("ps_server_retry_received_total",
                         help="RPCs arriving with the retry marker",
                         verb=method).inc()
        if method == "ping":
            return "pong"
        if method == "create_table":
            return self.create_table(kwargs["spec"])
        part = kwargs.get("partition")
        if method == "gather":
            return self.gather(kwargs["name"], kwargs["ids"], part)
        if method == "push_gradients":
            return self.push_gradients(
                kwargs["name"], kwargs["ids"], kwargs["grads"],
                kwargs.get("trainer_id", 0), kwargs.get("step", 0),
                kwargs.get("retry", False), part)
        if method == "push_delta":
            return self.push_delta(
                kwargs["name"], kwargs["ids"], kwargs["deltas"],
                kwargs.get("trainer_id", 0), kwargs.get("seq", -1),
                kwargs.get("retry", False), part)
        if method == "replicate":
            return self.replicate(
                kwargs["key"], kwargs["epoch"], kwargs["seq"],
                kwargs["op"], kwargs["ids"], kwargs["payload"],
                kwargs.get("dedup"))
        if method == "promote":
            return self.promote(
                _table_key(kwargs["name"], part),
                kwargs["epoch"], kwargs.get("backups"))
        if method == "fetch_replica_state":
            return self.fetch_replica_state(
                kwargs["key"], kwargs.get("backup"),
                kwargs.get("have_seq", 0))
        if method == "resync":
            return self.resync(
                _table_key(kwargs["name"], part), kwargs["primary"],
                kwargs.get("self_endpoint"))
        if method == "adopt_role":
            return self.adopt_role(_table_key(kwargs["name"], part),
                                   kwargs["epoch"], kwargs["role"])
        if method == "replica_status":
            return self.replica_status(_table_key(kwargs["name"], part))
        if method == "to_dense":
            self._check_readable(_table_key(kwargs["name"], part))
            return self._table(kwargs["name"], part).to_dense()
        if method == "nbytes":
            return self._table(kwargs["name"], part).nbytes()
        if method == "stats":
            # idempotent observability verb: per-table traffic counters
            # (when a name is given) + this server process's telemetry
            # registry slice — per-verb latency histogram summaries,
            # retry/replay-dedup counters, bytes in/out; replicated
            # partitions add their role/epoch/seq/backup-lag state.
            # `memory` (ISSUE 11) is this process's per-hosted-table
            # resident-byte accounting — rows x row width + optimizer
            # accumulators + the replication log ring
            out = {"server": server_telemetry(),
                   "memory": self.memory_stats()}
            name = kwargs.get("name")
            if name:
                key = _table_key(name, part)
                t = self._table(name, part)
                out["push_calls"] = t.push_calls
                out["pushed_bytes"] = t.pushed_bytes
                rs = self.replicas.get(key)
                if rs is not None:
                    out["replica"] = rs.status()
            return out
        if method == "state_dict":
            self._check_readable(_table_key(kwargs["name"], part))
            return self._table(kwargs["name"], part).state_dict()
        if method == "load_state_dict":
            key = _table_key(kwargs["name"], part)
            rs = self._check_writable(key)
            table = self._table(kwargs["name"], part)
            if rs is not None:
                self._apply_replicated(
                    key, lambda: table.load_state_dict(kwargs["state"]),
                    "load_state", None, kwargs["state"], {})
            else:
                table.load_state_dict(kwargs["state"])
            return 0
        if method == "snapshot":
            return self.snapshot()
        if method == "drop_table":
            with self.lock:
                name = kwargs["name"]
                for key in [k for k in self.tables
                            if k == name or k.startswith(name + "@p")]:
                    self.tables.pop(key, None)
                    self.specs.pop(key, None)
                    self.sync.pop(key, None)
                    self.gens.pop(key, None)
                    self.replicas.pop(key, None)
            return 0
        if method == "shutdown":
            self.shutdown_event.set()
            return 0
        raise ValueError(f"unknown PS method {method!r}")

    def memory_stats(self) -> dict:
        """Per-hosted-table-key resident bytes (ISSUE 11 satellite):
        value shards + optimizer accumulators + dirty-set overhead, and
        for replicated partitions the replication log ring — the
        pserver-process capacity-planning row the `stats` verb carries
        and fleet.ps_stats() / debugz /statusz surface."""
        with self.lock:
            items = list(self.tables.items())
            reps = dict(self.replicas)
        out = {}
        total = 0
        for key, t in items:
            row = t.memory_stats()
            rs = reps.get(key)
            if rs is not None:
                row["replog_bytes"] = rs.log_bytes()
                row["replog_entries"] = len(rs.log)
                row["resident_bytes"] += row["replog_bytes"]
            total += row["resident_bytes"]
            out[key] = row
        out["total_resident_bytes"] = total
        return out

    # -- snapshots --------------------------------------------------------

    def snapshot(self) -> int:
        """Atomically checkpoint every hosted table (tmp + os.replace: a
        crash mid-write can never leave a torn file, so the newest
        snapshot on disk is always loadable). Same format as
        preload_dir, so a supervised restart restores it through the
        existing create_table path. A manifest.json (snapshot epoch,
        trainer-group generation, table geometries) is committed LAST,
        so a stable cross-job snapshot dir is self-describing.

        Two modes (PADDLE_PS_SNAPSHOT_MODE): "full" (default) writes
        `<key>.pkl` per table exactly as before — O(table bytes) per
        tick; "incremental" writes a periodic full BASE plus sha256-
        checksummed dirty-row DELTA files chained by the manifest —
        O(touched rows) per tick, which is what makes sub-second
        cadences viable on multi-GB tables. The chain compacts into a
        fresh base every PADDLE_PS_SNAPSHOT_COMPACT_EVERY deltas and on
        load. Returns the number of files written."""
        if not self.snapshot_dir:
            return 0
        os.makedirs(self.snapshot_dir, exist_ok=True)
        if self.snapshot_mode == "incremental":
            return self._snapshot_incremental()
        with self.lock:
            items = list(self.tables.items())
            gens = dict(self.gens)
        n = 0
        for key, t in items:
            rs = self.replicas.get(key)
            if rs is None:
                state = t.state_dict()
            else:
                # one critical section: replicated writes apply under
                # rs.lock (_apply_replicated / replicate), so capturing
                # state AND seq inside it yields a consistent cut — a
                # seq ahead of the state would make a restore+resync
                # skip replaying writes the snapshot doesn't contain
                with rs.lock:
                    state = t.state_dict()
                    state["replica_meta"] = {"seq": rs.seq,
                                             "epoch": rs.epoch}
            _atomic_write(os.path.join(self.snapshot_dir, f"{key}.pkl"),
                          pickle.dumps(state,
                                       protocol=pickle.HIGHEST_PROTOCOL))
            n += 1
        if n:
            self._snapshot_epoch += 1
            manifest = {
                "format": 1,
                "snapshot_epoch": self._snapshot_epoch,
                "generation": max(gens.values(), default=0),
                "unix_time": time.time(),
                "tables": {
                    key: {"rows": t.rows, "dim": t.dim}
                    for key, t in items
                },
            }
            _atomic_write(os.path.join(self.snapshot_dir, "manifest.json"),
                          json.dumps(manifest, indent=1).encode())
        return n

    def _snapshot_incremental(self) -> int:
        """Base + dirty-row delta chain. Per table: a fresh BASE when
        none exists or the chain hit the compaction bound, else one
        DELTA holding only the rows touched since the last tick (none
        touched = nothing written). The manifest commit (atomic, last)
        is the consistency point; files it no longer references are
        removed AFTER it lands."""
        with self.lock:
            items = list(self.tables.items())
            gens = dict(self.gens)
        wrote = 0
        doomed: List[str] = []  # superseded chain files, removed last
        for key, t in items:
            rs = self.replicas.get(key)

            def cut(capture, _rs=rs):
                """Capture table state and replica seq in ONE rs.lock
                critical section (writes apply under rs.lock): seq ahead
                of the state loses resync-tail updates, state ahead of
                seq re-applies non-idempotent push_gradients."""
                if _rs is None:
                    return capture(), None
                with _rs.lock:
                    return capture(), {"seq": _rs.seq,
                                       "epoch": _rs.epoch}

            ent = self._snap_chain.get(key)
            if ent is None or len(ent["deltas"]) >= max(
                    1, SNAPSHOT_COMPACT_EVERY):
                # compaction / first base: everything dirty is folded in
                state, meta = cut(
                    lambda: (t.drain_dirty(), t.state_dict())[1])
                if meta:
                    state["replica_meta"] = meta
                blob = pickle.dumps(state,
                                    protocol=pickle.HIGHEST_PROTOCOL)
                serial = (ent["serial"] + 1) if ent else 0
                fname = f"{key}.base.{serial:04d}.pkl"
                _atomic_write(os.path.join(self.snapshot_dir, fname), blob)
                if ent:
                    doomed.append(ent["base"])
                    doomed.extend(d["file"] for d in ent["deltas"])
                self._snap_chain[key] = {
                    "serial": serial, "base": fname,
                    "base_sha256": hashlib.sha256(blob).hexdigest(),
                    "deltas": [],
                }
                _REG.counter("ps_server_snapshot_bytes_total",
                             kind="base").inc(len(blob))
                wrote += 1
            else:
                delta, meta = cut(t.drain_dirty)
                if delta["rows"] == 0:
                    continue  # bytes per tick scale with touched rows
                if meta:
                    delta["replica_meta"] = meta
                blob = pickle.dumps(delta,
                                    protocol=pickle.HIGHEST_PROTOCOL)
                fname = (f"{key}.delta.{ent['serial']:04d}."
                         f"{len(ent['deltas']):05d}.pkl")
                _atomic_write(os.path.join(self.snapshot_dir, fname), blob)
                ent["deltas"].append({
                    "file": fname,
                    "sha256": hashlib.sha256(blob).hexdigest(),
                    "rows": delta["rows"],
                })
                _REG.counter("ps_server_snapshot_bytes_total",
                             kind="delta").inc(len(blob))
                _REG.counter("ps_server_snapshot_rows_total").inc(
                    delta["rows"])
                wrote += 1
        if wrote:
            self._snapshot_epoch += 1
            manifest = {
                "format": 2,
                "mode": "incremental",
                "snapshot_epoch": self._snapshot_epoch,
                "generation": max(gens.values(), default=0),
                "unix_time": time.time(),
                "tables": {key: {"rows": t.rows, "dim": t.dim}
                           for key, t in items},
                "chains": {key: dict(ent) for key, ent
                           in self._snap_chain.items()},
            }
            _atomic_write(os.path.join(self.snapshot_dir, "manifest.json"),
                          json.dumps(manifest, indent=1).encode())
            for fname in doomed:
                try:
                    os.remove(os.path.join(self.snapshot_dir, fname))
                except OSError:
                    pass
        return wrote

    def start_snapshotter(self) -> None:
        if not (self.snapshot_dir and self.snapshot_secs > 0):
            return
        if self._snap_thread is not None:
            return

        def loop():
            while not self.shutdown_event.wait(self.snapshot_secs):
                try:
                    self.snapshot()
                except Exception as e:  # keep serving; snapshots degrade
                    print(f"[ps_server] snapshot failed: {e}",
                          file=sys.stderr, flush=True)

        self._snap_thread = threading.Thread(target=loop, daemon=True)
        self._snap_thread.start()


def server_telemetry() -> dict:
    """This process's ps_server_* registry slice, JSON-ready — the
    payload of the `stats` verb. Histograms dump as summaries
    (count/sum/min/max/avg, plus the slowest-sample trace exemplar when
    tracing stamped one); the Prometheus exposition carries full
    buckets for scrapers."""
    snap = _REG.snapshot()
    return {k: v for k, v in snap.items() if k.startswith("ps_server_")}


def client_telemetry() -> dict:
    """The ps_client_* slice of THIS process's registry — per-verb
    latency histograms (exemplars included), retry/failover/hedge
    counters. RemoteTable.stats() attaches it so one stats() call shows
    both ends of the data plane."""
    snap = _REG.snapshot()
    return {k: v for k, v in snap.items() if k.startswith("ps_client_")}


def _server_span_attrs(method: str, kwargs: dict) -> dict:
    """Small, always-picklable span attributes for a server-side verb:
    enough identity for tracetop to group sync rounds and name culprits
    without ever copying a payload array."""
    attrs = {"verb": method}
    for k, out in (("name", "table"), ("key", "table"), ("tag", "tag"),
                   ("partition", "partition"), ("trainer_id", "trainer"),
                   ("epoch", "epoch")):
        v = kwargs.get(k)
        if v is not None:
            attrs[out] = v
    # one `round` key for whatever the verb calls its sequence number
    for k in ("step", "seq"):
        if kwargs.get(k) is not None:
            attrs["round"] = kwargs[k]
            break
    if kwargs.get("retry"):
        attrs["retry"] = True
    return attrs


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.server.track(self.request)  # type: ignore[attr-defined]
        srv: PSServer = self.server.ps  # type: ignore[attr-defined]
        while True:
            try:
                (method, kwargs), n_in = _recv_msg_sized(self.request)
            except (ConnectionError, EOFError):
                return
            # trace context (ISSUE 9): popped BEFORE dispatch so verbs
            # never see it; a traced client against an untraced server
            # costs this one dict op and nothing else
            trace_hdr = kwargs.pop("_trace", None) \
                if isinstance(kwargs, dict) else None
            # counted at ARRIVAL, not after the reply: an RPC whose
            # client vanished mid-round-trip was still handled and must
            # show in the books deterministically
            _REG.counter("ps_server_rpc_total", verb=method).inc()
            _REG.counter("ps_server_bytes_in_total", verb=method).inc(n_in)
            t0 = time.perf_counter()
            with _tracing.server_span(
                    f"server:{method}", trace_hdr,
                    attrs=(_server_span_attrs(method, kwargs)
                           if _tracing.enabled() else None)) as ssp:
                try:
                    result = srv.handle(method, kwargs)
                    reply = (True, result)
                except BaseException as e:  # noqa: BLE001 — ship to client
                    _REG.counter("ps_server_errors_total",
                                 verb=method).inc()
                    reply = (False, f"{type(e).__name__}: {e}")
                    if ssp is not None:
                        ssp.status = f"error:{type(e).__name__}"
            _REG.histogram("ps_server_rpc_ms",
                           help="server-side verb handling latency "
                                "(sync pushes include the barrier wait)",
                           verb=method).observe(
                (time.perf_counter() - t0) * 1e3,
                trace_id=(ssp.trace_id if ssp is not None else None))
            try:
                n_out = _send_msg(self.request, reply)
            except OSError:
                return  # peer gone; the retry path owns recovery
            _REG.counter("ps_server_bytes_out_total", verb=method).inc(n_out)
            if srv.shutdown_event.is_set():
                threading.Thread(
                    target=self.server.shutdown, daemon=True).start()
                return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._live_conns: set = set()
        self._conn_lock = threading.Lock()

    def track(self, request) -> None:
        with self._conn_lock:
            self._live_conns.add(request)

    def close_all_connections(self) -> None:
        """Hard-close every open client connection (parked handler
        threads wake with EOF). Used to simulate an abrupt pserver
        death for in-process failover tests, and by serve()'s teardown
        so a shut-down server can never keep answering on sockets that
        outlived the listener."""
        with self._conn_lock:
            conns, self._live_conns = list(self._live_conns), set()
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


def serve(port: int = 0, host: str = "0.0.0.0", ready_cb=None,
          preload_dir: Optional[str] = None,
          snapshot_dir: Optional[str] = None,
          snapshot_secs: Optional[float] = None,
          snapshot_mode: Optional[str] = None):
    """Run the pserver event loop (blocks). port=0 picks a free port;
    ready_cb (tests) receives the bound (host, port). Snapshot knobs
    default from PADDLE_PS_SNAPSHOT_DIR / PADDLE_PS_SNAPSHOT_SECS /
    PADDLE_PS_SNAPSHOT_MODE; a clean shutdown writes one final snapshot
    so a graceful restart is lossless (a crash loses at most one
    interval — one delta's worth of rows in incremental mode)."""
    if snapshot_dir is None:
        snapshot_dir = os.environ.get("PADDLE_PS_SNAPSHOT_DIR") or None
    if snapshot_secs is None:
        snapshot_secs = float(
            os.environ.get("PADDLE_PS_SNAPSHOT_SECS", 0) or 0)
    _arm_metrics_sink()
    # step tracing (ISSUE 9): arm the flight-recorder triggers (SIGTERM,
    # crash, exit) and the span push exporter; both are no-ops unless
    # PADDLE_TRACING / PADDLE_TRACES_PUSH_URL armed them
    _tracing.maybe_install_hooks()
    try:
        from ..telemetry import export as _export

        _export.maybe_start_traces()
    except Exception:  # noqa: BLE001 — telemetry must not stop serving
        pass
    srv = _TCPServer((host, port), _Handler)
    srv.ps = PSServer(preload_dir=preload_dir,  # type: ignore[attr-defined]
                      snapshot_dir=snapshot_dir,
                      snapshot_secs=snapshot_secs,
                      snapshot_mode=snapshot_mode)
    srv.ps.start_snapshotter()
    # stamp liveness for the launcher's supervisor when heartbeats are on
    # (same channel trainers use; catches a HUNG pserver, not just death)
    hb = None
    hb_dir = os.environ.get("PADDLE_HEARTBEAT_DIR")
    hb_tag = os.environ.get("PADDLE_PS_RANK_TAG")
    if hb_dir and hb_tag:
        from .heartbeat import HeartBeatWorker

        hb = HeartBeatWorker(hb_dir, hb_tag).start()
    # job control plane (coordinator.py): renew a membership lease
    # carrying the per-partition replica summary, so an expired primary
    # lease lets the coordinator promote a backup with no client in the
    # loop. No-op (two env reads) when the launcher didn't arm leases.
    lease_worker = None
    bound_host, bound_port = srv.server_address[0], srv.server_address[1]
    if bound_host in ("0.0.0.0", ""):
        bound_host = "127.0.0.1"
    try:
        from . import coordinator as _coord

        lease_worker = _coord.maybe_start_lease_worker(
            kind="pserver", tag=hb_tag,
            self_endpoint=f"{bound_host}:{bound_port}",
            payload_fn=lambda: {"partitions": srv.ps.replica_summary()})
    except Exception as e:  # noqa: BLE001 — leases are advisory here
        print(f"[ps_server] lease worker failed to start: {e}",
              file=sys.stderr, flush=True)
    if ready_cb is not None:
        ready_cb(srv.server_address)
    if srv.ps.adopted_manifest is not None:
        # printed AFTER the ready banner: the launcher reads the first
        # stdout line to learn the bound port
        m = srv.ps.adopted_manifest
        print(f"[ps_server] adopting snapshot dir {preload_dir!r} "
              f"(epoch {m.get('snapshot_epoch')}, generation "
              f"{m.get('generation')}, tables "
              f"{sorted(m.get('tables', {}))})", flush=True)
    try:
        srv.serve_forever(poll_interval=0.1)
    finally:
        if hb is not None:
            hb.stop()
        if lease_worker is not None:
            lease_worker.stop()
        srv.close_all_connections()
        srv.server_close()
        try:
            srv.ps.snapshot()
        except Exception as e:
            print(f"[ps_server] final snapshot failed: {e}",
                  file=sys.stderr, flush=True)
        # clean-exit span dump: flightrec.<tag>.json for tracetop plus
        # trace.<tag>.json so the launcher's timeline merge gets a
        # pserver lane (SIGTERM/crash paths dump via the hooks above)
        _tracing.shutdown_dump()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.ps_server")
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("PADDLE_PORT", 0)))
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--preload_dir", default=os.environ.get(
        "PADDLE_PS_PRELOAD_DIR", ""))
    p.add_argument("--snapshot_dir", default=os.environ.get(
        "PADDLE_PS_SNAPSHOT_DIR", ""))
    p.add_argument("--snapshot_secs", type=float, default=float(
        os.environ.get("PADDLE_PS_SNAPSHOT_SECS", 0) or 0))
    p.add_argument("--snapshot_mode", default=os.environ.get(
        "PADDLE_PS_SNAPSHOT_MODE", ""), choices=["", "full", "incremental"])
    args = p.parse_args(argv)

    def ready(addr):
        # the launcher reads this line to learn the bound port
        print(f"[ps_server] listening on {addr[0]}:{addr[1]}", flush=True)

    serve(args.port, args.host, ready_cb=ready,
          preload_dir=args.preload_dir or None,
          snapshot_dir=args.snapshot_dir or None,
          snapshot_secs=args.snapshot_secs,
          snapshot_mode=args.snapshot_mode or None)
    return 0


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class _Conn:
    """Pooled client connections to ONE endpoint. Pooling (not one shared
    socket) matters: a sync-mode push BLOCKS in the server barrier, and a
    second table's push or a gather from another runtime thread must not
    queue behind it — the cross-table ordering deadlock the reference
    avoids with per-request gRPC calls (grpc_client.h AsyncSendVar).

    call() retries transport faults (ConnectionError / EOF / timeout /
    refused connect) with exponential backoff + jitter and a fresh
    socket per attempt, so a pserver restart is invisible to the caller.
    Replay-sensitive verbs (push_gradients, push_delta) are marked
    `retry=True` from the second attempt on; the server's dedup keys
    make the replay apply-once. Application errors the server REPLIED
    with are never retried — the RPC itself succeeded."""

    # verbs whose replay the server dedups: (trainer_id, step|seq) on
    # the PS plane, request_id on the serving plane's generate
    _MARK_RETRY = ("push_gradients", "push_delta", "generate")

    def __init__(self, endpoint: str, deadline: Optional[float] = None,
                 max_attempts: Optional[int] = None,
                 io_timeout: Optional[float] = None):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self.addr = (host, int(port))
        # deadline > 0: the retry LOOP is bounded by wall time (failover
        # in bounded time); 0/None: attempt-count bound, exactly the
        # pre-deadline behavior (PADDLE_PS_CALL_DEADLINE_SECS).
        # max_attempts additionally caps attempts UNDER a deadline —
        # replication forwards use it so a dead backup (instant refused
        # connects) is dropped immediately instead of riding out the
        # whole deadline meant for hung peers.
        # io_timeout is the SOCKET timeout: it defaults to the sync-
        # barrier envelope because a sync push legitimately BLOCKS in
        # the server barrier — a short recv timeout there would read a
        # slow peer trainer as a dead pserver and promote over live
        # data. Only quick admin verbs (probes, forwards, resync) pass
        # a short one.
        self.deadline = float(RPC_DEADLINE if deadline is None else deadline)
        self.max_attempts = max_attempts
        self.io_timeout = float(SYNC_TIMEOUT + 30 if io_timeout is None
                                else io_timeout)
        self._free: List[socket.socket] = []
        self._lock = threading.Lock()

    def _checkout(self) -> socket.socket:
        with self._lock:
            if self._free:
                return self._free.pop()
        s = socket.create_connection(self.addr, timeout=self.io_timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def call(self, method: str, **kwargs):
        # causal tracing (ISSUE 9): one client span for the whole RPC,
        # a child span per attempt (its id rides the payload as the
        # `_trace` traceparent so the server's handling parents under
        # THAT attempt) and per backoff sleep. Tracing off: rpc_span is
        # None, every guard below is one is-None check, and kwargs gains
        # no key — the wire bytes are bit-identical.
        rpc_span = _tracing.begin(
            f"rpc:{method}", kind="client",
            attrs={"peer": self.endpoint, "verb": method})
        try:
            return self._call_traced(rpc_span, method, kwargs)
        except BaseException as e:
            if rpc_span is not None:
                rpc_span.status = f"error:{type(e).__name__}"
            raise
        finally:
            _tracing.finish(rpc_span)

    def _call_traced(self, rpc_span, method: str, kwargs: dict):
        inj = faults.injector()
        last_err: Optional[BaseException] = None
        t_rpc = time.perf_counter()
        deadline_t = t_rpc + self.deadline if self.deadline > 0 else None
        sent_bytes = rcvd_bytes = 0
        attempt = 0
        while True:
            if attempt:
                if method in self._MARK_RETRY:
                    kwargs["retry"] = True
                back = min(RPC_BACKOFF_CAP,
                           RPC_BACKOFF_BASE * (2 ** (attempt - 1)))
                back *= 0.5 + random.random()  # jittered
                if deadline_t is not None:
                    # never sleep past the deadline; give up at it
                    remaining = deadline_t - time.perf_counter()
                    if remaining <= 0:
                        break
                    back = min(back, remaining)
                bo_span = _tracing.begin("backoff", parent=rpc_span,
                                         attrs={"after_attempt": attempt})
                time.sleep(back)
                _tracing.finish(bo_span)
            s = None
            att_span = _tracing.begin(f"attempt:{method}", kind="client",
                                      parent=rpc_span,
                                      attrs={"n": attempt + 1})
            if att_span is not None:
                kwargs["_trace"] = _tracing.header_for(att_span)
            try:
                s = self._checkout()
                if inj is not None:
                    inj.before_send(method)  # refuse/delay/stall rules
                sent_bytes += _send_msg(s, (method, kwargs))
                if inj is not None and inj.drop_after_send(method):
                    raise faults.FaultError(
                        f"fault injection: dropped connection after "
                        f"sending {method!r}")
                (ok, result), n_in = _recv_msg_sized(s)
                rcvd_bytes += n_in
            except (OSError, EOFError) as e:
                # includes ConnectionError, socket.timeout, refused
                # connects while a supervised pserver restarts
                _tracing.finish(att_span,
                                status=f"transport:{type(e).__name__}")
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                last_err = e
                attempt += 1
                if self.max_attempts is not None \
                        and attempt >= self.max_attempts:
                    break
                if deadline_t is not None:
                    if time.perf_counter() >= deadline_t:
                        break
                    continue  # time remains: the deadline is the bound
                if attempt > RPC_MAX_RETRIES:
                    break
                continue
            except BaseException:
                _tracing.finish(att_span, status="error")
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                raise
            _tracing.finish(att_span,
                            status=None if ok else "app_error")
            with self._lock:
                self._free.append(s)
            # per-verb client telemetry: wall latency INCLUDING backoff
            # (what the training step actually waited), retries, bytes;
            # the trace_id rides as the histogram's slowest-sample
            # exemplar, so a latency scrape names a trace to pull
            _REG.histogram("ps_client_rpc_ms",
                           help="client RPC wall latency incl. retries",
                           verb=method).observe(
                (time.perf_counter() - t_rpc) * 1e3,
                trace_id=(rpc_span.trace_id if rpc_span is not None
                          else None))
            _REG.counter("ps_client_rpc_total", verb=method).inc()
            if attempt:
                _REG.counter("ps_client_retries_total",
                             help="retried RPC attempts",
                             verb=method).inc(attempt)
            _REG.counter("ps_client_bytes_sent_total",
                         verb=method).inc(sent_bytes)
            _REG.counter("ps_client_bytes_received_total",
                         verb=method).inc(rcvd_bytes)
            if not ok:
                _REG.counter("ps_client_app_errors_total",
                             verb=method).inc()
                if isinstance(result, str) and result.startswith(
                        "KeyError") and "no table" in result:
                    raise TableMissingError(f"pserver {self.addr}: {result}")
                if isinstance(result, str) and result.startswith(
                        "NotPrimaryError"):
                    raise NotPrimaryError(
                        f"pserver {self.addr}: {result}")
                if isinstance(result, str) and result.startswith(
                        "StalePrimaryError"):
                    raise StalePrimaryError(
                        f"pserver {self.addr}: {result}")
                raise RuntimeError(f"pserver {self.addr}: {result}")
            return result
        _REG.counter("ps_client_rpc_failed_total", verb=method).inc()
        if deadline_t is not None:
            raise ConnectionError(
                f"pserver {self.addr}: RPC {method!r} exceeded its "
                f"{self.deadline}s deadline after {attempt} attempts: "
                f"{last_err}") from last_err
        raise ConnectionError(
            f"pserver {self.addr}: RPC {method!r} still failing after "
            f"{attempt} attempts: {last_err}") from last_err

    def close(self):
        with self._lock:
            for s in self._free:
                try:
                    s.close()
                except OSError:
                    pass
            self._free.clear()


class RemoteTable:
    """Client shim: the ShardedHostTable duck type over N pservers.

    Rows are round-robin sharded across servers (global row r lives on
    server r % n at local row r // n — the reference ps_dispatcher
    RoundRobin placement), so with one server the hosted table is
    byte-identical (same seed, same shape) to the in-process one.

    generation (default PADDLE_ELASTIC_RESTART): the trainer group's
    restart attempt, carried in the create_table handshake so a server
    that outlived the previous group resets its sync barrier. Every verb
    goes through _call, which re-creates the table (idempotent; the
    server preloads its latest snapshot) if a restarted pserver lost it.

    replication R (PADDLE_PS_REPLICATION, default 1): partition p's rows
    get a PRIMARY on pserver p plus R-1 prefix-consistent BACKUPS on
    pservers (p+1)%n .. (p+R-1)%n (the chain). The client then adds:

      fast failover — when the primary's deadline-capped retry budget is
        exhausted, the next live replica in the chain is PROMOTED
        (epoch+1) and training continues; a daemon thread re-enrolls the
        dead endpoint once the supervisor respawns it (create_table →
        resync: snapshot + seq-tail anti-entropy) so the partition heals
        back to R replicas without a pause.
      hedged pulls — read verbs (gather, stats) race a backup-directed
        hedge issued after the verb's observed latency quantile
        (PADDLE_PS_HEDGE_QUANTILE, default p95); first response wins,
        the loser is discarded (hedges issued/won counters in the
        registry).

    R=1 sends byte-identical wire messages to the pre-replication
    protocol: no partition field, no promote/replicate verbs.
    """

    def __init__(self, name, shape, endpoints: List[str],
                 dtype: str = "float32", num_shards: int = 4,
                 optimizer: str = "sgd", learning_rate: float = 0.1,
                 initializer_std: Optional[float] = None, seed: int = 0,
                 sync_trainers: int = 0, trainer_id: int = 0,
                 generation: Optional[int] = None,
                 replication: Optional[int] = None):
        self.name = name
        self.rows, self.dim = int(shape[0]), int(shape[1])
        self.dtype = np.dtype(dtype)
        self.learning_rate = float(learning_rate)
        self.optimizer = optimizer
        self.endpoints = list(endpoints)
        self.trainer_id = int(trainer_id)
        self.generation = int(
            os.environ.get("PADDLE_ELASTIC_RESTART", 0)
            if generation is None else generation)
        self._n = len(self.endpoints)
        if replication is None:
            replication = int(
                os.environ.get("PADDLE_PS_REPLICATION", 1) or 1)
        self.replication = max(1, int(replication))
        if self.replication > 1 and self.replication > self._n:
            raise ValueError(
                f"replication={self.replication} needs at least that "
                f"many distinct pservers, got {self._n} "
                f"(PADDLE_PS_REPLICATION vs PADDLE_PSERVERS_IP_PORT_LIST)")
        # replicated clients default to a bounded per-RPC deadline so
        # failover triggers in bounded time; R=1 keeps the attempt bound
        conn_deadline = None
        if self.replication > 1 and RPC_DEADLINE <= 0:
            conn_deadline = REPLICATED_DEADLINE_DEFAULT
        self._conns = [_Conn(e, deadline=conn_deadline)
                       for e in self.endpoints]
        self._step = 0
        self._delta_seq = 0
        self._step_lock = threading.Lock()
        # multi-server fan-out pool: per-server RPCs overlap instead of
        # serializing N round-trips (the reference's async gRPC client
        # model, grpc_client.h AsyncSendVar); connections are pooled per
        # endpoint so concurrent calls never share a socket
        self._pool = None
        if self._n > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(max_workers=self._n)
        self._specs: List[dict] = []
        for s in range(self._n):
            n_rows = (self.rows - s + self._n - 1) // self._n
            spec = {
                "name": name, "shape": (n_rows, self.dim),
                "dtype": str(self.dtype), "num_shards": num_shards,
                "optimizer": optimizer, "learning_rate": learning_rate,
                "initializer_std": initializer_std,
                # distinct per-server streams when sharded; the single-
                # server layout reproduces the local table bit-for-bit
                "seed": seed if self._n == 1 else seed + s,
                "sync_trainers": sync_trainers,
                "generation": self.generation,
            }
            if self.replication > 1:
                # the spec is PARTITION identity — identical on every
                # replica of partition s (seed included), so primary and
                # backups initialize bit-identically
                spec["partition"] = s
                spec["replicas"] = [
                    self.endpoints[(s + i) % self._n]
                    for i in range(self.replication)]
            self._specs.append(spec)
        if self.replication <= 1:
            for s, conn in enumerate(self._conns):
                conn.call("create_table", spec=self._specs[s])
        else:
            self._init_replicated()

    # -- replication bookkeeping -----------------------------------------
    def _init_replicated(self):
        from concurrent.futures import ThreadPoolExecutor

        R = self.replication
        # chain[p] = server indices hosting partition p, primary first
        self._chain = [[(p + i) % self._n for i in range(R)]
                       for p in range(self._n)]
        self._primary_idx = [0] * self._n  # index INTO the chain
        self._pepoch = [0] * self._n
        # RLock: _refresh_primary holds it while _refresh_primary_locked
        # schedules rejoins, which re-enter it to dedupe
        self._route_lock = threading.RLock()
        self._rejoining: set = set()
        self._hedge_q = HEDGE_QUANTILE
        self._hedge_min = HEDGE_MIN_SAMPLES
        self._hedge_pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * self._n))
        for p in range(self._n):
            for j in self._chain[p]:
                self._conns[j].call("create_table", spec=self._specs[p])
            try:
                self._conns[self._chain[p][0]].call(
                    "promote", name=self.name, partition=p, epoch=0,
                    backups=[self.endpoints[j]
                             for j in self._chain[p][1:]])
                for j in self._chain[p][1:]:
                    self._conns[j].call("adopt_role", name=self.name,
                                        partition=p, epoch=0,
                                        role="backup")
            except RuntimeError as e:
                if "StalePromote" not in str(e):
                    raise
                # a failover already moved this partition on; adopt it
                self._refresh_primary(p)

    # -- addressing ------------------------------------------------------
    def _locate(self, ids: np.ndarray):
        if ids.size and (ids.min() < 0 or ids.max() >= self.rows):
            bad = ids[(ids < 0) | (ids >= self.rows)][0]
            raise IndexError(
                f"table {self.name!r}: id {int(bad)} out of range "
                f"[0, {self.rows})")
        return ids % self._n, ids // self._n

    def _call(self, s: int, method: str, **kwargs):
        """One partition's RPC with restart recovery: a pserver that
        came back empty (supervised respawn) gets the idempotent create
        — which preloads its latest snapshot — and the verb is replayed.
        Replicated tables additionally route to the partition's current
        primary, fail over on exhausted retry budgets, and hedge read
        verbs to a backup."""
        if self.replication <= 1:
            try:
                return self._conns[s].call(method, **kwargs)
            except TableMissingError:
                self._conns[s].call("create_table", spec=self._specs[s])
                return self._conns[s].call(method, **kwargs)
        kwargs.setdefault("partition", s)
        if method in ("gather", "stats") and self._hedge_q > 0:
            return self._hedged_call(s, method, kwargs)
        return self._replica_call(s, method, kwargs)

    def _conn_call(self, j: int, p: int, method: str, kwargs: dict):
        """Raw call to server j for partition p, with the idempotent
        recreate-on-missing recovery (replicated flavor)."""
        try:
            return self._conns[j].call(method, **kwargs)
        except TableMissingError:
            self._conns[j].call("create_table", spec=self._specs[p])
            return self._conns[j].call(method, **kwargs)

    def _replica_call(self, p: int, method: str, kwargs: dict,
                      hops: int = 0):
        """Primary-routed call with fast failover: an unreachable
        primary (deadline-capped retries exhausted) promotes the next
        live replica and the verb replays there — marked `retry` for
        writes, so a round that already merged-and-forwarded before the
        primary died applies exactly once."""
        j = self._chain[p][self._primary_idx[p]]
        try:
            return self._conn_call(j, p, method, kwargs)
        except ConnectionError:
            if hops >= self.replication:
                raise
            self._failover(p, dead_j=j)
        except (NotPrimaryError, StalePrimaryError):
            # our routing is behind the cluster: adopt the real primary
            if hops >= self.replication + 2:
                raise
            self._refresh_primary(p)
        if method in ("push_gradients", "push_delta"):
            kwargs["retry"] = True  # first try may have landed
        return self._replica_call(p, method, kwargs, hops + 1)

    def _probe(self, j: int, p: int):
        """replica_status of server j for partition p, or None if it is
        unreachable/unusable right now."""
        try:
            st = self._conn_call(j, p, "replica_status",
                                 {"name": self.name, "partition": p})
            return None if st.get("stale") else st
        except Exception:  # noqa: BLE001 — candidate scan must not die
            return None

    @staticmethod
    def _promote_rank(st: dict, idx: int):
        """Candidate ordering for promotion: replicas that HOLD DATA
        (role backup/primary — they applied the forward prefix) always
        outrank a role-None husk (a just-respawned empty server that
        has not resynced), regardless of its epoch; then epoch, then
        last-applied seq, then chain order. Promoting an empty replica
        while a caught-up one exists would be silent data loss."""
        has_data = 1 if st.get("role") in ("backup", "primary") else 0
        return (has_data, int(st.get("epoch", 0)), int(st.get("seq", 0)),
                -idx)

    def _failover(self, p: int, dead_j: int) -> None:
        """Promote the best live replica of partition p (highest
        (epoch, seq), chain order breaking ties) and keep training;
        a rejoin thread re-enrolls the dead endpoint once its
        supervised respawn answers again."""
        with self._route_lock:
            chain = self._chain[p]
            if chain[self._primary_idx[p]] != dead_j:
                return  # another thread already failed this partition over
            best = None
            for idx, j in enumerate(chain):
                if j == dead_j:
                    continue
                st = self._probe(j, p)
                if st is None:
                    continue
                rank = self._promote_rank(st, idx)
                if best is None or rank > best[0]:
                    best = (rank, idx, st)
            if best is None:
                raise ConnectionError(
                    f"table {self.name!r} partition {p}: primary "
                    f"{self.endpoints[dead_j]} is unreachable and no "
                    f"live replica remains")
            rank, idx, st = best
            if (st.get("role") == "primary"
                    and int(st.get("epoch", 0)) > self._pepoch[p]):
                # a control plane (coordinator lease elector) or a peer
                # trainer already promoted this replica at a newer
                # epoch — adopt the claim instead of deposing it with a
                # redundant epoch bump; adoption is not a client-driven
                # failover, so it gets its own counter
                _REG.counter("ps_client_primary_adoptions_total").inc()
                self._pepoch[p] = int(st.get("epoch", 0))
                self._primary_idx[p] = idx
                print(f"[ps_client] pserver {self.endpoints[dead_j]} "
                      f"unreachable for table {self.name!r} partition "
                      f"{p}; adopting already-promoted primary "
                      f"{self.endpoints[chain[idx]]} (epoch "
                      f"{self._pepoch[p]})", file=sys.stderr, flush=True)
                for p2 in range(self._n):
                    if dead_j in self._chain[p2]:
                        self._schedule_rejoin(p2, dead_j)
                return
            _REG.counter("ps_client_failovers_total").inc()
            new_epoch = max(self._pepoch[p], rank[1]) + 1
            backups = [self.endpoints[j] for j in chain
                       if j not in (dead_j, chain[idx])]
            target = chain[idx]
            print(f"[ps_client] pserver {self.endpoints[dead_j]} "
                  f"unreachable for table {self.name!r} partition {p}; "
                  f"promoting {self.endpoints[target]} (epoch "
                  f"{new_epoch})", file=sys.stderr, flush=True)
            try:
                self._conn_call(target, p, "promote",
                                {"name": self.name, "partition": p,
                                 "epoch": new_epoch, "backups": backups})
                self._pepoch[p] = new_epoch
                self._primary_idx[p] = idx
            except (NotPrimaryError, StalePrimaryError, RuntimeError):
                # lost the promote race to a peer trainer: adopt theirs
                self._refresh_primary_locked(p)
        # the dead server also held BACKUP copies of its neighbours'
        # partitions (their primaries dropped it on forward failure) —
        # re-enroll it everywhere it belongs once it respawns
        for p2 in range(self._n):
            if dead_j in self._chain[p2]:
                self._schedule_rejoin(p2, dead_j)

    def _refresh_primary(self, p: int) -> None:
        with self._route_lock:
            self._refresh_primary_locked(p)

    def _refresh_primary_locked(self, p: int) -> None:
        """Re-resolve partition p's primary from the replicas' own
        claims: highest-epoch primary claimant wins; with none — e.g.
        the old primary was respawned EMPTY before we noticed it died —
        promote the best-(epoch, seq) live replica (deterministic across
        trainers). Replicas that probe dead or behind (a just-respawned
        empty one) are left out of the forward set and scheduled for the
        rejoin/resync path instead — never silently abandoned at R=1."""
        chain = self._chain[p]
        probes = {}
        claimant = best = None
        for idx, j in enumerate(chain):
            st = self._probe(j, p)
            probes[j] = st
            if st is None:
                continue
            rank = self._promote_rank(st, idx)
            if st.get("role") == "primary" and (
                    claimant is None or rank > claimant[0]):
                claimant = (rank, idx)
            if best is None or rank > best[0]:
                best = (rank, idx)
        if claimant is not None:
            self._pepoch[p] = claimant[0][1]
            self._primary_idx[p] = claimant[1]
            return
        if best is None:
            raise ConnectionError(
                f"table {self.name!r} partition {p}: no live replica")
        new_epoch = max(self._pepoch[p], best[0][1]) + 1
        target = chain[best[1]]
        healthy = [j for j in chain
                   if j != target and probes.get(j) is not None
                   and probes[j].get("role") == "backup"]
        # a no-claimant promote IS a failover: the old primary vanished
        # (or came back empty) without us ever seeing a transport error
        _REG.counter("ps_client_failovers_total").inc()
        print(f"[ps_client] no primary claims table {self.name!r} "
              f"partition {p}; promoting {self.endpoints[target]} "
              f"(epoch {new_epoch})", file=sys.stderr, flush=True)
        self._conn_call(target, p, "promote",
                        {"name": self.name, "partition": p,
                         "epoch": new_epoch,
                         "backups": [self.endpoints[j] for j in healthy]})
        self._pepoch[p] = new_epoch
        self._primary_idx[p] = best[1]
        for j in chain:
            if j != target and j not in healthy:
                self._schedule_rejoin(p, j)

    def _schedule_rejoin(self, p: int, dead_j: int) -> None:
        """Daemon thread: once the dead endpoint answers again
        (supervised respawn), re-create the partition table there
        (preloads its snapshot) and drive `resync` — anti-entropy from
        the current primary (seq-tail when covered, else full state) —
        so the partition heals back to R replicas."""
        key = (p, dead_j)
        with self._route_lock:
            if key in self._rejoining:
                return
            self._rejoining.add(key)

        def loop():
            ep = self.endpoints[dead_j]
            deadline = time.monotonic() + REJOIN_SECS
            try:
                while time.monotonic() < deadline:
                    time.sleep(0.5)
                    c = _Conn(ep, deadline=3.0, io_timeout=15.0)
                    try:
                        c.call("ping")
                        c.call("create_table", spec=self._specs[p])
                        prim = self.endpoints[
                            self._chain[p][self._primary_idx[p]]]
                        if prim == ep:
                            return  # it came back as primary already
                        st = c.call("replica_status", name=self.name,
                                    partition=p)
                        if (st.get("role") == "backup"
                                and not st.get("stale")):
                            return  # a peer trainer already resynced it
                        out = c.call("resync", name=self.name,
                                     partition=p, primary=prim,
                                     self_endpoint=ep)
                        _REG.counter("ps_client_rejoins_total").inc()
                        print(f"[ps_client] pserver {ep} rejoined table "
                              f"{self.name!r} partition {p} as backup "
                              f"({out.get('mode')}, seq "
                              f"{out.get('seq')})", file=sys.stderr,
                              flush=True)
                        return
                    except Exception:  # noqa: BLE001 — retry until alive
                        continue
                    finally:
                        c.close()
                print(f"[ps_client] giving up re-enrolling {ep} for "
                      f"table {self.name!r} partition {p} after "
                      f"{REJOIN_SECS}s", file=sys.stderr, flush=True)
            finally:
                with self._route_lock:
                    self._rejoining.discard(key)

        threading.Thread(target=loop, daemon=True,
                         name=f"ps-rejoin-{self.name}-p{p}").start()

    def _hedged_call(self, p: int, method: str, kwargs: dict):
        """Tail-tolerant read: race the primary against a backup hedge
        issued after the verb's observed latency quantile. First
        response wins; the loser finishes in the background and is
        discarded. Falls back to the plain primary path until the
        latency histogram has enough samples to size the delay.

        ps_client_effective_read_ms records what the CALLER waited —
        ps_client_rpc_ms keeps recording each connection's raw RPC
        latency (the losing primary still logs its full tail there), so
        the two histograms together show exactly what hedging bought."""
        t_eff = time.perf_counter()
        try:
            return self._hedged_call_inner(p, method, kwargs)
        finally:
            _REG.histogram(
                "ps_client_effective_read_ms",
                help="read latency as the caller saw it (hedging "
                     "included; compare with ps_client_rpc_ms)",
                verb=method).observe((time.perf_counter() - t_eff) * 1e3)

    def _hedged_call_inner(self, p: int, method: str, kwargs: dict):
        from concurrent import futures as _fut

        hist = _REG.histogram("ps_client_rpc_ms", verb=method)
        chain = self._chain[p]
        if hist.count < self._hedge_min or len(chain) < 2:
            return self._replica_call(p, method, kwargs)
        delay_s = max(hist.quantile(self._hedge_q) / 1e3, 1e-3)
        # _tracing.bound: the pool thread re-binds THIS thread's span
        # context, so the primary attempt, the hedge, and the winner all
        # share one trace (identity function when tracing is off)
        fut = self._hedge_pool.submit(_tracing.bound(
            lambda: self._replica_call(p, method, dict(kwargs))))
        try:
            return fut.result(timeout=delay_s)
        except _fut.TimeoutError:
            pass
        _REG.counter("ps_client_hedges_issued_total",
                     help="backup-directed hedges for slow reads",
                     verb=method).inc()
        backup_j = chain[(self._primary_idx[p] + 1) % len(chain)]

        def _hedge_exec():
            with _tracing.span(f"hedge:{method}",
                               attrs={"partition": p,
                                      "peer": self.endpoints[backup_j]}):
                return self._conn_call(backup_j, p, method, dict(kwargs))

        hedge = self._hedge_pool.submit(_tracing.bound(_hedge_exec))
        pending = {fut: "primary", hedge: "hedge"}
        last_err = None
        while pending:
            done, _ = _fut.wait(set(pending),
                                return_when=_fut.FIRST_COMPLETED)
            for f in done:
                src = pending.pop(f)
                err = f.exception()
                if err is None:
                    if src == "hedge":
                        _REG.counter("ps_client_hedges_won_total",
                                     verb=method).inc()
                    return f.result()
                last_err = err
        raise last_err

    def _fanout(self, thunks):
        """Run one thunk per server, overlapped when a pool exists.
        Thunks carry the caller's trace context into the pool threads
        (tracing.bound is identity when the layer is off)."""
        if self._pool is None:
            return [t() for t in thunks]
        return [f.result() for f in
                [self._pool.submit(_tracing.bound(t)) for t in thunks]]

    # -- serving ---------------------------------------------------------
    def gather(self, ids) -> np.ndarray:
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        srv, local = self._locate(ids)
        out = np.empty((ids.shape[0], self.dim), self.dtype)
        masks = [srv == s for s in range(self._n)]
        rows = self._fanout([
            (lambda s=s, m=m: self._call(
                s, "gather", name=self.name, ids=local[m]))
            if m.any() else (lambda: None)
            for s, m in enumerate(masks)
        ])
        for m, r in zip(masks, rows):
            if r is not None:
                out[m] = r
        return out

    def push_gradients(self, ids, grads) -> None:
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        grads = np.asarray(grads, np.float32).reshape(ids.shape[0], self.dim)
        # SDC drill site (telemetry/numerics.py): a bitflip:push_grad
        # rule corrupts one value of THIS rank's outgoing gradient —
        # flag-off the array passes through untouched (one flag read)
        from .faults import bitflip_point

        grads = bitflip_point("push_grad", grads)
        with self._step_lock:
            step = self._step
            self._step += 1
        srv, local = self._locate(ids)
        # every server participates in every sync round (even with zero
        # rows) so its barrier bookkeeping sees all trainers each step;
        # overlapped: in sync mode each call blocks on the barrier
        self._fanout([
            lambda s=s: self._call(
                s, "push_gradients", name=self.name, ids=local[srv == s],
                grads=grads[srv == s], trainer_id=self.trainer_id,
                step=step)
            for s in range(self._n)
        ])

    def push_delta(self, ids, deltas) -> None:
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        deltas = np.asarray(deltas, np.float32).reshape(
            ids.shape[0], self.dim)
        with self._step_lock:
            seq = self._delta_seq
            self._delta_seq += 1
        srv, local = self._locate(ids)
        masks = [srv == s for s in range(self._n)]
        self._fanout([
            (lambda s=s, m=m: self._call(
                s, "push_delta", name=self.name, ids=local[m],
                deltas=deltas[m], trainer_id=self.trainer_id, seq=seq))
            if m.any() else (lambda: None)
            for s, m in enumerate(masks)
        ])

    # -- introspection / checkpoint --------------------------------------
    def nbytes(self) -> int:
        return sum(self._call(s, "nbytes", name=self.name)
                   for s in range(self._n))

    def stats(self) -> dict:
        """Aggregated table traffic counters + each pserver's telemetry
        slice under "servers" (the idempotent `stats` verb). Replicated
        tables add a "replication" section: factor plus each partition's
        replica roles/epochs/seqs — the operator's view of failovers,
        lag, and dropped backups. "client" is THIS process's ps_client_*
        slice (verb latency histograms with trace-exemplars, retry and
        hedge counters) so one call shows both ends of the data plane."""
        agg = {"push_calls": 0, "pushed_bytes": 0, "servers": [],
               "client": client_telemetry()}
        parts: dict = {}
        for s in range(self._n):
            st = self._call(s, "stats", name=self.name)
            agg["push_calls"] += st["push_calls"]
            agg["pushed_bytes"] += st["pushed_bytes"]
            agg["servers"].append(st.get("server", {}))
            # per-partition resident bytes (ISSUE 11): this table's key
            # slice of the answering server's memory accounting (a
            # pserver may host other tables — only ours counts), one
            # row per partition KEY (replica copies are partition-
            # identical by construction, so dedup by key is exact for
            # the value shards and an estimate for the replog ring)
            for key, row in (st.get("memory") or {}).items():
                if key == self.name or str(key).startswith(
                        self.name + "@p"):
                    parts[key] = row
        resident = sum(int(r.get("resident_bytes", 0))
                       for r in parts.values())
        agg["memory"] = {
            "partitions": parts,
            "resident_bytes": resident,
            # cluster-wide estimate: every partition keeps R copies
            "replicated_resident_bytes": resident
            * max(1, self.replication),
        }
        if self.replication > 1:
            agg["replication"] = {"factor": self.replication,
                                  "partitions": self.replica_status()}
        return agg

    def memory_stats(self) -> dict:
        """Aggregated resident-byte accounting for this table across
        its pservers (the `stats` verb's memory section filtered to
        this table's partitions) — the debugz /statusz ps_memory row."""
        return self.stats()["memory"]

    def replica_status(self) -> List[dict]:
        """Per-partition replica states (role, epoch, last-applied seq,
        dropped backups) straight from each chain member; unreplicated
        tables report []. Replica lag is visible as seq deltas between
        a partition's primary and its backups."""
        if self.replication <= 1:
            return []
        out = []
        for p in range(self._n):
            primary_j = self._chain[p][self._primary_idx[p]]
            row = {"partition": p,
                   "primary": self.endpoints[primary_j],
                   "epoch": self._pepoch[p], "replicas": []}
            seqs = []
            for j in self._chain[p]:
                try:
                    st = self._conns[j].call(
                        "replica_status", name=self.name, partition=p)
                except Exception as e:  # noqa: BLE001 — dead replica
                    st = {"error": type(e).__name__}
                if "seq" in st:
                    seqs.append(int(st["seq"]))
                row["replicas"].append(
                    {"endpoint": self.endpoints[j], **st})
            if seqs:
                row["max_lag"] = max(seqs) - min(seqs)
            out.append(row)
        return out

    def server_stats(self) -> List[dict]:
        """Per-pserver telemetry snapshots (no table counters) — verb
        latencies, retry/replay-dedup counters, bytes in/out."""
        return [self._conns[s].call("stats").get("server", {})
                for s in range(self._n)]

    def to_dense(self) -> np.ndarray:
        out = np.empty((self.rows, self.dim), self.dtype)
        for s in range(self._n):
            out[s::self._n] = self._call(s, "to_dense", name=self.name)
        return out

    def state_dict(self):
        return {"servers": [self._call(s, "state_dict", name=self.name)
                            for s in range(self._n)]}

    def load_state_dict(self, state):
        if "servers" in state:
            for s, st in enumerate(state["servers"]):
                self._call(s, "load_state_dict", name=self.name, state=st)
        else:  # a local-table checkpoint restored into a hosted run
            if self._n != 1:
                raise ValueError(
                    "single-table checkpoint needs exactly 1 pserver")
            self._call(0, "load_state_dict", name=self.name, state=state)

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        if getattr(self, "_hedge_pool", None) is not None:
            self._hedge_pool.shutdown(wait=False)
        for c in self._conns:
            c.close()


# ---------------------------------------------------------------------------
# env contract
# ---------------------------------------------------------------------------


def pserver_endpoints() -> List[str]:
    """PADDLE_PSERVERS_IP_PORT_LIST (reference role_maker.py:497)."""
    raw = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
    return [e.strip() for e in raw.split(",") if e.strip()]


def training_role() -> str:
    return os.environ.get("PADDLE_TRAINING_ROLE", "TRAINER").upper()


if __name__ == "__main__":
    sys.exit(main())
